"""Unified metrics registry — one named-counter namespace for every engine.

The reference keeps its counter taxonomy in one place (the Tracker's
interval columns, src/main/host/tracker.c) and every consumer — the
heartbeat log, the tools scripts — reads that one schema. Our rebuild had
grown three ad-hoc dict shapes instead: the TPU engines' ``Metrics``
NamedTuple, the CPU engine's plain dict (a key subset), and whatever
``tools/heartbeat_report.py`` guessed from the JSONL. This module is the
single source of truth the three now share:

* ``METRIC_SPECS`` — the canonical counter namespace: name → (kind, help).
  ``tests/test_telemetry.py`` asserts it stays in sync with the engine's
  ``Metrics._fields`` so the namespaces cannot drift.
* ``normalize(d)`` — project any engine's metrics dict onto the canonical
  namespace (missing counters → 0, unknown extras preserved), so the
  heartbeat and the report never KeyError on an engine that lacks a field.
* ``to_prometheus(d)`` — Prometheus text exposition (counters get the
  ``_total`` suffix, gauges don't), servable via ``ExpositionServer``.
* the JSONL record-type constants (``REC_*``) and the ring column schema
  (``RING_FIELDS``) every stream producer/consumer shares
  (see docs/OBSERVABILITY.md for the concrete record shapes).

Deliberately jax-free: tools and report scripts import it without paying
an accelerator-runtime import.
"""

from __future__ import annotations

import threading

COUNTER = "counter"
GAUGE = "gauge"

# name → (kind, help). Order is the canonical export order.
METRIC_SPECS: dict[str, tuple[str, str]] = {
    "events": (COUNTER, "events executed"),
    "rounds": (COUNTER, "inner scheduler rounds run (batch engines)"),
    "windows": (COUNTER, "conservative windows completed"),
    "pkts_sent": (COUNTER, "packets routed out of host outboxes"),
    "pkts_delivered": (COUNTER, "packets scattered into destination event buffers"),
    "pkts_lost": (COUNTER, "packets dropped by path loss draws"),
    "ev_overflow": (COUNTER, "events dropped: full event buffer"),
    "ob_overflow": (COUNTER, "packets dropped: full outbox"),
    "round_cap_hits": (COUNTER, "windows that hit the max_rounds safety cap"),
    "tcp_fast_rtx": (COUNTER, "TCP fast-retransmit (3 dup-ACK) episodes"),
    "tcp_rto": (COUNTER, "TCP retransmit-timeout episodes"),
    "tcp_ooo_drops": (COUNTER, "out-of-order segments dropped (GBN receiver)"),
    "x2x_overflow": (COUNTER, "packets dropped: all_to_all bucket full (sharded)"),
    "x2x_max_fill": (GAUGE, "high-water demanded all_to_all bucket fill"),
    "ev_max_fill": (GAUGE, "high-water window-end event-slot fill (vs ev_cap)"),
    "ob_max_fill": (GAUGE, "high-water per-window outbox fill (vs outbox_cap)"),
    "compact_max_fill": (GAUGE, "high-water window active-host count: demanded "
                                "compaction-bucket lanes (vs compact_cap; "
                                "per-shard block count under sharding)"),
    "down_events": (COUNTER, "events discarded: host stopped (churn)"),
    "down_pkts": (COUNTER, "packets dropped: destination host stopped"),
    "nic_tx_drops": (COUNTER, "packets dropped: NIC uplink queue full"),
    "nic_rx_drops": (COUNTER, "packets dropped: NIC downlink queue full"),
    "nic_aqm_drops": (COUNTER, "packets dropped: RED early-drop (uplink)"),
    "pops_pkt": (COUNTER, "K_PKT events popped"),
    "pops_deliver": (COUNTER, "K_PKT_DELIVER events popped"),
    "pops_timer": (COUNTER, "K_TCP_TIMER events popped"),
    "pops_txr": (COUNTER, "K_TX_RESUME events popped"),
    "pops_app": (COUNTER, "K_APP events popped"),
    "fires_pkt": (COUNTER, "rounds where the K_PKT pass fired"),
    "fires_deliver": (COUNTER, "rounds where the K_PKT_DELIVER pass fired"),
    "fires_timer": (COUNTER, "rounds where the K_TCP_TIMER pass fired"),
    "fires_txr": (COUNTER, "rounds where the K_TX_RESUME pass fired"),
    "fires_app": (COUNTER, "rounds where the K_APP pass fired"),
    "link_down_pkts": (COUNTER, "packets dropped: link outage window (fault plane)"),
    "host_restarts": (COUNTER, "host restart resets applied (fault plane churn)"),
    # Wasted-work accounting (performance attribution plane): per-window
    # boundary samples accumulated as running sums, so the per-window value
    # rides the telemetry ring as a delta like any counter. All three are
    # engine-independent boundary quantities (the window-start pending set
    # and the per-window send set are the same on every engine — the digest
    # contract's argument), so they are bit-exact cpu<->tpu<->sharded.
    "active_hosts": (COUNTER, "sum over windows of hosts with >=1 eligible "
                              "event at window start (vs n_hosts: the "
                              "fraction of the [cap, H] plane passes doing "
                              "real work)"),
    "elig_events": (COUNTER, "sum over windows of events eligible at window "
                             "start (the work actually available to the "
                             "round loop)"),
    "outbox_hosts": (COUNTER, "sum over windows of hosts with >=1 outbox "
                              "slot used (vs n_hosts: the live fraction of "
                              "the route/deliver pass)"),
    "chunk_retries": (COUNTER, "chunks discarded and replayed after overflow "
                               "(--on-overflow retry; txn.OverflowGuard)"),
    "retry_windows_rerun": (COUNTER, "windows re-executed by overflow "
                                     "chunk retries"),
}

# HOST-side counters (txn.OverflowGuard): maintained by the chunk runner on
# the host, never in the device Metrics tuple — they ride the canonical
# namespace (normalize/Prometheus) but are excluded from the Metrics-fields
# sync contract, from heartbeat deltas (the retries block carries them) and
# from ring percentile stats (chunk-level, not per-window).
HOST_FIELDS = ("chunk_retries", "retry_windows_rerun")

# JSONL record types every consumer recognises (docs/OBSERVABILITY.md).
# ``digest`` is the CPU oracle's per-window state-digest row (the batched
# engines carry the same words as ring columns instead). Fleet mode
# (shadow1_tpu/fleet/) emits one ``fleet_exp`` final record per experiment
# plus one ``fleet_summary``; its ring records are the solo schema with an
# added ``exp`` experiment-id field — consumers group by it and keep it out
# of any value math.
REC_HEARTBEAT = "heartbeat"
REC_TRACKER = "tracker"
REC_RING = "ring"
REC_RING_GAP = "ring_gap"
REC_DIGEST = "digest"
REC_FLEET_EXP = "fleet_exp"
REC_FLEET_SUMMARY = "fleet_summary"
# Fleet recovery plane (fleet/run.py, docs/OBSERVABILITY.md §"Fleet
# recovery records"): ``fleet_retry`` = one record per discarded+replayed
# fleet chunk (windows, caps grown, offending lanes per counter);
# ``fleet_quarantine`` = one record per lane sliced out of the sweep
# (exp/seed/reason/window/knob + the solo-resumable checkpoint path).
# Chunk-level events, never per-window rows — like the retry counters,
# they stay out of ring percentile math by being their own record types
# (tools/heartbeat_report.py's fleet-recovery section reads them).
REC_FLEET_RETRY = "fleet_retry"
REC_FLEET_QUARANTINE = "fleet_quarantine"
# Preemption plane (PR 7): ``resume`` = one record per lineage resume (which
# generation, corrupt newer ones skipped); ``lineage`` = supervisor events
# (watchdog_kill / preempted / corrupt_head / discard_all) — both on stderr,
# summarized by tools/heartbeat_report.py's lineage section.
REC_RESUME = "resume"
REC_LINEAGE = "lineage"
# Memory plane (shadow1_tpu/mem.py): one ``mem`` record per batched run on
# stderr (event = estimate | downshift | final) — estimated per-plane bytes
# vs the device budget, applied downshifts, and the backend's measured peak
# when it reports one. Like the digest/retry columns, mem fields never
# enter ring percentile math: they are their own record type, summarized by
# tools/heartbeat_report.py's "memory" section.
REC_MEM = "mem"
# Performance attribution plane: ``work`` is the CPU oracle's per-window
# wasted-work row (the batched engines carry the same values as the
# RING_WORK ring columns instead — one schema, two carriers, exactly like
# the digest words). Fields: window, active_hosts, elig_events,
# outbox_hosts. Summarized by tools/heartbeat_report.py's work-efficiency
# section; never enters ring percentile math.
REC_WORK = "work"
# Serve plane (shadow1_tpu/serve/, docs/OBSERVABILITY.md §"Serve
# records"): ``serve`` = daemon-level events (start / accept / reject /
# batch_start / batch_done / evict / shutdown — each with a ``cache``
# hit|miss field on batch_start); ``serve_job`` = one record per job
# state transition (queued → running → done|failed|rejected|evicted),
# the rows heartbeat_report's serve section tabulates. Daemon-level
# events, never per-window rows — like the digest/retry columns they
# stay out of ring percentile math by being their own record types.
REC_SERVE = "serve"
REC_SERVE_JOB = "serve_job"
# Serve resilience planes (docs/OBSERVABILITY.md §"Serve records"):
# ``serve_queue`` = admission backpressure events (enqueue /
# waiting_headroom / reject_full) each with the queue's depth, queued
# est_peak bytes and oldest-wait age at that instant; ``serve_deadline``
# = one record per expiry (kind = queue_ttl | running — a running expiry
# names the committed-prefix checkpoint and ran_s); ``serve_retry`` = the
# transient-failure retry plane (event = retry | bisect | exhausted, with
# the batch, job list, attempt count and backoff). All daemon-level, out
# of ring percentile math like every serve record.
REC_SERVE_QUEUE = "serve_queue"
REC_SERVE_DEADLINE = "serve_deadline"
REC_SERVE_RETRY = "serve_retry"
# Flow-probe plane (telemetry/probes.py, EngineParams.probes): ``flow`` =
# one per-window sample of one watched (host, sock) entity — the PROBE_FIELDS
# columns plus window/sim_time_s/host/sock (sock −1 = host-only view). The
# batched engines carry the samples in the [W, K, F] probe ring and drain
# them at chunk boundaries; the CPU oracle emits the same rows at window
# boundaries (probe_rows) — bit-identical streams, like the digest words.
# ``flow_gap`` mirrors ``ring_gap``: windows overwritten before a drain.
# Fleet rows add the ``exp`` id, same rule as ring records.
REC_FLOW = "flow"
REC_FLOW_GAP = "flow_gap"
# Link-telemetry plane (telemetry/links.py, EngineParams.link_telem):
# ``link`` = one CUMULATIVE per-edge snapshot per chunk boundary per active
# (src_vertex, dst_vertex) edge — the LINK_FIELDS columns plus
# window/sim_time_s/src_vertex/dst_vertex. Snapshots are running totals
# (diff consecutive records per edge for rates), so a drain is a pure
# function of device state and every engine's stream at the same boundary
# is bit-identical (the digest-words argument). ``link_gap`` marks a
# stream rebase: the window cursor regressed below the last drained
# boundary (fleet lane rebind / mid-sweep lane lifecycle), so earlier
# snapshots and later ones belong to different runs of the lane.
# Fleet rows add the ``exp`` id, same rule as ring records.
REC_LINK = "link"
REC_LINK_GAP = "link_gap"
RECORD_TYPES = (REC_HEARTBEAT, REC_TRACKER, REC_RING, REC_RING_GAP,
                REC_DIGEST, REC_FLEET_EXP, REC_FLEET_SUMMARY,
                REC_FLEET_RETRY, REC_FLEET_QUARANTINE,
                REC_RESUME, REC_LINEAGE, REC_MEM, REC_WORK,
                REC_SERVE, REC_SERVE_JOB, REC_SERVE_QUEUE,
                REC_SERVE_DEADLINE, REC_SERVE_RETRY,
                REC_FLOW, REC_FLOW_GAP,
                REC_LINK, REC_LINK_GAP)

# Serve-plane job-ledger namespace (shadow1_tpu/serve/daemon.py): exported
# on the daemon's Prometheus endpoint (--metrics-port) with the
# ``shadow1_serve`` prefix, DISTINCT from the engine counter namespace
# above — the engines' Metrics-fields sync contract never sees these.
SERVE_SPECS: dict[str, tuple[str, str]] = {
    "jobs_submitted": (COUNTER, "job submissions accepted into the spool"),
    "jobs_rejected": (COUNTER, "jobs rejected at admission (config/memory)"),
    "jobs_done": (COUNTER, "jobs finished successfully"),
    "jobs_failed": (COUNTER, "jobs failed (quarantined lane / runtime error)"),
    "jobs_evicted": (COUNTER, "job evictions (priority preemption drains)"),
    "jobs_queued": (GAUGE, "jobs waiting in the lane-packing queue"),
    "jobs_waiting": (GAUGE, "jobs in waiting_headroom (fit idle, not live)"),
    "jobs_running": (GAUGE, "jobs in the in-flight fleet batch"),
    "queue_depth": (GAUGE, "admitted jobs waiting (queued + waiting_headroom)"),
    "queue_bytes": (GAUGE, "est_peak bytes of every waiting job, summed"),
    "oldest_wait_s": (GAUGE, "age of the oldest waiting job"),
    "jobs_queue_full": (COUNTER, "queue_full rejections (backpressure caps)"),
    "jobs_expired": (COUNTER, "deadline expiries (queue TTL + running)"),
    "batch_retries": (COUNTER, "transient-failure batch retries (backoff)"),
    "jobs_bisected": (COUNTER, "jobs split into solo batches after repeat crashes"),
    "batches_run": (COUNTER, "fleet batches executed"),
    "cache_hits": (COUNTER, "hot-engine cache hits (compile skipped)"),
    "cache_misses": (COUNTER, "hot-engine cache misses (trace + compile paid)"),
    "cache_evictions": (COUNTER, "hot-engine cache LRU evictions"),
    "cache_entries": (GAUGE, "compiled engines currently resident in the cache"),
    # Link-telemetry roll-up (the result router watches ``link`` records as
    # they demux into per-job result.jsonl streams): the hottest single
    # edge seen across all tenants — cumulative wire bytes and total drops
    # (loss + link_down + NIC backlog) of the busiest / lossiest edge.
    "top_edge_bytes": (GAUGE, "wire bytes on the hottest edge seen (link records)"),
    "top_edge_drops": (GAUGE, "drops on the lossiest edge seen (link records)"),
}

# The drop/overflow counter group: every way a modeled event or packet can
# be discarded, with the human-readable reason. Heartbeat records and the
# CLI's final JSON group these under one structured ``drops`` block (and
# tools/heartbeat_report.py prints them as a drop-reason table) instead of
# eleven flat counters scattered through ``delta``. The fault plane's
# discards live here too — churn experiments must account for every
# fault-induced loss through the same table.
DROP_SPECS: dict[str, str] = {
    "ev_overflow": "event buffer full",
    "ob_overflow": "outbox full",
    "x2x_overflow": "all_to_all bucket full (sharded)",
    "nic_tx_drops": "NIC uplink queue full",
    "nic_rx_drops": "NIC downlink queue full",
    "nic_aqm_drops": "RED early drop (uplink)",
    "tcp_ooo_drops": "out-of-order segment (GBN receiver)",
    "down_events": "event at a dead host (churn)",
    "down_pkts": "destination host dead at arrival (churn)",
    "link_down_pkts": "link outage window (fault plane)",
    "pkts_lost": "path loss draw",
}
DROP_FIELDS = tuple(DROP_SPECS)

# ---------------------------------------------------------------------------
# On-device telemetry ring schema (consumed by telemetry/ring.py, which owns
# the jax side; declared here so report tools stay jax-free).
# Counter columns are PER-WINDOW DELTAS of the matching METRIC_SPECS
# counters; gauge columns are per-window occupancy gauges.
# ---------------------------------------------------------------------------
RING_COUNTERS = (
    "events", "rounds", "pkts_sent", "pkts_delivered", "pkts_lost",
    "ev_overflow", "ob_overflow", "x2x_overflow", "down_events", "down_pkts",
    "link_down_pkts", "host_restarts",
)
# Wasted-work accounting columns (performance attribution plane): per-window
# DELTAS of the matching METRIC_SPECS counters, i.e. the window's boundary
# sample itself (the counters are running sums of per-window samples).
# Additive across shards like the counter deltas (each shard counts its host
# block; the psum is the global value, bit-equal to single-device), and
# mirrored bit-exactly by the CPU oracle's boundary sampling (work_rows).
# Kept OUT of RING_COUNTERS so ring percentile consumers that rank raw
# counter deltas don't blend utilization samples in — the work-efficiency
# section (tools/heartbeat_report.py) owns their presentation.
RING_WORK = (
    "active_hosts",   # hosts with >=1 eligible event at window start
    "elig_events",    # events eligible at window start
    "outbox_hosts",   # hosts that used >=1 outbox slot this window
)
RING_GAUGES = (
    "evbuf_fill",       # max pending events on any host at window end
    "ev_max_fill",      # running high-water of evbuf_fill (vs ev_cap)
    "ob_max_fill",      # running high-water per-window outbox fill
    "compact_max_fill", # running high-water compaction-bucket demand
    "x2x_max_fill",     # running high-water all_to_all bucket demand
)
# Determinism flight recorder (core/digest.py, EngineParams.state_digest):
# one order-independent state-digest word per subsystem per window. All
# zeros when state_digest is off. Sum-combined (psum'd under sharding),
# NOT deltas and NOT gauges — compare them across runs, never aggregate.
RING_DIGESTS = (
    "dg_evbuf",   # occupied event slots keyed by (host, time, tb, kind, p)
    "dg_outbox",  # this window's buffered sends (before the window-end clear)
    "dg_tcp",     # live sockets: every tcp-plane field + message-boundary FIFO
    "dg_nic",     # per-host NIC clocks and byte/AQM counters
    "dg_rng",     # per-host deterministic counters (self_ctr/pkt_ctr/cpu_busy
                  # + model draw counters)
)
RING_FIELDS = RING_COUNTERS + RING_WORK + RING_GAUGES + RING_DIGESTS

# ---------------------------------------------------------------------------
# Flow-probe column schema (consumed by telemetry/probes.py, which owns the
# jax side; declared here so report tools stay jax-free). One [K, F] row per
# window per watched entity, F = len(PROBE_FIELDS), sampled at the window
# boundary — the same engine-independent boundary state the digest hashes,
# so cpu/tpu/sharded/fleet streams compare bit-exact. TCP columns are zero
# for host-only probes (sock == −1) and for non-net models; NIC backlogs are
# ns of serialization debt relative to the window end (max(free_at − end, 0)).
# There are no per-host NIC drop counters in NicState (drops are global
# metrics), so the byte counters carry the per-host wire activity instead.
# ---------------------------------------------------------------------------
PROBE_FIELDS = (
    "tcp_state",          # TCP_* state enum (0 = free/closed)
    "cwnd",               # congestion window, bytes
    "ssthresh",           # slow-start threshold, bytes
    "srtt",               # smoothed RTT, ns (0 until first sample)
    "rttvar",             # RTT variance, ns
    "rto",                # retransmit timeout, ns
    "inflight",           # snd_nxt − snd_una (signed seq distance), bytes
    "snd_max",            # highest sequence ever sent (u32 window)
    "peer_wnd",           # last advertised peer receive window, bytes
    "nic_tx_backlog_ns",  # uplink serialization backlog past window end, ns
    "nic_rx_backlog_ns",  # downlink serialization backlog past window end, ns
    "nic_tx_bytes",       # lifetime wire bytes sent by the host
    "nic_rx_bytes",       # lifetime wire bytes received by the host
    "pending_events",     # events queued at the host at the boundary
)

# ---------------------------------------------------------------------------
# Link-telemetry column schema (consumed by telemetry/links.py, which owns
# the jax side; declared here so tools/netreport.py stays jax-free). One
# [V, V, F] i64 accumulator keyed (src_vertex, dst_vertex); every column is
# a RUNNING TOTAL since sim start. ``pkts``/``bytes`` count packets OFFERED
# to the edge at routing time (everything that reached an outbox slot —
# the pkts_sent population; ob_overflow losses never reached an edge);
# drop columns partition the offered packets that died on the edge;
# ``queued_ns_*`` measure NIC serialization debt: depart − window_start of
# the send window, per offered packet (values past the window length mean
# the uplink is carrying backlog across windows — the saturation signal).
# The first LINK_MAX_COL columns are additive (psum across shards / diff
# across snapshots); ``queued_ns_max`` is a high-water gauge (max-reduced,
# never diffed) — the fill-gauge rule.
# ---------------------------------------------------------------------------
LINK_FIELDS = (
    "pkts",               # packets offered to the edge (routing time)
    "bytes",              # wire bytes offered (payload + WIRE_OVERHEAD)
    "loss_drops",         # path-loss draws lost on the edge
    "link_down_drops",    # fault-plane outage drops on the edge
    "nic_backlog_drops",  # NIC uplink drop-tail drops, egress-edge attributed
    "queued_ns_sum",      # sum of per-packet NIC queueing (depart - win_start)
    "queued_ns_max",      # high-water per-packet NIC queueing (gauge)
)
LINK_MAX_COL = LINK_FIELDS.index("queued_ns_max")


def counter_names() -> tuple[str, ...]:
    return tuple(n for n, (k, _) in METRIC_SPECS.items() if k == COUNTER)


def gauge_names() -> tuple[str, ...]:
    return tuple(n for n, (k, _) in METRIC_SPECS.items() if k == GAUGE)


def normalize(metrics: dict) -> dict[str, int]:
    """Project ``metrics`` onto the canonical namespace.

    Every canonical counter is present (missing → 0, canonical order);
    engine-specific extras follow, preserved verbatim — so consumers can
    index any canonical name without guarding, on any engine's dict."""
    out = {name: int(metrics.get(name, 0)) for name in METRIC_SPECS}
    out.update({k: v for k, v in metrics.items() if k not in METRIC_SPECS})
    return out


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def to_prometheus(metrics: dict, prefix: str = "shadow1",
                  labels: dict | None = None,
                  specs: dict | None = None) -> str:
    """Prometheus text exposition (version 0.0.4) of a metrics dict.

    Canonical counters are exported as ``<prefix>_<name>_total``, gauges as
    ``<prefix>_<name>``; unknown extras default to counter kind. ``specs``
    selects the namespace table (default METRIC_SPECS; the serve daemon's
    job ledger exports through SERVE_SPECS instead — dicts are then taken
    as-is, no engine-counter normalization)."""
    lab = ""
    if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                         for k, v in sorted(labels.items()))
        lab = "{" + inner + "}"
    lines = []
    table = METRIC_SPECS if specs is None else specs
    rows = normalize(metrics) if specs is None else \
        {**{n: metrics.get(n, 0) for n in table},
         **{k: v for k, v in metrics.items() if k not in table}}
    for name, value in rows.items():
        kind, help_ = table.get(name, (COUNTER, "engine-specific counter"))
        metric = f"{prefix}_{name}" + ("_total" if kind == COUNTER else "")
        lines.append(f"# HELP {metric} {_escape_help(help_)}")
        lines.append(f"# TYPE {metric} {kind}")
        # Integral values print as integers; fractional gauges (wait-time
        # seconds) keep their fraction — int() would floor a sub-second
        # queue wait to a lying zero.
        v = float(value or 0)
        lines.append(f"{metric}{lab} {int(v) if v == int(v) else v}")
    return "\n".join(lines) + "\n"


class ExpositionServer:
    """Minimal Prometheus-style scrape endpoint (GET /metrics).

    ``get_metrics`` is called per scrape and must return a metrics dict —
    typically ``lambda: Engine.metrics_dict(latest_state)`` refreshed at
    chunk boundaries, so scraping never touches the device mid-window.

        srv = ExpositionServer(lambda: metrics, port=0)  # 0 = ephemeral
        srv.start()
        ... scrape http://127.0.0.1:{srv.port}/metrics ...
        srv.stop()
    """

    def __init__(self, get_metrics, port: int = 0, host: str = "127.0.0.1",
                 prefix: str = "shadow1", labels: dict | None = None,
                 specs: dict | None = None):
        self.get_metrics = get_metrics
        self._addr = (host, port)
        self.prefix = prefix
        self.labels = labels
        self.specs = specs
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return self._httpd.server_address[1]

    def start(self) -> "ExpositionServer":
        import http.server

        reg = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path.rstrip("/") in ("", "/metrics"):
                    body = to_prometheus(reg.get_metrics(), prefix=reg.prefix,
                                         labels=reg.labels,
                                         specs=reg.specs).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer(self._addr, Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
