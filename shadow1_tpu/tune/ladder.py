"""The geometric capacity ladder — quantized cap values, bounded recompiles.

Caps are STATIC shapes: every distinct value is a distinct XLA program, and
engine round bodies take minutes to compile on the real chip. An adaptive
controller that chased the exact measured peak would recompile every chunk;
quantizing to a fixed geometric ladder bounds the reachable cap set to
O(log(range)) values, so the controller's engine cache — and the jit
cache — stay small no matter how occupancy wanders.

The ladder interleaves powers of two with their 1.5× midpoints
(8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256, ...): successive steps are
×1.33/×1.5, every value is lane-tiling-friendly, and the familiar config
caps (48, 96, 256, 512) are all on it.

Deliberately jax-free: tools/captune.py and the report scripts import this
without paying an accelerator-runtime import.
"""

from __future__ import annotations

import math

# Smallest cap the tuner will ever pick; also the ladder's anchor.
LADDER_MIN = 8
# Default sizing headroom: target cap = quantize(ceil(peak * HEADROOM)).
# Fill gauges are window-end samples — a LOWER bound on the true mid-window
# peak (docs/PERF.md cap economics) — so the policy sizes generously and
# lets the overflow counters police the residual risk.
HEADROOM = 1.5
# A cap below ceil(peak * MIN_HEADROOM) is flagged under-provisioned
# (grow advice / controller grow trigger via grow_frac = 1/MIN_HEADROOM).
MIN_HEADROOM = 1.2


def cap_ladder(hi: int = 1 << 22) -> list[int]:
    """Ladder values in [LADDER_MIN, hi]: 8, 12, 16, 24, 32, 48, 64, 96, ..."""
    out: list[int] = []
    v = LADDER_MIN
    while v <= hi:
        out.append(v)
        if v + v // 2 <= hi:
            out.append(v + v // 2)
        v *= 2
    return out


def quantize_cap(need: int) -> int:
    """Smallest ladder value ≥ ``need``."""
    need = max(int(need), LADDER_MIN)
    v = LADDER_MIN
    while v < need:
        mid = v + v // 2  # the 1.5× midpoint comes before the next double
        if mid >= need:
            return mid
        v *= 2
    return v


def next_step(cap: int) -> int:
    """Smallest ladder value strictly above ``cap`` (cap need not be on it)."""
    return quantize_cap(int(cap) + 1)


def recommend_cap(peak: int, headroom: float = HEADROOM) -> int:
    """Measured peak fill → ladder-quantized recommended cap."""
    return quantize_cap(math.ceil(max(int(peak), 0) * headroom))


def classify(peak: int, cap: int, headroom: float = HEADROOM) -> dict:
    """Advisory verdict for one (measured peak, configured cap) pair.

    Returns ``{"verdict": "grow"|"shrink"|"ok", "recommended": int,
    "over_factor": float}`` — ``grow`` when the cap is under the minimum
    headroom over the peak (overflow risk), ``shrink`` when it exceeds the
    quantized target (over-provisioned by ``over_factor`` = cap/peak),
    ``ok`` when it sits in between (e.g. a hand-validated tight cap)."""
    peak, cap = int(peak), int(cap)
    target = recommend_cap(peak, headroom)
    floor = math.ceil(max(peak, 1) * MIN_HEADROOM)
    if cap < floor:
        verdict = "grow"
    elif cap > target:
        verdict = "shrink"
    else:
        verdict = "ok"
    return {
        "verdict": verdict,
        "recommended": target if verdict != "ok" else cap,
        "target": target,
        "over_factor": round(cap / max(peak, 1), 2),
    }
