"""Backend bootstrap: pick a live jax platform without hanging.

The reference selects its execution backend from CLI/config alone
(src/main/core/support/options.c); a TPU-native framework additionally has
to survive the accelerator being unreachable. On some machines the TPU PJRT
plugin is pre-selected via an env hook in a way that wins over plain
``os.environ`` mutation, and when the TPU service is down, backend init
*hangs* rather than erroring — so any entry point that just imports jax and
touches a device can eat an entire CI budget (this killed both driver gates
in round 1).

The cure, applied by every entry point (bench.py, __graft_entry__, CLI):

1. Probe the default backend **in a subprocess with a deadline**. The child
   inherits the environment, so it initializes exactly the backend the
   parent would; if it hangs or errors, the parent learns that without
   hanging itself.
2. If the probe reports a live backend with enough devices, let the parent
   initialize normally (TPU numbers when TPU is up).
3. Otherwise force the CPU platform — ``jax.config.update("jax_platforms",
   "cpu")`` is the only route that reliably overrides the env hook (see
   tests/conftest.py) — with ``--xla_force_host_platform_device_count=N``
   when multiple (virtual) devices are needed.

All functions here must be called BEFORE the first jax array/device
operation in the process; after backend init the platform is fixed.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

_PROBE_SRC = (
    "import jax, json; "
    "print(json.dumps({'backend': jax.default_backend(),"
    " 'n_devices': len(jax.devices())}))"
)

# Cache of the subprocess probe for this process (probe cost ~ jax import).
_probe_cache: dict | None = None


def probe_default_backend(deadline_s: float | None = None) -> dict:
    """Initialize jax's default backend in a subprocess; report or time out.

    Returns ``{"backend": str, "n_devices": int}`` when the child
    initializes within the deadline, else ``{"backend": "", "n_devices": 0,
    "error": str}``. The result is cached per process.
    """
    global _probe_cache
    if _probe_cache is not None:
        return _probe_cache
    if deadline_s is None:
        deadline_s = float(os.environ.get("SHADOW1_TPU_PROBE_DEADLINE", "45"))
    try:
        # NEVER kill the probe child at the deadline: SIGKILLing a process
        # inside tunnel device-init is what wedges the tunnel for every
        # subsequent client (docs/PERF.md round-5). On timeout the child is
        # left to finish detached (start_new_session) and the caller falls
        # back to CPU; the orphan exits on its own once init resolves.
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            out_p = os.path.join(td, "out")
            err_p = os.path.join(td, "err")
            with open(out_p, "w") as fo, open(err_p, "w") as fe:
                proc = subprocess.Popen(
                    [sys.executable, "-c", _PROBE_SRC],
                    stdout=fo, stderr=fe, text=True,
                    start_new_session=True,
                )
            try:
                proc.wait(timeout=deadline_s)
            except subprocess.TimeoutExpired:
                # Reap the orphan eventually without blocking or killing:
                # a daemon thread waits it out, avoiding a zombie + the
                # Popen.__del__ ResourceWarning.
                import threading

                threading.Thread(target=proc.wait, daemon=True).start()
                _probe_cache = {
                    "backend": "", "n_devices": 0,
                    "error": f"backend init exceeded {deadline_s:.0f}s "
                             "deadline (probe child left to finish detached)",
                }
                return _probe_cache
            stdout, stderr = open(out_p).read(), open(err_p).read()
        if proc.returncode == 0:
            _probe_cache = json.loads(stdout.strip().splitlines()[-1])
        else:
            _probe_cache = {
                "backend": "", "n_devices": 0,
                "error": f"rc={proc.returncode}: {stderr.strip()[-500:]}",
            }
    except Exception as e:  # noqa: BLE001 — any probe failure means fallback
        _probe_cache = {"backend": "", "n_devices": 0, "error": repr(e)}
    return _probe_cache


def force_cpu(n_devices: int = 1) -> None:
    """Force the CPU platform with at least ``n_devices`` virtual devices.

    Must run before jax initializes a backend. XLA_FLAGS is read at CPU
    client creation, so mutating it here (pre-init) is effective. An
    existing ``--xla_force_host_platform_device_count`` smaller than
    ``n_devices`` is raised to ``n_devices``.
    """
    if n_devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            )
        elif int(m.group(1)) < n_devices:
            os.environ["XLA_FLAGS"] = (
                flags[: m.start()]
                + f"--xla_force_host_platform_device_count={n_devices}"
                + flags[m.end():]
            )
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_live_platform(min_devices: int = 1,
                         deadline_s: float | None = None,
                         fallback_devices: int | None = None) -> str:
    """Guarantee the process will init a live backend with enough devices.

    Probes the default backend (subprocess + deadline). If it is alive and
    has ``min_devices`` devices, the default stands (real TPU when up).
    Otherwise forces CPU with ``fallback_devices`` (default ``min_devices``)
    virtual devices — pass a larger ``fallback_devices`` when a later call
    in the same process may need more (the platform is fixed at first use).
    Returns the chosen platform name ("cpu" or the probed backend).
    """
    info = probe_default_backend(deadline_s)
    if info["n_devices"] >= min_devices:
        return info["backend"]
    min_devices = max(min_devices, fallback_devices or 0)
    force_cpu(min_devices)
    # Verify the override took effect (it cannot after backend init — the
    # one precondition callers can violate). Loud failure beats a silently
    # wrong platform label.
    import jax

    backend = jax.default_backend()
    n = len(jax.devices())
    if backend != "cpu" or n < min_devices:
        raise RuntimeError(
            f"could not force cpu platform with {min_devices} devices "
            f"(got backend={backend!r} with {n}); ensure_live_platform must "
            "be called before the first jax device operation in the process"
        )
    return "cpu"
