"""Shared constants and engine parameters.

Both engines (cpu_engine and the TPU core) import from here so that the
simulation *semantics* — event kinds, packet flags, capacity limits, TCP
constants — are defined exactly once. The reference keeps the analogous
definitions in ``src/main/core/work/event.c`` (event ordering),
``src/main/routing/packet.c`` (header fields/flags) and
``src/main/host/descriptor/tcp.c`` (TCP constants).
"""

from __future__ import annotations

import dataclasses

# --------------------------------------------------------------------------
# CLI exit-code taxonomy (docs/SEMANTICS.md "Preemption contract", README).
# One table, defined in this jax-free module so the supervisor, the child,
# report tools and the tests all read the SAME codes — never magic ints.
# Any other nonzero exit is an unclassified crash (Python tracebacks exit 1;
# a signal death surfaces as 128+signum / negative returncode).
# --------------------------------------------------------------------------
EXIT_OK = 0          # run completed
EXIT_CONFIG = 2      # rejected before running: bad flags/config (argparse's
                     # own error code; structured FleetConfigError exits)
EXIT_CAPACITY = 4    # --on-overflow halt raised CapacityExceededError —
                     # deterministic config condition, supervisor never
                     # respawns (the child printed paste-ready cap advice)
EXIT_PREEMPTED = 5   # SIGTERM/SIGINT drain: the in-flight chunk was
                     # committed, a final snapshot written, and a parseable
                     # {"preempted": ...} record printed — the supervisor
                     # classifies this as clean-resume (no backoff, no crash
                     # accounting; rerun the same command to continue)
EXIT_HUNG = 6        # supervisor abort: the child's progress sidecar went
                     # stale past --watchdog-s twice consecutively with no
                     # forward progress — a deterministic wedge, not a
                     # transient device fault (see the no-kill probe
                     # playbook: tools/faultprobe)
EXIT_MEMORY = 7      # memory plane (shadow1_tpu/mem.py): the pre-flight
                     # byte budget rejected an oversubscribed config
                     # (MemoryBudgetError, per-plane attribution + paste-
                     # ready advice printed), or the runtime caught a
                     # RESOURCE_EXHAUSTED device OOM — either way a
                     # deterministic config-vs-device condition the
                     # supervisor never respawns into
EXIT_SERVE_SHUTDOWN = 8  # serve plane (shadow1_tpu/serve/): the daemon
                     # drained cleanly after SIGTERM/SIGINT (or a socket
                     # shutdown op) — the in-flight batch committed and
                     # checkpointed, every queued job persisted to the
                     # spool's queue.json; restarting the daemon on the
                     # same --spool resumes exactly where it left off
EXIT_SERVE_SPOOL = 9  # serve plane: the daemon REFUSED to start — the
                     # --spool directory is unusable (unwritable, torn
                     # beyond repair) or another live daemon already owns
                     # it (flock held, or daemon.json names a live holder
                     # under the heartbeat/pid stale-lock protocol; a
                     # SIGKILLed holder's leftovers classify stale and
                     # are reclaimed instead). Job submissions never use
                     # this code: a rejected job exits the submit client
                     # with EXIT_CONFIG / EXIT_MEMORY like the solo CLI
EXIT_QUEUE_FULL = 10  # serve plane backpressure: the job FITS an idle
                     # device but the daemon's bounded queue (--queue-depth
                     # / --queue-bytes) is at capacity — structured
                     # ``error=queue_full`` rejection carrying
                     # ``retry_after_s`` advice; resubmit after backing
                     # off (never a silent drop, never an OOM for the
                     # tenants already running)
EXIT_DEADLINE = 11   # serve plane deadlines: the job expired — either
                     # still waiting past --queue-ttl-s, or running past
                     # --deadline-s (drained at a chunk boundary; the
                     # result stream keeps the committed prefix, bit-
                     # identical to the same prefix of a straight run)

EXIT_CODES: dict[int, str] = {
    EXIT_OK: "ok",
    EXIT_CONFIG: "config rejected (flags/schema/fleet contract)",
    EXIT_CAPACITY: "capacity halt (CapacityExceededError, advice printed)",
    EXIT_PREEMPTED: "preempted (graceful drain; resume to continue)",
    EXIT_HUNG: "hung (watchdog killed a stale child twice, no progress)",
    EXIT_MEMORY: "memory (over HBM budget / RESOURCE_EXHAUSTED, advice printed)",
    EXIT_SERVE_SHUTDOWN: "serve daemon drained (queue persisted; restart to resume)",
    EXIT_SERVE_SPOOL: "serve daemon refused to start (spool unusable or owned)",
    EXIT_QUEUE_FULL: "serve queue full (backpressure; retry_after_s advice printed)",
    EXIT_DEADLINE: "serve deadline expired (queue TTL or running --deadline-s)",
}

# --------------------------------------------------------------------------
# Simulation time: int64 nanoseconds (reference SimulationTime is 1ns ticks).
# --------------------------------------------------------------------------
NS = 1
US = 1_000
MS = 1_000_000
SEC = 1_000_000_000

# --------------------------------------------------------------------------
# Event kinds. The reference dispatches closures (Task = fn + args,
# src/main/core/work/task.c); a tensorized engine needs a closed enum of
# handler kinds instead.
# --------------------------------------------------------------------------
K_NONE = 0        # empty slot
K_PHOLD = 1       # PHOLD benchmark hop (engine stress workload, SURVEY §4)
K_PKT = 2         # packet arrived at dst NIC (pre receive-queue)
K_PKT_DELIVER = 3 # packet cleared the NIC receive token bucket; process it
K_TCP_TIMER = 4   # per-socket retransmit timer check
K_TX_RESUME = 5   # continue flushing a socket's send buffer (burst bound)
K_APP = 6         # application state-machine wakeup (p0 = app opcode)
N_KINDS = 7

# Per-kind occupancy metric fields shared by both engines (kind →
# (pops-field, fires-field)): one table so the engines cannot drift.
KIND_METRIC_FIELDS = {
    K_PKT: ("pops_pkt", "fires_pkt"),
    K_PKT_DELIVER: ("pops_deliver", "fires_deliver"),
    K_TCP_TIMER: ("pops_timer", "fires_timer"),
    K_TX_RESUME: ("pops_txr", "fires_txr"),
    K_APP: ("pops_app", "fires_app"),
}

# Human-readable kind names — the phase attribution plane's handler-pass
# labels (jax.named_scope spans in core/engine.run_round, the per-pass rows
# of tools/opcensus.py and tools/phaseprobe.py).
KIND_NAMES = {
    K_NONE: "none",
    K_PHOLD: "phold",
    K_PKT: "pkt",
    K_PKT_DELIVER: "deliver",
    K_TCP_TIMER: "timer",
    K_TX_RESUME: "txr",
    K_APP: "app",
}

# Number of i32 payload columns on every event record.
NP = 10

# --------------------------------------------------------------------------
# Packet header flags (rides in the packed p1 column, bits 16..23).
# --------------------------------------------------------------------------
F_SYN = 1
F_ACK = 2
F_FIN = 4
F_RST = 8
F_DGRAM = 16      # datagram (UDP-like) — delivered straight to the app

# Packet event payload layout (p0..p9) — see docs/SEMANTICS.md:
#   p0 = src_host
#   p1 = src_sock | dst_sock << 8 | flags << 16
#   p2 = seq   (u32 wrapping byte offset, stored in i32)
#   p3 = ack   (u32 wrapping)
#   p4 = len   (payload bytes modeled; no actual bytes are carried)
#   p5 = wnd   (advertised receive window, bytes)
#   p6 = msg_end (u32 wrapping stream offset at which a message completes;
#                 0 sentinel = no message boundary in this segment)
#   p7 = msg_meta (opaque app metadata for that message)
#   p8, p9 = app scratch (datagrams: p8 = meta2)

# Event tie-break key classes (tb column, i64). Pop order is (time, tb)
# lexicographic — engine-independent, matching the reference's total event
# order (time, host, seq) in src/main/core/work/event.c (host is implicit
# here: buffers are per-host already).
TB_PACKET_BASE = 1 << 62  # packets order after same-time local events


def packet_tb(src_host: int, src_ctr: int) -> int:
    """Deterministic tie-break for a delivered packet event.

    Depends only on (src_host, per-src packet counter), so the CPU oracle
    (which schedules arrivals eagerly at send time) and the TPU engine
    (which scatters arrivals at window end) assign identical keys.
    """
    return TB_PACKET_BASE + (src_host << 32) + (src_ctr & 0xFFFFFFFF)


# --------------------------------------------------------------------------
# RNG purpose domains (counter-based keys: fold_in(seed, purpose, host, ctr)).
# Draws are order-independent so both engines reproduce identical streams.
# The reference gives each host a seeded RNG (src/main/host/host.c).
# --------------------------------------------------------------------------
R_PHOLD_DELAY = 1
R_PHOLD_DST = 2
R_LOSS = 3
R_APP = 4
R_TOR_PATH = 5
R_BTC = 6
R_JITTER = 7  # per-packet edge-latency jitter (ctr = src pkt counter)
R_AQM = 8     # RED early-drop coin (ctr = per-host uplink attempt counter)


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Static engine capacities and protocol constants.

    Shape-affecting fields are static (hashable dataclass → usable as a jit
    static argument). Both engines honour the same capacity bounds, but *which*
    items drop on overflow is engine-specific (eager order vs window-batch rank)
    — cross-engine parity is guaranteed only when the overflow counters are 0,
    which is what the metrics exist to police (docs/SEMANTICS.md §capacities).
    """

    # Per-host event buffer capacity (slots).
    ev_cap: int = 64
    # Per-host per-window packet outbox capacity.
    outbox_cap: int = 64
    # Sockets per host.
    sockets_per_host: int = 16
    # Per-socket in-flight message-boundary FIFO capacity.
    msgq_cap: int = 32
    # Max packets a single handler invocation may emit before it must yield
    # (schedules K_TX_RESUME at the same timestamp to continue).
    send_burst: int = 4
    # Max inner rounds per window (safety bound; overflow is counted).
    max_rounds: int = 256
    # Sharded engine: per-(src shard → dst shard) all_to_all bucket capacity
    # per window. 0 = auto (2× the uniform-traffic expectation, min 16).
    # Bucket-full drops are counted (x2x_overflow); parity requires 0.
    x2x_cap: int = 0
    # Sparse-window compaction bucket (active-host lanes per window; see
    # core/compact.py). 0 = off. Windows whose active-host count exceeds
    # the bucket run full-width — results are bit-identical either way, so
    # this is purely a perf knob. Size from tools/activeprobe.py (rung3
    # p99 = 284 of 1000; rung4 max = 1082 of 10000).
    compact_cap: int = 0
    # On-device telemetry ring: per-window counter-delta rows kept on
    # device (telemetry/ring.py) and drained at chunk boundaries. Value =
    # ring depth in windows (the horizon of per-window records a chunk can
    # recover); 0 = off — the SimState pytree then carries no ring leaf, so
    # the default is layout-identical to a ring-less build. Size it ≥ the
    # heartbeat chunk to get a gap-free time series (CLI --metrics-ring).
    metrics_ring: int = 0
    # Occupancy-driven capacity autotuning (shadow1_tpu/tune/): 1 = let the
    # chunk runner resize ev_cap between chunks from the measured high-water
    # fill gauges (grow before overflow, shrink after sustained low
    # occupancy; caps quantized to the tune.ladder geometric ladder so the
    # jit cache stays bounded). CLI --auto-caps overrides. outbox_cap is NOT
    # auto-resized by default: it is a semantic knob for TCP (tcp_flush
    # paces on outbox_space), so changing it mid-run changes the event
    # stream — see tune.autocap.CapPolicy.tune_outbox.
    auto_caps: int = 0
    # Flow-probe watchlist (telemetry/probes.py): K (host, sock) pairs whose
    # state columns are sampled once per window into the [W, K, F] probe
    # ring (W = metrics_ring depth). host is a GLOBAL host id; sock == -1
    # means the host-only (NIC/event) view. Resolved from the ``probes:``
    # config section / --watch through config/experiment.resolve_watchlist
    # — NEVER set raw names here; entries must be ints by trace time (they
    # are static jit arguments). () (default) = off: no probe leaf rides
    # SimState and zero probe ops are traced, the --state-digest rule.
    probes: tuple = ()
    # Determinism flight recorder (core/digest.py): 1 = compute per-window
    # order-independent state digests (one word per subsystem: evbuf,
    # outbox, tcp, nic, rng counters) inside the jitted window loop and
    # record them as telemetry-ring columns. Requires metrics_ring > 0 on
    # the batched engines (the ring is where the stream lives); the CPU
    # oracle mirrors the identical words at window boundaries. 0 (default)
    # = off: zero digest ops traced anywhere — the ring columns exist but
    # hold zeros. CLI --state-digest.
    state_digest: int = 0
    # Link-telemetry plane (telemetry/links.py): 1 = carry the [V, V, F]
    # per-edge accumulator in SimState and scatter-add every routed
    # packet's edge contribution at the window-end route phase (plus NIC
    # drop-tail drops at the tx sites), drained at chunk boundaries into
    # JSONL ``link`` records. 0 (default) = off: no link leaf rides
    # SimState and zero link ops are traced — the --state-digest rule.
    # The accumulator is never digested, so 1 is digest-neutral. CLI
    # --link-telem.
    link_telem: int = 0
    # Overflow policy (shadow1_tpu/txn.py; CLI --on-overflow): what the
    # chunk runner does when a chunk's fresh overflow deltas (ev_overflow /
    # ob_overflow / sharded x2x_overflow) are non-zero at its boundary.
    # "drop" (default) keeps today's counted-but-lossy behavior; "retry"
    # discards the tainted chunk, grows the offending cap one ladder step
    # (bit-exact state migration + re-jit) and replays the same chunk from
    # the saved chunk-start state — the retried run's digest stream
    # bit-matches a straight run at the final caps; "halt" raises a
    # structured CapacityExceededError with paste-ready cap advice.
    # Inert on the eager CPU oracle except "halt" (boundary check only).
    on_overflow: str = "drop"
    # Fleet lane-failure policy (fleet/run.py; CLI --on-lane-fail): what a
    # fleet run does when ONE lane deterministically fails at a chunk
    # boundary (capacity halt / retry-ladder exhaustion attributed to the
    # lane, or a per-lane selfcheck violation). "halt" (default) raises —
    # the whole sweep dies with the solo error/exit taxonomy; "quarantine"
    # slices the failing lane out of the chunk-START state into a
    # solo-resumable checkpoint plus a structured fleet_quarantine record,
    # repacks the survivors into an E-1 fleet (re-jit; survivor digest
    # streams provably unchanged — lanes are vmap-independent) and replays
    # the chunk, finishing the sweep at E-k/E. Inert on solo engines.
    on_lane_fail: str = "halt"
    # Mid-sweep lane finalization (fleet/run.py; CLI --lane-finalize):
    # 1 = at committed chunk boundaries, lanes whose event buffer has fully
    # drained (no live event anywhere — nothing can ever fire again) are
    # finalized early: their fleet_exp final record is emitted immediately
    # and they are sliced out of the fleet the quarantine way, so the
    # device program shrinks to the lanes still doing work. 0 (default) =
    # every lane runs the full window count. Inert on solo engines.
    lane_finalize: int = 0
    # In-run self-check (txn.check_boundary_identity; CLI --selfcheck):
    # 1 = verify the drop-accounting identity (every sent packet reaches
    # exactly one counted fate) at every chunk boundary (batched engines)
    # / window boundary (cpu oracle); violation raises SelfCheckError
    # naming the non-closing counters. 0 (default) = off.
    selfcheck: int = 0
    # Pop-min result extraction: "sum" (masked-sum over the one-hot — the
    # round-4 default) or "gather" (index via min-over-iota, then
    # take_along_axis — the round-3 style on the round-4 layout). Bit-exact
    # either way (the one-hot is exact); a perf A/B knob for the round-path
    # regression hunt (docs/PERF.md round-5).
    pop_extract: str = "sum"
    # Pop-min implementation: "xla" (the masked-reduction chain in
    # core/events.py) or "pallas" (the fused single-pass VMEM kernel in
    # core/popk.py — one HBM read/write per plane instead of ~12 full-plane
    # passes). Bit-exact either way (tests/test_events.py); a perf knob
    # pending on-chip A/B (docs/PERF.md round-5).
    pop_impl: str = "xla"
    # Push implementation, same contract: "xla" (first-free + one-hot
    # wheres) or "pallas" (core/popk.py fused single-pass kernel). Scoped
    # into the handler layers at trace time via events.push_impl_ctx.
    push_impl: str = "xla"

    # --- TCP constants (reference: src/main/host/descriptor/tcp.c) ---
    mss: int = 1460               # bytes per segment
    init_cwnd_mss: int = 10       # RFC6928 initial window
    sndbuf: int = 131072          # send buffer bytes
    rcvbuf: int = 131072          # advertised receive window (apps drain fast)
    rto_min: int = 200 * MS
    rto_max: int = 60 * SEC
    rto_init: int = 1 * SEC
    dupack_thresh: int = 3

    def __post_init__(self):
        assert self.sockets_per_host <= 256, "sock ids are packed into 8 bits"
        assert self.pop_extract in ("sum", "gather"), self.pop_extract
        assert self.metrics_ring >= 0, self.metrics_ring
        assert self.state_digest in (0, 1), self.state_digest
        assert self.link_telem in (0, 1), self.link_telem
        assert isinstance(self.probes, tuple), (
            "probes must be a tuple of (host, sock) int pairs "
            "(resolve_watchlist builds it)")
        for pr in self.probes:
            assert (isinstance(pr, tuple) and len(pr) == 2
                    and all(isinstance(v, int) for v in pr)), pr
            assert 0 <= pr[0], pr
            assert -1 <= pr[1] < self.sockets_per_host, pr
        assert self.auto_caps >= 0, self.auto_caps
        assert self.on_overflow in ("drop", "retry", "halt"), self.on_overflow
        assert self.on_lane_fail in ("halt", "quarantine"), self.on_lane_fail
        assert self.lane_finalize in (0, 1), self.lane_finalize
        assert self.selfcheck in (0, 1), self.selfcheck
        assert self.pop_impl in ("xla", "pallas"), self.pop_impl
        assert self.push_impl in ("xla", "pallas"), self.push_impl
        # The fused pop kernel extracts via the one-hot masked sum only; a
        # silent no-op pop_extract would corrupt exactly the A/B this knob
        # exists for.
        assert not (self.pop_impl == "pallas" and self.pop_extract != "sum"), (
            "pop_impl='pallas' implies pop_extract='sum'"
        )


# App notification flags (per-round, host-level — set by the transport layer,
# consumed by the app layer in the same round; the tensor analogue of the
# reference's descriptor status-bit → epoll → plugin callback chain,
# src/main/host/descriptor/descriptor.c + epoll.c, SURVEY §3.4).
N_ESTABLISHED = 1   # client: connect completed
N_ACCEPTED = 2      # server: child socket entered ESTABLISHED
N_MSG = 4           # in-order stream delivery crossed a message boundary
N_SPACE = 8         # send-buffer space became available
N_PEER_FIN = 16     # peer closed its direction
N_CLOSED = 32       # connection fully closed
N_DGRAM = 64        # datagram delivered
N_DATA = 128        # in-order stream bytes delivered (dlen)

# Wire overhead modeled per packet (IP + TCP headers), bytes.
WIRE_OVERHEAD = 40

# --- u32 wrapping sequence-number helpers (Python-int flavour, used by the
# CPU oracle; the TPU engine gets identical semantics from i32 overflow). ---
_M32 = 0xFFFFFFFF


def seq_add(a: int, n: int) -> int:
    return (a + n) & _M32


def seq_sub(a: int, b: int) -> int:
    """Signed distance a-b in sequence space."""
    d = (a - b) & _M32
    return d - (1 << 32) if d >= (1 << 31) else d


def seq_lt(a: int, b: int) -> bool:
    return seq_sub(a, b) < 0


def seq_le(a: int, b: int) -> bool:
    return seq_sub(a, b) <= 0


def ser_delay_ns(wire_bytes: int, bw_bits: int) -> int:
    """Serialization delay of a packet on a link, ns (ceil division)."""
    return (wire_bytes * 8 * SEC + bw_bits - 1) // bw_bits


# TCP connection states (reference tcp.c state machine).
TCP_FREE = 0
TCP_LISTEN = 1
TCP_SYN_SENT = 2
TCP_SYN_RCVD = 3
TCP_ESTABLISHED = 4
TCP_FIN_WAIT_1 = 5
TCP_FIN_WAIT_2 = 6
TCP_CLOSE_WAIT = 7
TCP_LAST_ACK = 8
TCP_CLOSING = 9
TCP_TIME_WAIT = 10
TCP_CLOSED = 11

# Shared TCP tuning constants (single source of truth for both engines).
SSTHRESH_INIT = 1 << 28
CWND_MAX = 1 << 28

# State sets used by both engines' send/receive paths.
TCP_SENDABLE_STATES = (
    TCP_SYN_SENT, TCP_SYN_RCVD, TCP_ESTABLISHED, TCP_CLOSE_WAIT,
    TCP_FIN_WAIT_1, TCP_LAST_ACK, TCP_CLOSING,
)
TCP_CONN_STATES = (
    TCP_SYN_SENT, TCP_SYN_RCVD, TCP_ESTABLISHED, TCP_FIN_WAIT_1,
    TCP_FIN_WAIT_2, TCP_CLOSE_WAIT, TCP_LAST_ACK, TCP_CLOSING,
)
TCP_RCV_STATES = (TCP_ESTABLISHED, TCP_FIN_WAIT_1, TCP_FIN_WAIT_2)
