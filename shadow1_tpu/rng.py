"""Counter-based deterministic randomness shared by both engines.

The reference gives every host its own seeded RNG (src/main/host/host.c) so
results are independent of worker scheduling. We go one step further: every
draw is a pure function of ``(seed, purpose, host, counter)`` via Threefry
``fold_in`` — order-independent, so the eager CPU oracle and the batched TPU
engine produce bit-identical streams no matter when each computes its draws.

All transforms from raw bits to values use minimal float chains (a single
multiply, or log+multiply) to keep eager-vs-jit rounding identical; the
parity tests in tests/ are the guard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def base_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(np.uint32(seed))


def _key(seed_key: jax.Array, purpose, host, ctr) -> jax.Array:
    k = jax.random.fold_in(seed_key, purpose)
    k = jax.random.fold_in(k, host)
    return jax.random.fold_in(k, ctr)


def bits(seed_key, purpose, host, ctr) -> jax.Array:
    """One u32 of raw randomness for (purpose, host, ctr). Scalar in, scalar out."""
    return jax.random.bits(_key(seed_key, purpose, host, ctr), (), jnp.uint32)


# Vectorized over (host, ctr) arrays — used by the TPU engine.
bits_v = jax.vmap(bits, in_axes=(None, None, 0, 0))


def uniform01(b: jax.Array) -> jax.Array:
    """u32 bits → float32 in [0, 1). Single exact multiply."""
    return b.astype(jnp.float32) * jnp.float32(2.0 ** -32)


def exponential_ns(b: jax.Array, mean_ns) -> jax.Array:
    """u32 bits → int64 ns exponential with the given mean.

    Uses -mean * log1p(-u); clamped to ≥ 1 ns so events always advance time.
    """
    u = uniform01(b)
    d = -jnp.float32(mean_ns) * jnp.log1p(-u)
    return jnp.maximum(d.astype(jnp.int64), 1)


def randint(b: jax.Array, n) -> jax.Array:
    """u32 bits → integer in [0, n) via 64-bit multiply-shift (exact, no bias
    for n ≪ 2^32 beyond the standard multiply-shift approximation; identical
    in both engines)."""
    return ((b.astype(jnp.uint64) * jnp.uint64(n)) >> jnp.uint64(32)).astype(jnp.int32)
