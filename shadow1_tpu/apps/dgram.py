"""dgram — periodic datagram traffic (UDP-path exerciser).

Each sender emits ``count`` datagrams of ``payload`` bytes at ``interval``
spacing to a fixed destination; receivers count deliveries. The minimal
workload for the NIC + routing + loss path without TCP (reference analogue:
the UDP feature test plugins, SURVEY §4).

model_cfg ([H] numpy arrays): dst, payload, interval, count, start_time.
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow1_tpu import net
from shadow1_tpu.consts import K_APP, N_DGRAM, NP
from shadow1_tpu.core.engine import push_local_event
from shadow1_tpu.core.events import push_local

OP_TICK = 1


def init(ctx, evbuf, tcpd):
    cfg = ctx.model_cfg
    app = {
        "dst": jnp.asarray(cfg["dst"], jnp.int32),
        "payload": jnp.asarray(cfg["payload"], jnp.int32),
        "interval": jnp.asarray(cfg["interval"], jnp.int64),
        "left": jnp.asarray(cfg["count"], jnp.int32),
        "rx_count": jnp.zeros(ctx.n_hosts, jnp.int64),
        "rx_bytes": jnp.zeros(ctx.n_hosts, jnp.int64),
    }
    sender = app["left"] > 0
    p = jnp.zeros((NP, ctx.n_hosts), jnp.int32).at[0].set(OP_TICK)
    k = jnp.full(ctx.n_hosts, K_APP, jnp.int32)
    evbuf, over = push_local(
        evbuf, sender, jnp.asarray(cfg["start_time"], jnp.int64), k, p
    )
    return app, evbuf, over.sum(dtype=jnp.int64), tcpd


def on_wakeup(st, ctx, ev, mask):
    m = mask & (ev.p[0] == OP_TICK)
    app = st.model.app
    send = m & (app["left"] > 0)
    zero = jnp.zeros(ctx.n_hosts, jnp.int32)
    st = net.udp_send(
        st, ctx, send, app["dst"], zero, app["payload"], zero + 1, zero, ev.time
    )
    app = dict(st.model.app)
    app["left"] = app["left"] - send.astype(jnp.int32)
    st = st._replace(model=st.model._replace(app=app))
    again = send & (app["left"] > 0)
    return push_local_event(st, ctx, again, ev.time + app["interval"], K_APP, p0=OP_TICK)


def on_notify(st, ctx, nf, now, mask):
    app = dict(st.model.app)
    dg = mask & ((nf.flags & N_DGRAM) != 0)
    app["rx_count"] = app["rx_count"] + dg.astype(jnp.int64)
    app["rx_bytes"] = app["rx_bytes"] + jnp.where(dg, nf.dlen.astype(jnp.int64), 0)
    return st._replace(model=st.model._replace(app=app))


def summary(app) -> dict:
    return {
        "rx_count": app["rx_count"],
        "rx_bytes": app["rx_bytes"],
        "total_rx": app["rx_count"].sum(),
    }
