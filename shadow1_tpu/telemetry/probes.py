"""On-device flow-probe ring — per-window state samples of watched entities.

The telemetry ring (telemetry/ring.py) sees the ENGINE (counter deltas,
occupancy gauges); it cannot answer "what did flow (host 3, sock 0) do" —
per-flow TCP dynamics and per-NIC queue state were only reachable by
re-running on the CPU oracle. This module gives the batched engines the
reference Tracker's per-socket fidelity (src/main/host/tracker.c) without
breaking the zero-mid-window-host-sync contract:

* ``EngineParams.probes`` holds K watched (host, sock) pairs — resolved at
  config time (config/experiment.resolve_watchlist) so they are static
  Python ints by the time anything traces;
* a device-resident ``[W, K, F]`` i64 buffer rides in ``SimState.probes``
  beside the telemetry ring; at the end of every conservative window the
  engine gathers each probe's state columns (``registry.PROBE_FIELDS``
  order) and writes one [K, F] row at slot ``window % W`` — one
  dynamic_update_slice, entirely inside the jitted loop;
* at chunk boundaries the host drains the rows into JSONL ``flow`` records
  (``drain_probes``); overwritten windows are reported as one ``flow_gap``
  record, exactly like ``ring_gap``.

The samples are window-BOUNDARY state — the same engine-independent sets
the state digest hashes — so the CPU oracle mirrors them bit-exactly
(cpu_engine/engine.py probe_rows), each shard of a sharded run contributes
its owned probes through a one-hot psum (every shard then carries the
identical replicated ring), and fleet lanes vmap to [E, W, K, F] with
exp-tagged records. Probes default off: ``probe_init`` returns None, no
pytree leaf exists, and the traced program is bit-identical to a
probe-less build (the ``--state-digest`` rule).

i32-semantics columns (TCP sequence/window fields) widen via u32 so the
TPU's natural i32 wraparound and the oracle's masked Python ints compare
equal; ``inflight`` is the one SIGNED column (seq distance snd_nxt −
snd_una, computed in i32 then widened).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from shadow1_tpu.consts import SEC
from shadow1_tpu.telemetry.registry import (
    PROBE_FIELDS,
    REC_FLOW,
    REC_FLOW_GAP,
)


class ProbeRing(NamedTuple):
    """The device-resident probe ring: one [K, F] row per window."""

    buf: jnp.ndarray  # i64 [W, K, len(PROBE_FIELDS)]


def probe_init(n_windows: int, probes: tuple) -> ProbeRing | None:
    """A W-row probe ring for K watched entities, or None when disabled.

    None (no probes, or no ring depth) contributes no pytree leaf, so a
    probe-less state keeps the historic leaf layout — checkpoints and
    sharding specs are unaffected unless probes are actually on."""
    if n_windows <= 0 or not probes:
        return None
    return ProbeRing(
        buf=jnp.zeros((int(n_windows), len(probes), len(PROBE_FIELDS)),
                      jnp.int64)
    )


def _u32w(v):
    """i32 plane value → i64 through the u32 window (the i32-semantics
    rule: the oracle masks with & 0xFFFFFFFF; a negative i32 here is the
    same wrapped u32)."""
    return v.astype(jnp.uint32).astype(jnp.int64)


def probe_sample(st, ctx, win_end, probes: tuple) -> jnp.ndarray:
    """Gather the [K, F] boundary sample of every watched entity (traced).

    ``probes`` are (global_host, sock) int pairs, sock == −1 for the
    host-only view. Probes owned by another shard's block contribute 0 in
    every column — the sharded engine's one-hot psum then reconstructs the
    owner's row exactly (shard/engine.py probe_reduce)."""
    n_hosts = ctx.n_hosts
    base = ctx.hosts[0]
    model = st.model
    mf = getattr(model, "_fields", ())
    has_net = "nic" in mf and "tcp" in mf
    from shadow1_tpu.core.events import tb_join

    live = st.evbuf.kind != 0  # K_NONE
    rows = []
    for gh, sock in probes:
        loc = jnp.asarray(gh, jnp.int32) - base
        owned = (loc >= 0) & (loc < n_hosts)
        locc = jnp.clip(loc, 0, n_hosts - 1)
        z = jnp.zeros((), jnp.int64)
        cols = dict.fromkeys(PROBE_FIELDS, z)
        if has_net and sock >= 0:
            tcp = model.tcp
            cols["tcp_state"] = _u32w(tcp["st"][sock, locc])
            cols["cwnd"] = _u32w(tcp["cwnd"][sock, locc])
            cols["ssthresh"] = _u32w(tcp["ssthresh"][sock, locc])
            cols["snd_max"] = _u32w(tcp["snd_max"][sock, locc])
            cols["peer_wnd"] = _u32w(tcp["peer_wnd"][sock, locc])
            # Signed seq distance: i32 subtraction wraps exactly like the
            # oracle's seq_sub, then the widen preserves the sign.
            cols["inflight"] = (
                tcp["snd_nxt"][sock, locc] - tcp["snd_una"][sock, locc]
            ).astype(jnp.int64)
            for f in ("srtt", "rttvar", "rto"):
                cols[f] = tb_join(tcp[f + "_hi"][sock, locc],
                                  tcp[f + "_lo"][sock, locc])
        if has_net:
            nic = model.nic
            cols["nic_tx_backlog_ns"] = jnp.maximum(
                nic.tx_free[locc] - win_end, 0)
            cols["nic_rx_backlog_ns"] = jnp.maximum(
                nic.rx_free[locc] - win_end, 0)
            cols["nic_tx_bytes"] = nic.tx_bytes[locc]
            cols["nic_rx_bytes"] = nic.rx_bytes[locc]
        cols["pending_events"] = live[:, locc].sum(dtype=jnp.int64)
        row = jnp.stack([cols[f] for f in PROBE_FIELDS])
        rows.append(jnp.where(owned, row, 0))
    return jnp.stack(rows)  # [K, F]


def probe_record(pring: ProbeRing, m0, row) -> ProbeRing:
    """Write one per-window [K, F] row (traced; end of window_step).

    ``m0`` is the window-entry Metrics — its pre-increment ``windows``
    counter is this window's global ordinal, the ring slot (same rule as
    ring_record)."""
    w = pring.buf.shape[0]
    slot = (m0.windows % w).astype(jnp.int32)
    z = jnp.zeros((), jnp.int32)
    return pring._replace(
        buf=jax.lax.dynamic_update_slice(
            pring.buf, row[None].astype(jnp.int64), (slot, z, z)
        )
    )


def drain_probes(st, window_ns: int, probes: tuple,
                 start: int = 0) -> list[dict]:
    """Host-side drain: the flow rows for windows [start, windows_done).

    One device→host fetch per call (chunk boundary, never mid-window).
    Returns JSONL-ready ``flow`` dicts in (window, probe) order; windows
    overwritten since ``start`` become one ``flow_gap`` record."""
    pring = getattr(st, "probes", None)
    if pring is None:
        return []
    buf = np.asarray(pring.buf)
    w = buf.shape[0]
    done = int(st.metrics.windows)
    lo = max(start, done - w)
    recs: list[dict] = []
    if lo > start:
        recs.append({
            "type": REC_FLOW_GAP,
            "windows_lost": lo - start,
            "first_window": start,
            "ring_slots": w,
        })
    for win in range(lo, done):
        rows = buf[win % w]
        t = round((win + 1) * window_ns / SEC, 9)
        for k, (gh, sock) in enumerate(probes):
            rec = {
                "type": REC_FLOW,
                "window": win,
                "sim_time_s": t,
                "host": int(gh),
                "sock": int(sock),
            }
            rec.update({f: int(v) for f, v in zip(PROBE_FIELDS, rows[k])})
            recs.append(rec)
    return recs
