"""Per-host per-window packet outboxes.

In the reference, a packet send walks NIC → topology path lookup → a locked
push onto the destination host's queue (SURVEY §3.3, src/main/routing/
topology.c + core/scheduler). Conservative windows guarantee every
cross-host event lands at least one window in the future, so the batched
engine buffers all sends of a window here and performs routing (latency
gather, loss draws) plus the destination scatter once per window — and, when
sharded, exactly one all_to_all per window over ICI (SURVEY §2.5).

Layout: slot-major, host-minor ([P, H]; payload [NP, P, H]) — see
core/dense.py for the tiling rationale. All [P, H] planes are i32 (the chip
has no native i64; docs/PERF.md): departure times ride the same
order-preserving (hi, lo) split as the event buffer (core/events.py
tb_split), joined once per window in route_outbox; the per-packet counter
plane holds the low 32 bits of the i64 ``pkt_ctr`` lifetime counter —
exact while no single host sends ≥ 2**31 packets in one run, which is far
outside the design envelope (the largest ladder rung totals 33M packets
across 5,000 hosts).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from shadow1_tpu.consts import NP
from shadow1_tpu.core.dense import set_col
from shadow1_tpu.core.events import tb_join, tb_split


class Outbox(NamedTuple):
    dst: jnp.ndarray        # i32 [P, H]
    kind: jnp.ndarray       # i32 [P, H] event kind to deliver at dst
    depart_hi: jnp.ndarray  # i32 [P, H] src-NIC departure time, high word
    depart_lo: jnp.ndarray  # i32 [P, H] low word (sign-flipped; tb_split)
    ctr: jnp.ndarray        # i32 [P, H] per-src packet counter (low word)
    p: jnp.ndarray          # i32 [NP, P, H]
    cnt: jnp.ndarray        # i32 [H] entries used this window
    pkt_ctr: jnp.ndarray    # i64 [H] lifetime per-src packet counter

    def abs_depart(self) -> jnp.ndarray:
        """i64 [P, H] departure times (window-granularity readers only)."""
        return tb_join(self.depart_hi, self.depart_lo)


def outbox_init(n_hosts: int, cap: int) -> Outbox:
    return Outbox(
        dst=jnp.zeros((cap, n_hosts), jnp.int32),
        kind=jnp.zeros((cap, n_hosts), jnp.int32),
        depart_hi=jnp.zeros((cap, n_hosts), jnp.int32),
        depart_lo=jnp.zeros((cap, n_hosts), jnp.int32),
        ctr=jnp.zeros((cap, n_hosts), jnp.int32),
        p=jnp.zeros((NP, cap, n_hosts), jnp.int32),
        cnt=jnp.zeros(n_hosts, jnp.int32),
        pkt_ctr=jnp.zeros(n_hosts, jnp.int64),
    )


def outbox_space(ob: Outbox) -> jnp.ndarray:
    return ob.dst.shape[0] - ob.cnt


def outbox_fill(ob: Outbox) -> jnp.ndarray:
    """Occupancy gauge: this window's fill on the busiest host, i64 scalar.
    Reads the maintained [H] counter — free; read before ``outbox_clear``."""
    return ob.cnt.max().astype(jnp.int64)


def outbox_append(ob: Outbox, mask, dst, kind, depart, p) -> tuple[Outbox, jnp.ndarray]:
    """Append one packet per host where ``mask``. Returns (ob, ok_mask).

    Callers that cannot tolerate drops (TCP) must check ``outbox_space``
    first and defer to the next window instead (K_TX_RESUME). Dense one-hot
    write — no scatter (core/dense.py). ``p`` is [NP, H]. Dispatches to the
    fused Pallas kernel under EngineParams.push_impl="pallas"
    (events.push_impl_ctx scope, core/popk.py).
    """
    from shadow1_tpu.core.events import _PUSH_IMPL

    if _PUSH_IMPL == "pallas":
        from shadow1_tpu.core.popk import outbox_append_fused

        return outbox_append_fused(ob, mask, dst, kind, depart, p)
    cap = ob.dst.shape[0]
    ok = mask & (ob.cnt < cap)
    dhi, dlo = tb_split(jnp.asarray(depart, jnp.int64))
    ob = ob._replace(
        dst=set_col(ob.dst, ob.cnt, dst, ok),
        kind=set_col(ob.kind, ob.cnt, kind, ok),
        depart_hi=set_col(ob.depart_hi, ob.cnt, dhi, ok),
        depart_lo=set_col(ob.depart_lo, ob.cnt, dlo, ok),
        ctr=set_col(ob.ctr, ob.cnt, ob.pkt_ctr.astype(jnp.int32), ok),
        p=set_col(ob.p, ob.cnt, p, ok),
        cnt=ob.cnt + ok.astype(jnp.int32),
        pkt_ctr=ob.pkt_ctr + ok.astype(jnp.int64),
    )
    return ob, ok


def outbox_clear(ob: Outbox) -> Outbox:
    return ob._replace(cnt=jnp.zeros_like(ob.cnt))
