"""Block-cached counter RNG draws for the CPU oracle.

The oracle consumes draws one at a time. Draws are pure functions of
(purpose, host, counter); since the shared RNG (shadow1_tpu.rng) is pure
integer arithmetic, the oracle evaluates its exact NumPy twins — zero
device dispatch (an eager jnp call per block was a device roundtrip and
dominated oracle runtime when the default backend was the TPU), bit-
identical values by construction (guarded by tests/test_rng.py). Blocks of
consecutive counters are still cached to amortize the vectorized hash.
"""

from __future__ import annotations

import numpy as np

from shadow1_tpu import rng

_BLOCK = 256


class DrawCache:
    def __init__(self, seed: int):
        self.key = rng.base_key_np(seed)
        self._bits: dict[tuple, np.ndarray] = {}
        self._xf: dict[tuple, np.ndarray] = {}  # transformed-value blocks

    def _bits_block(self, purpose: int, host: int, blk: int) -> np.ndarray:
        k = (purpose, host, blk)
        got = self._bits.get(k)
        if got is None:
            ctrs = np.arange(blk * _BLOCK, (blk + 1) * _BLOCK, dtype=np.int64)
            got = rng.bits_np(self.key, purpose, np.int64(host), ctrs)
            self._bits[k] = got
        return got

    def bits(self, purpose: int, host: int, ctr: int) -> np.uint32:
        return self._bits_block(purpose, host, ctr // _BLOCK)[ctr % _BLOCK]

    def _xf_block(self, tag, purpose, host, ctr, fn) -> np.ndarray:
        """Whole-block transform (one vectorized call per block)."""
        blk = ctr // _BLOCK
        k = (tag, purpose, host, blk)
        got = self._xf.get(k)
        if got is None:
            got = fn(self._bits_block(purpose, host, blk))
            self._xf[k] = got
        return got

    def exponential_ns(self, purpose: int, host: int, ctr: int, mean_ns: float) -> int:
        blk = self._xf_block(
            ("e", mean_ns), purpose, host, ctr,
            lambda b: rng.exponential_ns_np(b, mean_ns),
        )
        return int(blk[ctr % _BLOCK])

    def randint(self, purpose: int, host: int, ctr: int, n: int) -> int:
        blk = self._xf_block(("r", n), purpose, host, ctr, lambda b: rng.randint_np(b, n))
        return int(blk[ctr % _BLOCK])
