"""Bit-exact capacity migration of the engine's SoA planes.

Runs on HOST (plain numpy) at chunk boundaries or checkpoint load — never
inside the jitted window path — and re-shapes the ``[C, H]`` event-buffer
planes / ``[P, H]`` outbox planes to a new static capacity. Every operation
addresses the slot axis as ``axis=-2``, so planes with leading axes migrate
identically: a fleet state's ``[E, C, H]`` planes (fleet transactional
retry / fleet ``--auto-caps``) go through the exact same code path as a
solo ``[C, H]`` state — per lane, the migration is the solo migration:

* **grow**: append free-slot sentinel rows (exactly the ``evbuf_init`` /
  ``outbox_init`` fill values), occupied slots untouched;
* **shrink**: stable-compact each host column's OCCUPIED slots to the front,
  then truncate. Raises if any host holds more events than the new cap —
  the controller only shrinks to ladder steps above the measured high-water,
  so a refusal means the caller's policy is broken, not the data.

Exactness argument: pop order is decided purely by the (time, tb) keys
(core/events.py module docstring) and free-slot CONTENT is never read
(every reader masks on ``kind != K_NONE`` / ``slot < cnt``), so any
permutation of a column's occupied slots plus any free-slot padding is
semantically the identity. Slot ASSIGNMENT of future pushes differs after a
migration (first-free search, delivery rank), but that is an engine-internal
layout detail with no observable effect — the same argument that makes
``deliver_batch``'s layout engine-internal. The one caveat is overflow:
WHICH events drop when a buffer fills is layout-defined, so runs are
bit-exact across migrations only while the overflow counters stay 0 —
the same contract cross-engine parity already lives under
(docs/SEMANTICS.md "Bounds and overflow").
"""

from __future__ import annotations

import numpy as np

from shadow1_tpu.consts import K_NONE

_I64_MAX = np.int64(np.iinfo(np.int64).max)
_I32_FREE = np.int32(np.iinfo(np.int32).max)  # events.I32_FREE


def _tb_split_np(v) -> tuple[np.int32, np.int32]:
    """numpy mirror of core/events.tb_split (order-preserving i64 → i32×2)."""
    hi = np.int32(int(v) >> 32)
    lo_bits = (int(v) & 0xFFFFFFFF) ^ 0x80000000  # sign-flip, as uint bits
    lo = np.int32(lo_bits - (1 << 32) if lo_bits >= (1 << 31) else lo_bits)
    return hi, lo


def _pad_rows(x: np.ndarray, n: int, fill) -> np.ndarray:
    """Append ``n`` slot rows (axis -2) filled with ``fill``."""
    pad_shape = x.shape[:-2] + (n, x.shape[-1])
    return np.concatenate([x, np.full(pad_shape, fill, x.dtype)], axis=-2)


def _expand_order(order: np.ndarray, x: np.ndarray) -> np.ndarray:
    """Broadcast a slot-permutation ``order`` (shape [*lead, C, H]) onto a
    plane ``x`` (shape [*lead, *extra, C, H]) whose extra axes (e.g. the
    payload NP axis) sit between the shared leading axes and the slot axis:
    insert singleton axes there, then broadcast."""
    k = x.ndim - order.ndim
    o = order.reshape(order.shape[:-2] + (1,) * k + order.shape[-2:])
    return np.broadcast_to(o, x.shape)


def resize_evbuf(buf, new_cap: int):
    """EventBuf (numpy leaves) at cap C → the same queue contents at
    ``new_cap``. Returns a new EventBuf; [H]-vector/scalar leaves
    (self_ctr, epoch, n_elig, u32) are capacity-independent and carried
    as-is. Leading axes ([E, C, H] fleet planes) migrate per lane."""
    kind = np.asarray(buf.kind)
    cap = kind.shape[-2]
    new_cap = int(new_cap)
    if new_cap == cap:
        return buf
    planes = {f: np.asarray(getattr(buf, f))
              for f in ("time_hi", "time_lo", "t32", "tb_hi", "tb_lo",
                        "kind", "p")}
    if new_cap < cap:
        occupied = planes["kind"] != K_NONE
        n_occ = occupied.sum(axis=-2).max()
        if n_occ > new_cap:
            raise ValueError(
                f"cannot shrink ev_cap {cap} -> {new_cap}: a host holds "
                f"{int(n_occ)} events"
            )
        # Stable partition: occupied slots first, original slot order kept
        # (argsort of the free flag is stable ⇒ ties keep slot order).
        order = np.argsort(~occupied, axis=-2, kind="stable")
        for f, x in planes.items():
            o = _expand_order(order, x)
            planes[f] = np.take_along_axis(x, o, axis=-2)[..., :new_cap, :]
    else:
        thi, tlo = _tb_split_np(_I64_MAX)
        n = new_cap - cap
        planes["time_hi"] = _pad_rows(planes["time_hi"], n, thi)
        planes["time_lo"] = _pad_rows(planes["time_lo"], n, tlo)
        planes["t32"] = _pad_rows(planes["t32"], n, _I32_FREE)
        for f in ("tb_hi", "tb_lo", "p"):
            planes[f] = _pad_rows(planes[f], n, 0)
        planes["kind"] = _pad_rows(planes["kind"], n, K_NONE)
    return buf._replace(**planes)


def resize_outbox(ob, new_cap: int):
    """Outbox (numpy leaves) at cap P → ``new_cap``. Outbox entries are
    contiguous in [0, cnt) per host (append-only within a window, cleared at
    window end — chunk boundaries always see cnt == 0), so grow pads rows
    and shrink truncates; slots ≥ cnt are never read, so stale content
    beyond the truncation point is immaterial."""
    dst = np.asarray(ob.dst)
    cap = dst.shape[-2]
    new_cap = int(new_cap)
    if new_cap == cap:
        return ob
    if new_cap < cap and int(np.asarray(ob.cnt).max()) > new_cap:
        raise ValueError(
            f"cannot shrink outbox_cap {cap} -> {new_cap}: a host has "
            f"{int(np.asarray(ob.cnt).max())} pending sends"
        )
    planes = {}
    for f in ("dst", "kind", "depart_hi", "depart_lo", "ctr", "p"):
        x = np.asarray(getattr(ob, f))
        planes[f] = (x[..., :new_cap, :] if new_cap < cap
                     else _pad_rows(x, new_cap - cap, 0))
    return ob._replace(**planes)


def resize_state(st, ev_cap: int | None = None, outbox_cap: int | None = None):
    """SimState → SimState with the event buffer / outbox migrated. Leaves
    come back as numpy; callers re-place on device (engine.place_state).
    Metrics, model state, cpu_busy and the telemetry ring are capacity-
    independent and pass through untouched."""
    repl = {}
    if ev_cap is not None and int(ev_cap) != st.evbuf.kind.shape[-2]:
        repl["evbuf"] = resize_evbuf(st.evbuf, ev_cap)
    if outbox_cap is not None and int(outbox_cap) != st.outbox.dst.shape[-2]:
        repl["outbox"] = resize_outbox(st.outbox, outbox_cap)
    return st._replace(**repl) if repl else st
