"""Checkpoint / resume — snapshot the engine state pytree, continue later.

The reference has NO checkpointing (SURVEY §5: impossible with real process
memory in v1.x). Here engine state is a pytree of arrays, so a snapshot is
just the flattened tree serialized to one .npz file; resume loads it back
into the treedef of a freshly-initialized state and continues the window
loop. Determinism makes this exact: a run that checkpoints and resumes
produces bit-identical results to an uninterrupted run (tested in
tests/test_ckpt_obs.py).
"""

from __future__ import annotations

import numpy as np
import jax

# Snapshot format version. Bump whenever the SimState pytree's leaf order,
# count, or layout changes so stale snapshots fail with a clear message
# instead of an opaque shape/KeyError (round-3 advisor finding).
#   1: round 2-3 host-major layout
#   2: round 4 host-minor layout ([C,H]/[S,H]/[NP,C,H] tensors)
#   3: round 5 adds Metrics.x2x_max_fill (exchange occupancy high-water)
#   4: round 5 i32 round path — EventBuf gains t32/epoch, tb splits into
#      (tb_hi, tb_lo) i32 planes (core/events.py)
#   5: round 6 telemetry — SimState gains the optional ``telem`` ring leaf
#      (present only when EngineParams.metrics_ring > 0; a ring-less state
#      keeps the v4 leaf layout, but the format is bumped so a ring/ring-less
#      mismatch fails as a version error, not a confusing leaf-count one)
#   6: capacity autotuning — Metrics gains the ev_max_fill / ob_max_fill /
#      compact_max_fill gauges, and load_state learns CAP MIGRATION: a
#      snapshot whose ev_cap/outbox_cap differs from the engine's restores
#      via tune/resize.py instead of failing the shape check (--auto-caps
#      runs checkpoint at whatever cap they had grown to)
#   7: determinism flight recorder — the telemetry ring row widens by the
#      RING_DIGESTS state-digest columns (telemetry/registry.py), so any
#      snapshot carrying a ring leaf changes shape. No digest STATE rides
#      the snapshot beyond that: digest words are pure functions of the
#      engine state, which is why a resumed run's digest stream continues
#      bit-identically to the uninterrupted one with no extra bookkeeping.
#   8: fault plane — Metrics gains link_down_pkts / host_restarts, the ring
#      row widens by the matching counter columns, and every snapshot now
#      carries an ``integrity`` splitmix64 digest over all leaves:
#      load_state rejects truncated or bit-flipped snapshots with
#      CorruptCheckpointError instead of resuming from garbage, and the
#      supervisor (cli._supervise) discards a corrupt checkpoint like a
#      stale one rather than crash-looping on it.
#   9: fleet mode (shadow1_tpu/fleet/) — a snapshot may now hold a FLEET
#      state: every leaf carries a leading [E] experiment axis (event
#      buffers [E,C,H], metrics [E], rings [E,W,F]). Solo snapshots are
#      unchanged in layout, but the format is bumped so a fleet/solo
#      mixup fails as a version/shape error with this history to point at
#      rather than a confusing leaf-shape one. Per-experiment resume
#      slicing (fleet.engine.slice_experiment) re-saves one lane as a
#      plain solo snapshot.
#  10: performance attribution plane — Metrics gains the wasted-work
#      running sums active_hosts / elig_events / outbox_hosts, and any
#      snapshot carrying a telemetry ring widens its row by the matching
#      RING_WORK delta columns (telemetry/registry.py). Like the digest
#      columns, no extra state rides the snapshot beyond the new leaves:
#      the per-window values are pure boundary samples, so a resumed run's
#      work-gauge stream continues bit-identically.
#  11: flow-probe plane — SimState gains the optional ``probes`` ring leaf
#      ([W,K,F] i64, telemetry/probes.py; fleet: [E,W,K,F]), present only
#      when EngineParams.probes names watched entities AND metrics_ring > 0.
#      A probe-less state keeps the v10 leaf layout; the bump makes a
#      probes-on/probes-off mismatch fail as a version error. Probe rows
#      are pure window-boundary samples, so a resumed run's flow stream
#      continues bit-identically (same rule as the digest/work columns).
#  12: link-telemetry plane — SimState gains the optional ``links``
#      accumulator leaf ([V,V,F] i64, telemetry/links.py; fleet:
#      [E,V,V,F]), present only when EngineParams.link_telem is on. The
#      accumulator holds cumulative per-edge counters and drains as pure
#      running-total snapshots, so a resumed run's link stream continues
#      bit-identically with no baseline bookkeeping. A telemetry-off
#      state keeps the v11 leaf layout; the bump makes an on/off mismatch
#      fail as a version error.
CKPT_FORMAT = 12


class CorruptCheckpointError(ValueError):
    """The snapshot file is damaged (truncated zip, undecodable member, or
    integrity-digest mismatch) — as opposed to a well-formed snapshot of
    the wrong config, which stays a plain ValueError."""


_IM64 = (1 << 64) - 1
_IK = 0x2545F4914F6CDD1D           # the digest fold multiplier (core/digest)
_ISEED = 0xC6A4A7935BD1E995        # distinct seed: file integrity domain


def _integrity_digest(leaves) -> int:
    """Position-sensitive splitmix64 digest of the snapshot payload.

    Per leaf: the raw bytes (u64-padded) are each mixed with their word
    position and xor-reduced; leaf hashes then fold in order with the byte
    length, so any single flipped bit, swapped word, or truncated tail
    changes the digest. numpy-only — the supervisor verifies checkpoints
    host-side without touching an accelerator."""
    from shadow1_tpu.core.digest import _mix_int
    from shadow1_tpu.rng import _mix_np

    z = _ISEED
    for i, a in enumerate(leaves):
        a = np.ascontiguousarray(np.asarray(a))
        b = a.tobytes()
        pad = (-len(b)) % 8
        u = np.frombuffer(b + b"\0" * pad, np.uint64)
        if u.size:
            with np.errstate(over="ignore"):
                pos = np.arange(u.size, dtype=np.uint64)
                w = _mix_np(u + _mix_np(pos * np.uint64(_IK)
                                        + np.uint64(i + 1)))
            h = int(np.bitwise_xor.reduce(w))
        else:
            h = 0
        z = _mix_int((z * _IK + h) & _IM64)
        z = (z * _IK + len(b)) & _IM64
    return _mix_int(z)


def _flatten(st):
    leaves, treedef = jax.tree_util.tree_flatten(st)
    return leaves, treedef


def save_state(st, path: str) -> None:
    """Snapshot a SimState pytree to ``path`` (.npz).

    Write-then-rename: the fault-tolerant runners save while the device may
    be about to wedge the process; a crash mid-write must leave the previous
    snapshot intact, never a truncated zip."""
    import os

    leaves, _ = _flatten(st)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["format"] = np.asarray([CKPT_FORMAT, len(leaves)], np.int64)
    arrays["integrity"] = np.asarray(
        [_integrity_digest(arrays[f"leaf_{i}"] for i in range(len(leaves)))],
        np.uint64,
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp, path)


def load_state(template, path: str, migrate_caps: bool = True):
    """Load a snapshot into the structure of ``template`` (a SimState from
    ``engine.init_state()``) — shapes/dtypes must match the engine config.

    One sanctioned mismatch: with ``migrate_caps`` (default), a snapshot
    saved at a different ``ev_cap``/``outbox_cap`` is migrated to the
    template's caps via tune/resize.py (bit-exact — pop order lives in the
    (time, tb) keys, not slot indices). This is how an ``--auto-caps`` run's
    checkpoints — saved at whatever cap the controller had grown to —
    restore into an engine built from the config's static caps. Every other
    shape/dtype difference still fails as a config mismatch."""
    tleaves, treedef = _flatten(template)
    try:
        with np.load(path) as data:
            fmt = (data["format"] if "format" in data.files
                   else np.asarray([1, -1]))
            n_saved = int(fmt[1])
            saved = [data[f"leaf_{i}"] for i in range(max(n_saved, 0))
                     if f"leaf_{i}" in data.files]
            stored = (int(data["integrity"][0])
                      if "integrity" in data.files else None)
    except Exception as e:  # truncated zip / undecodable member / bad header
        raise CorruptCheckpointError(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e}) — "
            f"truncated or damaged snapshot; discard it and re-run"
        ) from e
    if int(fmt[0]) != CKPT_FORMAT:
        raise ValueError(
            f"checkpoint {path} has format v{int(fmt[0])}, this build "
            f"reads v{CKPT_FORMAT} — snapshot from an incompatible "
            f"framework version; re-run from scratch"
        )
    if stored is None or len(saved) != n_saved:
        raise CorruptCheckpointError(
            f"checkpoint {path} is missing state members "
            f"({len(saved)}/{n_saved} leaves, integrity "
            f"{'present' if stored is not None else 'absent'}) — truncated "
            f"snapshot; discard it and re-run"
        )
    if _integrity_digest(saved) != stored:
        raise CorruptCheckpointError(
            f"checkpoint {path} fails its integrity digest — the snapshot "
            f"was bit-corrupted after writing; discard it and re-run"
        )
    if n_saved != len(tleaves):
        raise ValueError(
            f"checkpoint {path} holds {n_saved} state leaves, engine "
            f"expects {len(tleaves)} — engine config mismatch"
        )
    leaves = saved
    if migrate_caps:
        # Structure (leaf count) already matched, so the saved leaves
        # unflatten into a SimState whose planes carry the SAVED caps;
        # migrate the event buffer / outbox onto the template's caps before
        # the strict per-leaf validation below.
        st = jax.tree_util.tree_unflatten(treedef, leaves)
        ev_cap = np.asarray(template.evbuf.kind).shape[-2]
        ob_cap = np.asarray(template.outbox.dst).shape[-2]
        if (np.asarray(st.evbuf.kind).shape[-2] != ev_cap
                or np.asarray(st.outbox.dst).shape[-2] != ob_cap):
            from shadow1_tpu.tune.resize import resize_state

            try:
                st = resize_state(st, ev_cap=ev_cap, outbox_cap=ob_cap)
            except ValueError as e:
                raise ValueError(
                    f"checkpoint {path} cannot migrate onto this engine's "
                    f"caps ({e}) — rebuild the engine at the snapshot's caps "
                    f"(ckpt.snapshot_caps) or resume with --auto-caps, which "
                    f"does this automatically"
                ) from e
            leaves = jax.tree_util.tree_leaves(st)
    for i, (have, want) in enumerate(zip(leaves, tleaves)):
        have = np.asarray(have)
        w = np.asarray(want)
        if have.shape != w.shape or have.dtype != w.dtype:
            raise ValueError(
                f"checkpoint leaf {i}: {have.shape}/{have.dtype} != "
                f"engine state {w.shape}/{w.dtype} — config mismatch"
            )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def verify_file(path: str) -> tuple[bool, str | None]:
    """Host-side snapshot health check: (ok, reason-if-not).

    Reads the file with numpy only (no engine, no accelerator) and checks
    the member set plus the integrity digest — the supervisor runs this
    BEFORE spawning a child on a leftover checkpoint, so a bit-corrupted
    snapshot is discarded like a stale one instead of crash-looping the
    respawn budget away (cli._supervise)."""
    try:
        with np.load(path) as data:
            if "format" not in data.files:
                return False, "no format member"
            n = int(data["format"][1])
            if "integrity" not in data.files:
                return False, "no integrity digest (pre-v8 or truncated)"
            stored = int(data["integrity"][0])
            leaves = []
            for i in range(n):
                if f"leaf_{i}" not in data.files:
                    return False, f"missing leaf_{i} of {n}"
                leaves.append(data[f"leaf_{i}"])
    except Exception as e:
        return False, f"unreadable ({type(e).__name__}: {e})"
    if _integrity_digest(leaves) != stored:
        return False, "integrity digest mismatch (bit corruption)"
    return True, None


def snapshot_caps(template, path: str) -> tuple[int, int] | None:
    """(ev_cap, outbox_cap) a snapshot was SAVED at, read off its leaf
    shapes without loading the full state. An ``--auto-caps`` run
    checkpoints at whatever cap the controller had grown to — possibly
    holding more events per host than the config's static cap can — so a
    supervised respawn must rebuild its engine at the snapshot's caps
    before resuming (cli.py does this; a shrink-on-load that would drop
    events refuses instead). Returns None when the snapshot's leaf layout
    doesn't match ``template`` (the format checks in load_state will say
    why)."""
    leaves = jax.tree_util.tree_leaves(template)

    def idx(leaf):
        for i, l in enumerate(leaves):
            if l is leaf:
                return i
        return None

    i_ev = idx(template.evbuf.kind)
    i_ob = idx(template.outbox.dst)
    try:
        with np.load(path) as data:
            for i in (i_ev, i_ob):
                if i is None or f"leaf_{i}" not in data.files:
                    return None
            ev, ob = data[f"leaf_{i_ev}"].shape, data[f"leaf_{i_ob}"].shape
    except Exception as e:  # truncated zip / undecodable member
        raise CorruptCheckpointError(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e}) — "
            f"truncated or damaged snapshot; discard it and re-run"
        ) from e
    # Slot axis is axis=-2 on solo ([C, H]) and fleet ([E, C, H]) planes
    # alike (the tune/resize.py convention).
    if len(ev) < 2 or len(ob) < 2:
        return None
    return int(ev[-2]), int(ob[-2])


def run_chunked(engine, st=None, n_windows: int | None = None,
                chunk: int = 0, on_chunk=None, profiler=None, retune=None,
                guard=None, selfcheck: bool = False, drain=None):
    """Run in fixed-size window chunks, invoking ``on_chunk(st, done)`` after
    each (for checkpoints/heartbeats). One compiled program is reused for
    every full chunk. Returns the final state.

    ``profiler`` (telemetry.PhaseProfiler) records one ``run-chunk`` span
    per chunk — the dominant phase every trace wants resolved.

    ``retune(engine, st) -> (engine, st)`` is the between-chunk adaptation
    hook (tune/autocap.CapController): it may hand back a DIFFERENT engine
    (re-jitted at new static capacities) with the state migrated to match.
    Called after ``on_chunk`` so heartbeats/checkpoints see the state that
    actually ran the chunk; never called after the final chunk.

    ``guard`` (txn.OverflowGuard — CLI ``--on-overflow retry|halt``) makes
    chunk execution TRANSACTIONAL: the chunk-start state is kept as the
    rollback point, and the guard's commit either accepts the chunk (no
    fresh overflow), discards it and replays at grown caps, or raises a
    structured CapacityExceededError. Commit runs BEFORE ``on_chunk``, so
    heartbeats and checkpoints only ever see committed (overflow-free)
    states — a checkpoint can never capture a tainted chunk. Without a
    guard (the default ``drop`` policy) no state is retained and no extra
    host sync is paid.

    ``selfcheck`` (CLI ``--selfcheck``) verifies the drop-accounting
    identity on every committed chunk boundary (txn.SelfCheckError on
    violation) — churnprobe's probe-only invariant, guarding every run.

    ``drain`` (preempt.DrainHandler) is the signal plane: when a
    SIGTERM/SIGINT has requested a drain, the loop finishes the in-flight
    chunk, commits it, lets ``on_chunk`` run (which forces the final
    snapshot when the run carries a checkpoint path) and raises
    preempt.PreemptedExit — checked only at chunk boundaries, never inside
    a window (a window is the atomic unit of the determinism contract)."""
    from shadow1_tpu.telemetry import PH_INIT, PH_RUN_CHUNK, maybe_span

    if st is None:
        with maybe_span(profiler, PH_INIT):
            st = engine.init_state()
    if guard is not None:
        guard.bind(engine, st)
    total = n_windows if n_windows is not None else engine.n_windows
    if chunk <= 0:
        chunk = total
    done = 0
    while done < total:
        step = min(chunk, total - done)
        # Rollback point: jax states are immutable and run() never donates,
        # so holding the reference is free until the commit drops it.
        st0 = st if guard is not None else None
        with maybe_span(profiler, PH_RUN_CHUNK, windows=step, done=done):
            # Under a guard the sharded engine's eager x2x safety net
            # stands down (guard.run_guarded passes check_x2x=False) — the
            # commit below owns the overflow response.
            st = (guard.run_guarded(engine, st, step) if guard is not None
                  else engine.run(st, n_windows=step))
            if profiler is not None:
                # Only when tracing: make the span cover execution, not just
                # async dispatch. Chunk boundary — never inside a window.
                jax.block_until_ready(st)
        if guard is not None:
            engine, st = guard.commit(engine, st0, st, done, step)
        done += step
        if selfcheck:
            from shadow1_tpu.txn import check_boundary_identity

            check_boundary_identity(
                type(engine).metrics_dict(st),
                where=f"chunk boundary, window {int(st.metrics.windows)}")
        # Sample the drain latch BEFORE on_chunk: on_chunk's forced-save
        # check can only see the latch as MORE set than this sample, so
        # whenever we raise below, the final snapshot was already forced —
        # a signal landing mid-on_chunk is honored one boundary later,
        # never honored without its snapshot.
        draining = drain is not None and drain.requested and done < total
        if on_chunk is not None:
            on_chunk(st, done)
        if draining:
            from shadow1_tpu.preempt import PreemptedExit

            raise PreemptedExit(
                st=st, signame=drain.signame, done_windows=done,
                win_start=int(np.asarray(st.win_start).max()))
        if retune is not None and done < total:
            engine, st = retune(engine, st)
            if guard is not None:
                guard.engine = engine
    return st
