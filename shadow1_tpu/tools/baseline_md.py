"""Render benchmark-ladder JSON rows into BASELINE.md's results table.

    python -m shadow1_tpu.tools.baseline_md LADDER_r03.json [...more.json]

Reads the row files produced by ``bench_ladder.py --json`` and prints a
markdown table (newest measurement per rung wins). Paste-ready for
BASELINE.md; keeping the renderer in-repo makes each round's refresh one
command instead of hand-edited numbers (SURVEY §6a: the ladder is the
measured baseline this repo produces for itself).
"""

from __future__ import annotations

import json
import sys


def load_rows(paths: list[str]) -> dict[str, dict]:
    rows: dict[str, dict] = {}
    for p in paths:
        with open(p) as f:
            for row in json.load(f):
                rows[row["rung"]] = row  # later files win
    return rows


def fmt(row: dict) -> str:
    if "error" in row:
        return (f"| {row['rung']} | — | — | — | — | — | — | — | "
                f"FAILED: `{row['error'][:60]}` |")
    win = f"{row['windows']}/{row['windows_configured']}"
    if row.get("status") == "done":
        win = str(row["windows"])
    over = row["ev_overflow"] + row["ob_overflow"]
    note = []
    if row.get("status") == "budget":
        note.append("budget-capped")
    if row.get("status") == "fault":
        note.append("GAVE UP on device faults (partial)")
    if row.get("process_respawns"):
        note.append(f"{row['process_respawns']} fault-resumes")
    if row.get("round_cap_hits"):
        note.append(f"{row['round_cap_hits']} round-cap hits")
    if row.get("oracle_events_per_sec"):
        note.append(f"oracle {row['oracle_events_per_sec']:,.0f} ev/s"
                    f" on {row['oracle_windows']} win")
    eps = row.get("events_per_sec")
    spw = row.get("sim_per_wall")
    return (
        f"| {row['rung']} | {row['n_hosts']:,} | {win} "
        f"| {row['events']:,} "
        f"| {'**' + format(eps, ',.0f') + '**' if eps is not None else '—'} "
        f"| {format(spw, '.3f') if spw is not None else '—'} "
        f"| {row['wall_s']:.0f} + "
        f"{row['compile_s']:.0f}c | {over} | {'; '.join(note) or '—'} |"
    )


def main() -> None:
    rows = load_rows(sys.argv[1:])
    # Provenance comes from the rows (stamped by bench_ladder at measurement
    # time); rendering later must not claim the current HEAD.
    commits = sorted({r.get("commit", "?") for r in rows.values()})
    print(f"Measured on the single axon TPU v5 lite chip, "
          f"commit(s) {', '.join(commits)}; "
          f"walls in seconds, compile excluded ('+ Nc' column).")
    print()
    print("| rung | hosts | windows | events | events/s | sim/wall "
          "| wall + compile | overflow | notes |")
    print("|---|---|---|---|---|---|---|---|---|")
    for name in sorted(rows):
        print(fmt(rows[name]))


if __name__ == "__main__":
    main()
