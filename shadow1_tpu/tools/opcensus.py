"""Op/fusion census of the window program — a tool AND a CI gate.

    python -m shadow1_tpu.tools.opcensus                    # gate vs OPCENSUS.json
    python -m shadow1_tpu.tools.opcensus --update           # re-baseline
    python -m shadow1_tpu.tools.opcensus configs/rung3_tor1k.yaml --sources

The performance attribution plane's static half (the wall-clock half is
tools/phaseprobe.py). The round cost of the sparse rungs is OP-COUNT bound
after fusion (docs/PERF.md round-5: 12.3k deliver-pass jaxpr eqns → ~1.3k
fusion kernels × fixed kernel cost), so the traced-eqn count per phase is
the earliest possible warning for ROADMAP item 1's kernel work: a handler
rewrite that doubles a pass's op count shows up here at trace time, before
any benchmark moves. This automates the round-5 manual census:

* **eqn census** — every window phase (core/engine.window_phases: prepare /
  rounds / deliver / telem), every handler pass (h_<kind>), the pop chain
  and the whole round body are traced to jaxprs and their equations counted
  RECURSIVELY (sub-jaxprs of cond/while/scan/pjit included). Tracing is
  deterministic: two runs produce identical counts.
* **source table** (``--sources``) — eqns grouped by the deepest user frame
  (``file.function``), reproducing the round-5 deliver-pass breakdown
  (tcp_flush / dense.get_col / events.push_local / ...) mechanically
  instead of by hand.
* **fusion census** (``--fusion``) — the phase programs are compiled and
  the fusion-kernel instructions counted from the optimized HLO: the
  post-XLA number the per-round fixed cost actually scales with. Backend-
  dependent, so the baseline records which backend counted it (eqn counts
  are backend-independent and are what the gate enforces).
* **drift gate** — without flags, measured eqn counts compare against the
  committed ``OPCENSUS.json``: any phase drifting more than ``tolerance``
  (default 10%) fails CI (exit 1), same shape as tools/benchgate.py.
  Intentional change? override once with ``SHADOW1_OPCENSUS_ACCEPT="why"``
  and re-baseline with ``--update``.
* ``--inject N`` — self-test hook: N extra arithmetic eqns traced into the
  ``rounds`` phase, so ci.sh can assert the gate actually trips.

Always prints one JSON line on stdout (the bench.py contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

BASELINE = os.path.join(os.path.dirname(__file__), "..", "..",
                        "OPCENSUS.json")
TOLERANCE = 0.10
ACCEPT_ENV = "SHADOW1_OPCENSUS_ACCEPT"

# The gated config set: the benchgate dense-phold shape plus the rung-1
# net/TCP config — tiny to build, but between them they trace every handler
# pass, the NIC arrival batch and the TCP flush machine.
DEFAULT_CONFIGS = ("smoke", "configs/rung1_filexfer.yaml")


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _sub_jaxprs(v):
    """Yield every Jaxpr nested in an eqn param value (pjit/cond/while/scan
    bodies, custom-call jaxprs, lists thereof)."""
    from jax import core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jcore.Jaxpr):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _sub_jaxprs(x)


def iter_eqns(jaxpr):
    """Every equation of ``jaxpr``, sub-jaxprs included (recursive)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _source_label(eqn) -> str:
    """``file.function`` of the deepest user frame that created the eqn —
    the round-5 census's grouping (dense.get_col, events.push_local, ...)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return "(no source)"
        base = os.path.basename(frame.file_name)
        if base.endswith(".py"):
            base = base[:-3]
        if base == "__init__":
            base = os.path.basename(os.path.dirname(frame.file_name))
        return f"{base}.{frame.function_name}"
    except Exception:
        return "(no source)"


def count_eqns(fn, *args, sources: bool = False):
    """(total_eqns, by_source|None) of ``fn`` traced at ``args``' shapes."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    total = 0
    by_src: dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        total += 1
        if sources:
            lbl = _source_label(eqn)
            by_src[lbl] = by_src.get(lbl, 0) + 1
    if not sources:
        return total, None
    return total, dict(sorted(by_src.items(), key=lambda kv: -kv[1]))


def count_fusions(fn, *args) -> dict:
    """Compiled-HLO kernel census of ``fn``: fusion instructions plus total
    top-level instructions (the launch count the fixed per-kernel cost
    multiplies). Backend-dependent — report with the backend name."""
    import re

    import jax

    text = jax.jit(fn).lower(*args).compile().as_text()
    return {
        "fusions": len(re.findall(r"= \S+ fusion\(", text)),
        "instructions": sum(
            1 for line in text.splitlines()
            if re.match(r"\s+(ROOT\s+)?%?\S+ = ", line)
        ),
    }


# ---------------------------------------------------------------------------
# the census
# ---------------------------------------------------------------------------

def _inject_eqns(fn, n: int):
    """Trace ``n`` extra add eqns into ``fn`` (drift-gate self-test)."""
    if not n:
        return fn

    def wrapped(fr):
        fr = fn(fr)
        x = fr.dg_ob
        for _ in range(n - 1):
            x = x + 1
        return fr._replace(dg_ob=x - (n - 1))

    return wrapped


def census(eng, sources: bool = False, fusion: bool = False,
           inject: int = 0) -> dict:
    """The per-config census dict: ``eqns`` per phase/handler pass (the
    gated, backend-independent numbers), optional ``sources`` breakdown per
    pass and ``fusions`` per window phase."""
    import jax
    import jax.numpy as jnp

    from shadow1_tpu.consts import KIND_NAMES, NP
    from shadow1_tpu.core.engine import (
        Popped,
        run_round,
        window_frame,
        window_phases,
    )
    from shadow1_tpu.core.events import pop_until, push_impl_ctx

    ctx, handlers = eng.ctx, eng._handlers
    st = eng.init_state()
    fr = window_frame(st, ctx)
    h = ctx.n_hosts
    win_end = st.win_start + ctx.window
    ev = Popped(
        mask=jnp.ones(h, bool),
        time=jnp.zeros(h, jnp.int64),
        kind=jnp.zeros(h, jnp.int32),
        p=jnp.zeros((NP, h), jnp.int32),
        tb=jnp.zeros(h, jnp.int64),
    )
    eqns: dict[str, int] = {}
    srcs: dict[str, dict] = {}
    fus: dict[str, dict] = {}
    phases = window_phases(ctx, handlers, None, eng._pre_window,
                           eng._model.make_handlers, None)
    for name, fn in phases:
        if name == "rounds":
            fn = _inject_eqns(fn, inject)
        eqns[name], by = count_eqns(fn, fr, sources=sources)
        if sources:
            srcs[name] = by
        if fusion:
            fus[name] = count_fusions(fn, fr)

    def in_push_scope(f):
        def g(*a):
            with push_impl_ctx(ctx.params.push_impl):
                return f(*a)

        return g

    for kind, hfn in sorted(handlers.items()):
        name = f"h_{KIND_NAMES.get(kind, kind)}"
        eqns[name], by = count_eqns(in_push_scope(hfn), st, ev,
                                    sources=sources)
        if sources:
            srcs[name] = by
    eqns["pop"], _ = count_eqns(
        lambda b: pop_until(b, win_end, extract=ctx.params.pop_extract),
        st.evbuf,
    )
    eqns["round"], _ = count_eqns(
        in_push_scope(lambda s: run_round(s, ctx, handlers, win_end)), st,
    )
    out: dict = {"eqns": eqns}
    if sources:
        out["sources"] = srcs
    if fusion:
        out["fusions"] = fus
        out["fusion_backend"] = jax.default_backend()
    return out


def run_census(config: str, sources=False, fusion=False, inject=0):
    """(label, census dict) for "smoke" or a YAML config path."""
    from shadow1_tpu.tools.phaseprobe import build_engine

    eng, label = build_engine(config)
    if label.endswith(".yaml"):
        label = label[:-5]
    return label, census(eng, sources=sources, fusion=fusion, inject=inject)


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def gate_config(measured: dict, base: dict, tol: float) -> list[str]:
    """Failure strings (empty = pass) comparing one config's measured
    ``eqns`` against the baseline's. Both directions are enforced: a phase
    that grew, shrank, appeared or vanished without a baseline update is
    drift — shrinkage is great news, but the baseline must say so."""
    fails = []
    b = base.get("eqns", {})
    m = measured.get("eqns", {})
    for phase, ref in b.items():
        if phase not in m:
            fails.append(f"phase {phase!r} vanished (baseline {ref} eqns)")
            continue
        if ref and abs(m[phase] - ref) / ref > tol:
            pct = 100 * (m[phase] - ref) / ref
            fails.append(f"phase {phase!r}: {m[phase]} eqns vs baseline "
                         f"{ref} ({pct:+.1f}% > ±{tol * 100:.0f}%)")
    for phase in m:
        if phase not in b:
            fails.append(f"new phase {phase!r} ({m[phase]} eqns) not in "
                         f"baseline")
    return fails


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.opcensus")
    ap.add_argument("configs", nargs="*", default=list(DEFAULT_CONFIGS),
                    help='YAML config paths and/or "smoke" (default: the '
                         "gated set)")
    ap.add_argument("--update", action="store_true",
                    help="write the measured census as the committed "
                         "baseline (OPCENSUS.json)")
    ap.add_argument("--baseline", default=BASELINE, help=argparse.SUPPRESS)
    ap.add_argument("--sources", action="store_true",
                    help="per-pass source breakdown (file.function) — the "
                         "round-5 census table, mechanically")
    ap.add_argument("--fusion", action="store_true",
                    help="also compile the window phases and count fusion "
                         "kernels (backend-dependent; slow for big configs)")
    ap.add_argument("--inject", type=int, default=0, metavar="N",
                    help="trace N extra eqns into the rounds phase "
                         "(drift-gate self-test)")
    ap.add_argument("--md", action="store_true",
                    help="print source tables as markdown (docs format)")
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)

    measured: dict[str, dict] = {}
    for cfg in args.configs:
        label, c = run_census(cfg, sources=args.sources, fusion=args.fusion,
                              inject=args.inject)
        measured[label] = c
        if args.sources:
            for pname, by in c.get("sources", {}).items():
                hdr = f"== {label} {pname}: {c['eqns'][pname]} eqns =="
                print(hdr, file=sys.stderr)
                rows = [(s, n) for s, n in by.items()]
                if args.md:
                    print("| source | eqns |\n|---|---|", file=sys.stderr)
                    for s, n in rows:
                        print(f"| {s} | {n} |", file=sys.stderr)
                else:
                    for s, n in rows:
                        print(f"  {s}: {n}", file=sys.stderr)
    if args.update:
        base = {
            "tolerance": TOLERANCE,
            "configs": {k: {"eqns": v["eqns"],
                            **({"fusions": v["fusions"],
                                "fusion_backend": v["fusion_backend"]}
                               if "fusions" in v else {})}
                        for k, v in measured.items()},
            "note": "opcensus baseline — ci.sh fails when any phase's "
                    "traced eqn count drifts beyond tolerance; override "
                    f"once with {ACCEPT_ENV}, then re-baseline with "
                    "--update",
        }
        with open(args.baseline, "w") as f:
            json.dump(base, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps({"census": measured, "gate": "updated",
                          "baseline": args.baseline}))
        return 0
    try:
        with open(args.baseline) as f:
            base = json.load(f)
    except OSError:
        print(json.dumps({"census": measured, "gate": "no_baseline",
                          "hint": "commit one with --update"}))
        return 0
    tol = float(base.get("tolerance", TOLERANCE))
    fails: dict[str, list] = {}
    for label, c in measured.items():
        bcfg = base.get("configs", {}).get(label)
        if bcfg is None:
            continue  # un-gated config (explicit census run)
        f = gate_config(c, bcfg, tol)
        if f:
            fails[label] = f
    verdict = {"census": measured, "tolerance": tol}
    if fails:
        accept = os.environ.get(ACCEPT_ENV)
        for label, msgs in fails.items():
            for msg in msgs:
                print(f"[opcensus] {label}: {msg}", file=sys.stderr,
                      flush=True)
        if accept:
            print(f"[opcensus] DRIFT ACCEPTED ({accept}) — commit the new "
                  f"baseline: python -m shadow1_tpu.tools.opcensus --update",
                  file=sys.stderr, flush=True)
            print(json.dumps({**verdict, "gate": "accepted",
                              "reason": accept, "fails": fails}))
            return 0
        print(f"[opcensus] OP-COUNT DRIFT: the traced window program "
              f"changed size beyond ±{tol * 100:.0f}%. If intentional, "
              f"override once: {ACCEPT_ENV}='why' — then re-baseline with "
              f"--update.", file=sys.stderr, flush=True)
        print(json.dumps({**verdict, "gate": "failed", "fails": fails}))
        return 1
    print(json.dumps({**verdict, "gate": "ok"}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
