"""tor — circuit-layer Tor model over the virtual TCP stack (BASELINE 3/4).

The model-application analogue of the reference's Tor plugin
(shadow-plugin-tor, SURVEY §2.4/§7.1: "Tor = circuit-layer message model:
client builds circuits over relays, fixed-size cells, per-hop queueing").
What is modeled:

* bootstrap — each client fetches a consensus document from a dirauth over
  TCP before building circuits (the dirauth role of rung 4);
* weighted path selection — guard/middle/exit drawn ∝ consensus bandwidth
  weight from the configured relay sets (real Tor's bandwidth-weighted
  sampling), via shared counter-based draws;
* telescoping circuit build — CREATE/CREATED, EXTEND/EXTENDED relayed
  through the partial circuit; relays open (or reuse) onward TCP conns on
  demand and multiplex circuits over them with per-conn circuit ids, the
  real link-protocol shape;
* streams — BEGIN to the exit, a cell-stream reply (one message of
  n_cells × 512 B), END; client thinks, then next stream/circuit.

Cells are 512-byte message boundaries on TCP (meta = circ<<18|aux<<4|cmd);
all loss/retransmit/queueing rides the virtual TCP machinery. Deliberate
model simplifications (docs/SEMANTICS.md): no DESTROY (circuits persist;
table capacity `ct_cap` must cover all circuits built), DATA streams are
store-and-forwarded per hop as whole messages (no circuit-level sendme flow
control yet), one circuit at a time per client.

Fan-out (dialing, cell sends, pending-CREATE drains) is expressed as
self-scheduled events so the traced round body instantiates the TCP send
path once (see apps/bitcoin.py note). The OP_TX_CELL site admission-checks
send-buffer space and a free message-boundary slot and retries next window
otherwise, so a congested conn defers cells instead of losing framing.

model_cfg:
  role           i32 [H]: 0=relay 1=client 2=dirauth 3=idle
  relay_weight   i64 [H] consensus weight (>0 for relays; Σ < 2^31)
  is_guard       bool [H], is_exit: bool [H] (subsets of relays)
  n_circuits     i32 [H] circuits per client (sequential)
  n_streams      i32 [H] streams per circuit (sequential)
  mean_stream_cells  f [H] mean cells per stream (exp, clip [1, cells_max])
  mean_think_ns  f [H]
  start_time     i64 [H]
  consensus_bytes  int (default 2048)
  cells_max      int (default 120; 120·512 B ≪ sndbuf)
  ct_cap         int (default 64) circuit-table slots per relay
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from shadow1_tpu import rng
from shadow1_tpu.consts import (
    K_APP,
    N_ESTABLISHED,
    N_MSG,
    N_PEER_FIN,
    R_TOR_PATH,
    TCP_ESTABLISHED,
    TCP_FREE,
    TCP_LISTEN,
)
from shadow1_tpu.core.dense import add_col, first_true_idx, get_col, set_col
from shadow1_tpu.core.engine import push_local_event
from shadow1_tpu.core.events import push_local
from shadow1_tpu.consts import NP as NPCOLS
from shadow1_tpu.tcp import tcp as T

CELL = 512

# meta = circ<<18 | aux<<4 | cmd  (circ ≤ 8191, aux ≤ 16383, cmd ≤ 15)
C_CREATE = 1
C_CREATED = 2
C_EXTEND = 3
C_EXTENDED = 4
C_BEGIN = 5
C_DATA = 6
C_END = 7
C_DIRREQ = 8
C_DIRRESP = 9

# K_APP opcodes
OP_START = 1
OP_TX_CELL = 2        # p1=sock p2=meta p3=nbytes
OP_CONNECT_RELAY = 3  # p1=sock p2=peer relay id
OP_DRAIN = 4          # p1=sock
OP_THINK = 5

# Client bootstrap/circuit states
CL_IDLE = 0
CL_DIR_CONN = 1
CL_DIR_FETCH = 2
CL_GUARD_CONN = 3
CL_BUILDING = 4
CL_STREAM = 5
CL_DONE = 7


def _meta(circ, aux, cmd):
    return (jnp.asarray(circ, jnp.int32) << 18) | (jnp.asarray(aux, jnp.int32) << 4) | cmd


def _decode(meta):
    return meta >> 18, (meta >> 4) & 0x3FFF, meta & 0xF


def tables(cfg) -> dict:
    """Static path-selection tables from the config (memoized; numpy).

    The equivalent of the consensus the reference's dirauths serve: member
    id lists + cumulative bandwidth weights for guard/middle/exit sampling.
    Kept out of engine state — they are compile-time constants.
    """
    t = cfg.get("_tor_tables")
    if t is None:
        role = np.asarray(cfg["role"], np.int32)
        weight = np.asarray(cfg["relay_weight"], np.int64)
        is_relay = role == 0

        def cum_ids(member):
            ids = np.nonzero(member)[0].astype(np.int32)
            w = weight[ids]
            assert len(ids) > 0 and (w > 0).all()
            cum = np.cumsum(w)
            assert cum[-1] < 2**31, "total weight must fit i31 for exact randint"
            return ids, cum

        g_ids, g_cum = cum_ids(is_relay & np.asarray(cfg["is_guard"], bool))
        e_ids, e_cum = cum_ids(is_relay & np.asarray(cfg["is_exit"], bool))
        r_ids, r_cum = cum_ids(is_relay)
        dir_ids = np.nonzero(role == 2)[0].astype(np.int32)
        assert len(dir_ids) > 0, "need at least one dirauth"
        t = cfg["_tor_tables"] = {
            "guard_ids": g_ids, "guard_cum": g_cum,
            "exit_ids": e_ids, "exit_cum": e_cum,
            "relay_ids": r_ids, "relay_cum": r_cum,
            "dir_ids": dir_ids,
        }
    return t


def init(ctx, evbuf, tcpd):
    cfg = ctx.model_cfg
    tables(cfg)  # validate config early
    role = np.asarray(cfg["role"], np.int32)
    h = ctx.n_hosts
    s = ctx.params.sockets_per_host
    ct = int(cfg.get("ct_cap", 64))
    app = {
        # Per-host config columns live in app state (NOT read from
        # ctx.model_cfg inside handlers) so they shard with the host axis —
        # a handler reading a global [n_total] cfg array inside the
        # shard-local block is a trace-time shape error (round-1 advisor
        # finding; same pattern as apps/tgen.py).
        "role": jnp.asarray(cfg["role"], jnp.int32),
        "cfg_n_streams": jnp.asarray(cfg["n_streams"], jnp.int32),
        "cfg_mean_cells": jnp.asarray(cfg["mean_stream_cells"], jnp.float32),
        "cfg_mean_think": jnp.asarray(cfg["mean_think_ns"], jnp.float32),
        # client
        "cl_state": jnp.zeros(h, jnp.int32),
        "cl_guard": jnp.full(h, -1, jnp.int32),
        "cl_circ": jnp.zeros(h, jnp.int32),
        "cl_hop": jnp.zeros(h, jnp.int32),
        "cl_mid": jnp.zeros(h, jnp.int32),
        "cl_exit": jnp.zeros(h, jnp.int32),
        "cl_circs_left": jnp.asarray(cfg["n_circuits"], jnp.int32),
        "cl_streams_left": jnp.zeros(h, jnp.int32),
        "cl_cells_want": jnp.zeros(h, jnp.int32),
        "ctr": jnp.zeros(h, jnp.int64),
        "streams_done": jnp.zeros(h, jnp.int32),
        "cells_rx": jnp.zeros(h, jnp.int64),
        "bootstrap_time": jnp.zeros(h, jnp.int64),
        "done_time": jnp.zeros(h, jnp.int64),
        # relay link conns + circuit table
        "rc_peer": jnp.full((s, h), -1, jnp.int32),
        "rc_next_circ": jnp.ones((s, h), jnp.int32),
        "ct_used": jnp.zeros((ct, h), bool),
        "ct_in_sock": jnp.zeros((ct, h), jnp.int32),
        "ct_in_circ": jnp.zeros((ct, h), jnp.int32),
        "ct_out_sock": jnp.full((ct, h), -1, jnp.int32),
        "ct_out_circ": jnp.zeros((ct, h), jnp.int32),
        "ct_pend": jnp.zeros((ct, h), bool),
        "cells_fwd": jnp.zeros(h, jnp.int64),
        "ct_overflow": jnp.zeros(h, jnp.int64),
        "cell_retries": jnp.zeros(h, jnp.int64),
    }
    tcpd = dict(tcpd)
    listeners = (role == 0) | (role == 2)
    tcpd["st"] = tcpd["st"].at[0].set(
        jnp.where(jnp.asarray(listeners), TCP_LISTEN, tcpd["st"][0])
    )
    starts = (role == 1) & (np.asarray(cfg["n_circuits"]) > 0)
    p = jnp.zeros((NPCOLS, h), jnp.int32).at[0].set(OP_START)
    kk = jnp.full(h, K_APP, jnp.int32)
    evbuf, over = push_local(
        evbuf, jnp.asarray(starts), jnp.asarray(cfg["start_time"], jnp.int64), kk, p
    )
    return app, evbuf, over.sum(dtype=jnp.int64), tcpd


# -- draws -----------------------------------------------------------------
def _draw_bits(ctx, app, mask):
    """One u32 per host from the host's R_TOR_PATH stream; advances ctr
    where ``mask``."""
    bits = rng.bits_v(ctx.key, R_TOR_PATH, ctx.hosts, app["ctr"])
    app["ctr"] = app["ctr"] + mask.astype(jnp.int64)
    return bits


def _pick_weighted(bits, ids, cum):
    """Bandwidth-weighted relay pick: u ∈ [0, Σw) via multiply-shift, then
    first cumulative bucket exceeding u (identical ints in both engines)."""
    u = rng.randint(bits, int(cum[-1]))
    idx = jnp.searchsorted(jnp.asarray(cum), u.astype(jnp.int64), side="right")
    jids = jnp.asarray(ids)
    return jids[jnp.minimum(idx, jids.shape[0] - 1)]


def _push_cell(st, ctx, mask, sock, meta, nbytes, now):
    return push_local_event(
        st, ctx, mask, now, K_APP, p0=OP_TX_CELL, p1=sock, p2=meta, p3=nbytes
    )


# -- client steps ----------------------------------------------------------
def _client_begin_circuit(st, ctx, mask, now):
    """Draw middle+exit, CREATE on the guard conn (sock 1)."""
    t = tables(ctx.model_cfg)
    app = dict(st.model.app)
    mid = _pick_weighted(_draw_bits(ctx, app, mask), t["relay_ids"], t["relay_cum"])
    ext = _pick_weighted(_draw_bits(ctx, app, mask), t["exit_ids"], t["exit_cum"])
    circ = app["cl_circ"] + 1
    app["cl_circ"] = jnp.where(mask, circ, app["cl_circ"])
    app["cl_mid"] = jnp.where(mask, mid, app["cl_mid"])
    app["cl_exit"] = jnp.where(mask, ext, app["cl_exit"])
    app["cl_hop"] = jnp.where(mask, 1, app["cl_hop"])
    app["cl_state"] = jnp.where(mask, CL_BUILDING, app["cl_state"])
    app["cl_streams_left"] = jnp.where(
        mask, app["cfg_n_streams"], app["cl_streams_left"]
    )
    st = st._replace(model=st.model._replace(app=app))
    one = jnp.ones(ctx.n_hosts, jnp.int32)
    return _push_cell(st, ctx, mask, one, _meta(circ, 0, C_CREATE), CELL, now)


def _client_begin_stream(st, ctx, mask, now):
    """Draw the stream size and BEGIN it on the current circuit."""
    cells_max = int(ctx.model_cfg.get("cells_max", 120))
    app = dict(st.model.app)
    want = jnp.clip(
        rng.exponential_ns(_draw_bits(ctx, app, mask), app["cfg_mean_cells"]),
        1, cells_max,
    ).astype(jnp.int32)
    app["cl_cells_want"] = jnp.where(mask, want, app["cl_cells_want"])
    app["cl_state"] = jnp.where(mask, CL_STREAM, app["cl_state"])
    circ = app["cl_circ"]
    st = st._replace(model=st.model._replace(app=app))
    one = jnp.ones(ctx.n_hosts, jnp.int32)
    return _push_cell(st, ctx, mask, one, _meta(circ, want, C_BEGIN), CELL, now)


def _client_think(st, ctx, mask, now):
    app = dict(st.model.app)
    think = rng.exponential_ns(_draw_bits(ctx, app, mask), app["cfg_mean_think"])
    st = st._replace(model=st.model._replace(app=app))
    return push_local_event(st, ctx, mask, now + think, K_APP, p0=OP_THINK)


# -- relay machinery -------------------------------------------------------
def _ct_find(app, sock, circ, side):
    """First circuit-table slot matching (sock, circ) on ``side`` ∈
    {'in', 'out'}. Returns (found[H], idx[H])."""
    m = (
        app["ct_used"]
        & (app[f"ct_{side}_sock"] == sock[None, :])
        & (app[f"ct_{side}_circ"] == circ[None, :])
    )
    return first_true_idx(m)


def _relay_on_cell(st, ctx, m, sock, meta, now):
    """The relay cell machine: one cell per host per round."""
    circ, aux, cmd = _decode(meta)
    app = dict(st.model.app)

    # --- C_CREATE: allocate a table entry, reply CREATED on the same leg.
    cr = m & (cmd == C_CREATE)
    has_free, slot = first_true_idx(~app["ct_used"])
    ok = cr & has_free
    app["ct_overflow"] = app["ct_overflow"] + (cr & ~has_free).astype(jnp.int64)
    # Dense one-hot writes, not .at[] scatters — XLA serializes dynamic-index
    # scatters on TPU and this block runs in every relay cell round
    # (core/dense.py; the round-2 scatter postmortem applies here too).
    app["ct_used"] = set_col(app["ct_used"], slot, True, ok)
    app["ct_in_sock"] = set_col(app["ct_in_sock"], slot, sock, ok)
    app["ct_in_circ"] = set_col(app["ct_in_circ"], slot, circ, ok)
    app["ct_out_sock"] = set_col(app["ct_out_sock"], slot, -1, ok)
    app["ct_pend"] = set_col(app["ct_pend"], slot, False, ok)
    st = st._replace(model=st.model._replace(app=app))
    st = _push_cell(st, ctx, ok, sock, _meta(circ, 0, C_CREATED), CELL, now)

    # --- locate the entry for every other cell, by in-side then out-side.
    app = dict(st.model.app)
    other = m & (cmd != C_CREATE)
    f_in, i_in = _ct_find(app, sock, circ, "in")
    f_out, i_out = _ct_find(app, sock, circ, "out")
    from_in = other & f_in
    from_out = other & ~f_in & f_out
    idx = jnp.where(from_in, i_in, jnp.where(from_out, i_out, 0))
    out_sock0 = get_col(app["ct_out_sock"], idx)

    # --- C_EXTEND from the in-side with no out leg yet: open/reuse the
    # onward conn and queue its CREATE.
    ext = from_in & (cmd == C_EXTEND) & (out_sock0 < 0)
    target = aux
    # reuse: first outbound conn already dialed to this relay
    reuse_m = app["rc_peer"] == target[None, :]
    any_reuse, r_sock = first_true_idx(reuse_m)
    has_reuse = ext & any_reuse
    # else: lowest FREE socket ≥ 1 (children take the top; see tcp.py)
    tcp_free = st.model.tcp["st"] == TCP_FREE
    tcp_free = tcp_free.at[0].set(False)
    need_dial = ext & ~has_reuse
    any_free, d_sock = first_true_idx(tcp_free)
    can_dial = need_dial & any_free
    app["ct_overflow"] = app["ct_overflow"] + (need_dial & ~can_dial).astype(jnp.int64)
    osock = jnp.where(has_reuse, r_sock, d_sock)
    oks = has_reuse | can_dial
    # allocate the out-circ id from the conn's counter
    ocirc = get_col(app["rc_next_circ"], osock)
    app["rc_next_circ"] = add_col(app["rc_next_circ"], osock, 1, oks)
    app["rc_peer"] = set_col(app["rc_peer"], d_sock, target, can_dial)
    app["ct_out_sock"] = set_col(app["ct_out_sock"], idx, osock, oks)
    app["ct_out_circ"] = set_col(app["ct_out_circ"], idx, ocirc, oks)
    # CREATE goes out now if the conn is up, else when it establishes.
    conn_up = has_reuse & (get_col(st.model.tcp["st"], osock) == TCP_ESTABLISHED)
    app["ct_pend"] = set_col(app["ct_pend"], idx, ~conn_up, oks)
    st = st._replace(model=st.model._replace(app=app))
    st = _push_cell(st, ctx, conn_up, osock, _meta(ocirc, 0, C_CREATE), CELL, now)
    st = push_local_event(
        st, ctx, can_dial, now, K_APP, p0=OP_CONNECT_RELAY, p1=d_sock, p2=target
    )

    # --- C_CREATED arriving on an out leg: translate to EXTENDED inward.
    app = st.model.app
    created = from_out & (cmd == C_CREATED)
    in_sock = get_col(app["ct_in_sock"], idx)
    in_circ = get_col(app["ct_in_circ"], idx)
    st = _push_cell(
        st, ctx, created, in_sock, _meta(in_circ, 0, C_EXTENDED), CELL, now
    )

    # --- C_BEGIN landing at the exit (in-side entry, no out leg): serve the
    # stream — one DATA message of aux cells, then END.
    at_exit = from_in & (cmd == C_BEGIN) & (out_sock0 < 0)
    st = _push_cell(
        st, ctx, at_exit, sock, _meta(circ, aux, C_DATA), aux * CELL, now
    )
    st = _push_cell(st, ctx, at_exit, sock, _meta(circ, 0, C_END), CELL, now)

    # --- forwarding: everything else crosses the relay.
    app = st.model.app
    out_sock = get_col(app["ct_out_sock"], idx)
    out_circ = get_col(app["ct_out_circ"], idx)
    # EXTEND with an existing out leg telescopes onward (the next relay does
    # the extending); only the ext-handled case (fresh out leg this round)
    # must not also forward.
    fwd_in = (
        from_in & ~ext & (cmd != C_CREATED) & ~at_exit & (out_sock >= 0)
    )
    fwd_out = from_out & (cmd != C_CREATED)
    nbytes = jnp.where(cmd == C_DATA, aux * CELL, CELL)
    napp = dict(app)
    napp["cells_fwd"] = napp["cells_fwd"] + (fwd_in | fwd_out).astype(jnp.int64)
    st = st._replace(model=st.model._replace(app=napp))
    st = _push_cell(st, ctx, fwd_in, out_sock, _meta(out_circ, aux, cmd), nbytes, now)
    st = _push_cell(st, ctx, fwd_out, in_sock, _meta(in_circ, aux, cmd), nbytes, now)
    return st


# -- event handlers --------------------------------------------------------
def on_wakeup(st, ctx, ev, mask):
    op = ev.p[0]
    now = ev.time
    zero = jnp.zeros(ctx.n_hosts, jnp.int32)
    t = tables(ctx.model_cfg)

    # OP_START: client dials a dirauth on sock 2. Rare (one per client
    # bootstrap) but carries a tcp_connect — lax.cond keeps it out of every
    # steady-state K_APP round (same for the other rare opcodes below; a
    # cond whose block is fully masked is a no-op by construction, so the
    # gating is exact).
    start = mask & (op == OP_START)
    two = jnp.full(ctx.n_hosts, 2, jnp.int32)

    def _op_start(st):
        app = dict(st.model.app)
        b = _draw_bits(ctx, app, start)
        d_idx = rng.randint(b, len(t["dir_ids"]))
        dirauth = jnp.asarray(t["dir_ids"])[d_idx]
        app["cl_state"] = jnp.where(start, CL_DIR_CONN, app["cl_state"])
        st = st._replace(model=st.model._replace(app=app))
        return T.tcp_connect(st, ctx, start, two, dirauth, zero, now)

    st = jax.lax.cond(start.any(), _op_start, lambda s: s, st)

    # OP_TX_CELL: the single transport-send site. Admission: the full
    # message must fit the send buffer and a boundary slot must be free;
    # otherwise retry at the next window start (deterministic backoff).
    tx = mask & (op == OP_TX_CELL)
    sock, meta, nbytes = ev.p[1], ev.p[2], ev.p[3]
    tcp = st.model.tcp
    sk = jnp.where(tx, sock, 0)
    snd_una = get_col(tcp["snd_una"], sk)
    app_end = get_col(tcp["app_end"], sk)
    buffered = (app_end - snd_una) - (snd_una == 0).astype(jnp.int32)
    fits = (ctx.params.sndbuf - buffered) >= nbytes
    mq_ok = ~get_col(tcp["mq_valid"], sk).all(axis=0)
    can = tx & fits & mq_ok
    retry = tx & ~can
    st, _acc = T.tcp_send(st, ctx, can, sock, nbytes, meta, now)
    app = dict(st.model.app)
    app["cell_retries"] = app["cell_retries"] + retry.astype(jnp.int64)
    st = st._replace(model=st.model._replace(app=app))
    t_retry = (now // ctx.window + 1) * ctx.window
    st = push_local_event(
        st, ctx, retry, t_retry, K_APP, p0=OP_TX_CELL, p1=sock, p2=meta, p3=nbytes
    )

    # OP_CONNECT_RELAY: dial an onward relay conn.
    dial = mask & (op == OP_CONNECT_RELAY)
    st = jax.lax.cond(
        dial.any(),
        lambda s: T.tcp_connect(s, ctx, dial, ev.p[1], ev.p[2], zero, now),
        lambda s: s, st,
    )

    # OP_DRAIN: send one pending CREATE on an established conn; loop while
    # more remain.
    drain = mask & (op == OP_DRAIN)

    def _op_drain(st):
        sock = ev.p[1]
        app = dict(st.model.app)
        pend = app["ct_used"] & app["ct_pend"] & (app["ct_out_sock"] == sock[None, :])
        any_p, idx = first_true_idx(pend)
        has = drain & any_p
        ocirc = get_col(app["ct_out_circ"], idx)
        app["ct_pend"] = set_col(app["ct_pend"], idx, False, has)
        more = drain & (pend.sum(axis=0) > 1)
        st = st._replace(model=st.model._replace(app=app))
        st = _push_cell(st, ctx, has, sock, _meta(ocirc, 0, C_CREATE), CELL, now)
        return push_local_event(st, ctx, more, now, K_APP, p0=OP_DRAIN, p1=sock)

    st = jax.lax.cond(drain.any(), _op_drain, lambda s: s, st)

    # OP_THINK: next stream on this circuit, or next circuit.
    think = mask & (op == OP_THINK)

    def _op_think(st):
        app = st.model.app
        next_stream = think & (app["cl_streams_left"] > 0)
        st2 = _client_begin_stream(st, ctx, next_stream, now)
        next_circ = think & ~next_stream & (st2.model.app["cl_circs_left"] > 0)
        return _client_begin_circuit(st2, ctx, next_circ, now)

    return jax.lax.cond(think.any(), _op_think, lambda s: s, st)


def on_notify(st, ctx, nf: T.Notif, now, mask):
    f = nf.flags
    sock = nf.sock
    role = st.model.app["role"]
    is_client = role == 1
    est = (f & N_ESTABLISHED) != 0
    msg = (f & N_MSG) != 0
    circ, aux, cmd = _decode(nf.meta)
    one = jnp.ones(ctx.n_hosts, jnp.int32)
    two = jnp.full(ctx.n_hosts, 2, jnp.int32)
    t = tables(ctx.model_cfg)
    app = st.model.app

    # Client bootstrap and circuit-build blocks run under lax.cond: each
    # fires a handful of times per client ever, but carries tcp_connect /
    # tcp_close / weighted-draw machinery that every notify round would
    # otherwise pay for (the gating is exact — all writes are masked).

    # Client: dirauth conn up → request the consensus.
    dir_up = mask & is_client & est & (sock == 2) & (app["cl_state"] == CL_DIR_CONN)

    def _dir_up(st):
        napp = dict(st.model.app)
        napp["cl_state"] = jnp.where(dir_up, CL_DIR_FETCH, napp["cl_state"])
        st = st._replace(model=st.model._replace(app=napp))
        return _push_cell(st, ctx, dir_up, two, _meta(0, 0, C_DIRREQ), CELL, now)

    st = jax.lax.cond(dir_up.any(), _dir_up, lambda s: s, st)

    # Client: consensus received → close dir conn, dial the drawn guard.
    app = st.model.app
    got_dir = (
        mask & is_client & msg & (sock == 2) & (cmd == C_DIRRESP)
        & (app["cl_state"] == CL_DIR_FETCH)
    )

    def _got_dir(st):
        napp = dict(st.model.app)
        guard = _pick_weighted(
            _draw_bits(ctx, napp, got_dir), t["guard_ids"], t["guard_cum"]
        )
        napp["cl_guard"] = jnp.where(got_dir, guard, napp["cl_guard"])
        napp["bootstrap_time"] = jnp.where(got_dir, now, napp["bootstrap_time"])
        napp["cl_state"] = jnp.where(got_dir, CL_GUARD_CONN, napp["cl_state"])
        st = st._replace(model=st.model._replace(app=napp))
        st = T.tcp_close(st, ctx, got_dir, two, now)
        zero = jnp.zeros(ctx.n_hosts, jnp.int32)
        return T.tcp_connect(st, ctx, got_dir, one, guard, zero, now)

    st = jax.lax.cond(got_dir.any(), _got_dir, lambda s: s, st)

    # Client: guard conn up → first circuit.
    app = st.model.app
    guard_up = (
        mask & is_client & est & (sock == 1) & (app["cl_state"] == CL_GUARD_CONN)
    )
    st = jax.lax.cond(
        guard_up.any(),
        lambda s: _client_begin_circuit(s, ctx, guard_up, now),
        lambda s: s, st,
    )

    # Client: circuit-build and stream cells on the guard conn.
    app = st.model.app
    cl_msg = mask & is_client & msg & (sock == 1) & (circ == app["cl_circ"])
    hop = app["cl_hop"]
    creatd = cl_msg & (cmd == C_CREATED) & (hop == 1)
    ext2 = cl_msg & (cmd == C_EXTENDED) & (hop == 2)
    ext3 = cl_msg & (cmd == C_EXTENDED) & (hop == 3)

    def _circ_build(st):
        app = st.model.app
        napp = dict(app)
        napp["cl_hop"] = jnp.where(creatd | ext2, hop + 1, napp["cl_hop"])
        st = st._replace(model=st.model._replace(app=napp))
        st = _push_cell(
            st, ctx, creatd, one, _meta(app["cl_circ"], app["cl_mid"], C_EXTEND),
            CELL, now,
        )
        st = _push_cell(
            st, ctx, ext2, one, _meta(app["cl_circ"], app["cl_exit"], C_EXTEND),
            CELL, now,
        )
        return _client_begin_stream(st, ctx, ext3, now)

    st = jax.lax.cond(
        (creatd | ext2 | ext3).any(), _circ_build, lambda s: s, st
    )

    # Client: stream data/end.
    app = st.model.app
    data = cl_msg & (cmd == C_DATA) & (app["cl_state"] == CL_STREAM)
    napp = dict(app)
    napp["cells_rx"] = napp["cells_rx"] + jnp.where(data, aux, 0).astype(jnp.int64)
    ended = cl_msg & (cmd == C_END) & (napp["cl_state"] == CL_STREAM)
    napp["streams_done"] = napp["streams_done"] + ended.astype(jnp.int32)
    napp["cl_streams_left"] = napp["cl_streams_left"] - ended.astype(jnp.int32)
    circ_done = ended & (napp["cl_streams_left"] == 0)
    napp["cl_circs_left"] = napp["cl_circs_left"] - circ_done.astype(jnp.int32)
    all_done = circ_done & (napp["cl_circs_left"] == 0)
    napp["done_time"] = jnp.where(all_done, now, napp["done_time"])
    napp["cl_state"] = jnp.where(all_done, CL_DONE, napp["cl_state"])
    st = st._replace(model=st.model._replace(app=napp))
    st = _client_think(st, ctx, ended & ~all_done, now)

    # Dirauth: serve consensus requests; reap disconnected clients.
    consensus_bytes = int(ctx.model_cfg.get("consensus_bytes", 2048))
    dreq = mask & (role == 2) & msg & (cmd == C_DIRREQ)
    d_fin = mask & (role == 2) & ((f & N_PEER_FIN) != 0)

    def _dirauth(st):
        st = _push_cell(
            st, ctx, dreq, sock, _meta(0, 0, C_DIRRESP), consensus_bytes, now
        )
        return T.tcp_close(st, ctx, d_fin, sock, now)

    st = jax.lax.cond((dreq | d_fin).any(), _dirauth, lambda s: s, st)

    # Relay: onward conn established → drain pending CREATEs.
    app = st.model.app
    dialed = get_col(app["rc_peer"], sock) >= 0
    r_est = mask & (role == 0) & est & dialed
    st = push_local_event(st, ctx, r_est, now, K_APP, p0=OP_DRAIN, p1=sock)

    # Relay: the cell machine.
    r_msg = mask & (role == 0) & msg
    return _relay_on_cell(st, ctx, r_msg, sock, nf.meta, now)


def summary(app) -> dict:
    return {
        "streams_done": app["streams_done"],
        "cells_rx": app["cells_rx"],
        "bootstrap_time": app["bootstrap_time"],
        "done_time": app["done_time"],
        "cells_fwd": app["cells_fwd"],
        "ct_overflow": app["ct_overflow"],
        "cell_retries": app["cell_retries"],
        "total_streams_done": app["streams_done"].sum(),
        "total_cells_rx": app["cells_rx"].sum(),
        "total_cells_fwd": app["cells_fwd"].sum(),
        "total_ct_overflow": app["ct_overflow"].sum(),
        "clients_done": (app["done_time"] > 0).sum(),
    }
