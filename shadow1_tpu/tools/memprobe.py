"""Memory-plane verifier — estimator audit, feasibility search, sub-batch
parity proof (shadow1_tpu/mem.py, docs/SEMANTICS.md §"Memory contract").

    python -m shadow1_tpu.tools.memprobe CONFIG [CONFIG ...] --audit
    python -m shadow1_tpu.tools.memprobe CONFIG --maxfit [--budget BYTES]
    python -m shadow1_tpu.tools.memprobe SWEEP.yaml --subbatch [--sub K]

Three modes (combinable; default ``--audit``):

* ``--audit`` — estimator-vs-actual byte audit: for each config, compute
  the pre-flight estimate, then BUILD the engine + state for real and
  measure ``jax.live_arrays()``. The resident estimate must track the
  measured bytes within ``mem.AUDIT_TOLERANCE`` (10%) — this is the drift
  guard that keeps the analytic const/variant models honest against the
  abstractly-traced state. One table row per config; exit 1 when any row
  is out of tolerance.
* ``--maxfit`` — binary-search the feasible envelope on the current
  budget (backend-reported, env ``SHADOW1_MEM_BYTES``, or ``--budget``):
  the max host count H at this config's shape class, and — when the
  config carries a ``sweep:`` — the max lane count E. Estimator-only:
  nothing is allocated, so probing a 16M-host point costs milliseconds.
* ``--subbatch`` — the downshift bit-exactness proof (chaosprobe idiom):
  run the config's sweep as ONE full-E fleet with the determinism flight
  recorder on, then again as sequential sub-batches of ``--sub`` lanes
  (default: ceil(E/2)), and assert every lane's per-window digest stream
  AND parity metrics are bit-identical between the two — lanes are
  independent, so sub-batching is digest-neutral (the property
  ``--on-oom downshift`` relies on). Each sub-batch is additionally run
  THROUGH a mid-batch checkpoint cycle (snapshot at the halfway chunk,
  reload into a fresh engine, continue) — the per-sub-batch
  checkpoint/resume path that lets ``--on-oom downshift`` compose with
  ``--ckpt`` (cli._fleet_subbatched) must be digest-neutral too. Exit 3
  on divergence, paritytrace pointer in the verdict.

The last stdout line is always one JSON verdict.
"""

from __future__ import annotations

import argparse
import json
import sys

EXIT_DIVERGED = 3
EXIT_AUDIT_FAILED = 1


def _parity_counter_names():
    from shadow1_tpu.telemetry.registry import METRIC_SPECS, gauge_names

    # Per-lane parity comparands: every canonical counter that is not a
    # batch-engine-only occupancy artifact (rounds/fires are trace-shape
    # dependent and excluded from cross-run parity everywhere else too).
    skip = set(gauge_names()) | {"rounds", "round_cap_hits"}
    skip |= {n for n in METRIC_SPECS if n.startswith("fires_")}
    return [n for n in METRIC_SPECS if n not in skip]


def audit_config(path: str, fleet: bool = False) -> dict:
    """One estimator-vs-actual row: build the engine + state for real and
    compare measured live bytes against the resident estimate."""
    import gc

    import jax

    from shadow1_tpu import mem
    from shadow1_tpu.config.experiment import load_experiment

    if fleet:
        from shadow1_tpu.fleet.expand import load_sweep

        plan = load_sweep(path)
        exp, params, n_exp = plan.exps[0], plan.params, len(plan.exps)
    else:
        exp, params, _ = load_experiment(path)
        n_exp = 1
    est = mem.estimate(exp, params, n_exp=n_exp)
    gc.collect()
    base = mem.live_bytes()
    if fleet:
        from shadow1_tpu.fleet.engine import FleetEngine

        eng = FleetEngine(plan.exps, params, plan.max_rounds)
    else:
        from shadow1_tpu.core.engine import Engine

        eng = Engine(exp, params)
    st = eng.init_state()
    jax.block_until_ready(st)
    measured = mem.live_bytes() - base
    del st, eng
    gc.collect()
    ratio = est.resident_bytes / measured if measured else float("inf")
    return {
        "config": path,
        "n_exp": n_exp,
        "estimated_state": est.state_bytes,
        "estimated_resident": est.resident_bytes,
        "estimated_peak": est.peak_bytes,
        "measured_live": int(measured),
        "ratio": round(ratio, 4),
        "ok": bool(abs(ratio - 1.0) <= mem.AUDIT_TOLERANCE),
    }


def maxfit(path: str, budget: int) -> dict:
    """Binary-search the feasible envelope at ``budget`` — estimator-only,
    so nothing is allocated at any probed point."""
    from shadow1_tpu import mem
    from shadow1_tpu.config.experiment import load_experiment

    exp, params, _ = load_experiment(path)
    # ONE real estimate; the search itself is pure arithmetic — every
    # state plane is [.., H], so peak scales ~H (const tables too).
    base = mem.estimate(exp, params, n_exp=1)
    per_host = base.peak_bytes / max(exp.n_hosts, 1)

    def fits_h(h: int) -> bool:
        return per_host * h <= budget

    if not fits_h(1):
        # even one host exceeds the budget — an honest infeasible verdict
        # beats reporting the unverified lower bound of the bisection.
        lo = 0
    else:
        lo, hi = 1, exp.n_hosts
        # expand upward to the envelope edge first
        while fits_h(hi) and hi < (1 << 24):
            lo, hi = hi, hi * 2
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            if fits_h(mid):
                lo = mid
            else:
                hi = mid
    out = {"config": path, "budget": int(budget), "hosts": exp.n_hosts,
           "max_hosts": int(lo)}
    try:
        from shadow1_tpu.fleet.expand import load_sweep

        plan = load_sweep(path)
    except Exception:  # noqa: BLE001 — no sweep: section, solo config
        plan = None
    if plan is not None:
        est = mem.estimate(plan.exps[0], plan.params,
                           n_exp=len(plan.exps))
        out["sweep_lanes"] = len(plan.exps)
        out["max_lanes"] = int(est.max_lanes(budget))
    return out


def _lane_streams(eng, st) -> dict[int, dict[int, tuple]]:
    """Per-lane {window: digest words} from a fleet state's rings."""
    from shadow1_tpu.core.digest import SUBSYSTEMS

    streams: dict[int, dict[int, tuple]] = {}
    for r in eng.drain_rings(st):
        if r["type"] != "ring":
            continue
        streams.setdefault(r["exp"], {})[r["window"]] = tuple(
            r[f"dg_{s}"] for s in SUBSYSTEMS)
    return streams


def subbatch_parity(path: str, sub: int | None, windows: int | None,
                    say) -> dict:
    """Full-E fleet vs sequential sub-batches (each cycled through a
    mid-batch checkpoint save/reload): per-lane digest streams and parity
    counters must be bit-identical (the downshift + per-batch-ckpt
    contract)."""
    import dataclasses
    import os
    import tempfile

    import jax

    from shadow1_tpu.ckpt import load_state, save_state
    from shadow1_tpu.fleet.engine import FleetEngine, fleet_metrics_per_exp
    from shadow1_tpu.fleet.expand import load_sweep

    plan = load_sweep(path)
    E = len(plan.exps)
    params = dataclasses.replace(plan.params, state_digest=1,
                                 metrics_ring=max(plan.params.metrics_ring,
                                                  64))
    sub = sub or -(-E // 2)
    n_windows = windows
    if n_windows is None:
        n_windows = min(int(-(-plan.exps[0].stop_time
                              // plan.exps[0].window)), 100)
    # Ring depth must cover the compared horizon so both sides drain the
    # identical gap-free window set.
    params = dataclasses.replace(
        params, metrics_ring=max(params.metrics_ring, n_windows))
    say(f"full fleet: {E} lanes x {n_windows} windows")
    eng_full = FleetEngine(plan.exps, params, plan.max_rounds)
    st_full = eng_full.run(n_windows=n_windows)
    jax.block_until_ready(st_full)
    full_streams = _lane_streams(eng_full, st_full)
    full_metrics = fleet_metrics_per_exp(st_full)
    counters = _parity_counter_names()
    sub_streams: dict[int, dict[int, tuple]] = {}
    sub_metrics: dict[int, dict] = {}
    half = n_windows // 2
    ck_dir = tempfile.TemporaryDirectory(prefix="memprobe_")
    ck = os.path.join(ck_dir.name, "batch.npz")
    for i in range(0, E, sub):
        say(f"sub-batch lanes [{i}, {min(i + sub, E)}) "
            f"(ckpt cycle at window {half})")
        eng_b = FleetEngine(plan.exps[i:i + sub], params,
                            plan.max_rounds[i:i + sub])
        eng_b.exp_base = i
        if half > 0:
            # Mid-batch checkpoint cycle: snapshot, reload into a FRESH
            # engine, continue — the per-sub-batch resume path of
            # --on-oom downshift + --ckpt must be digest-neutral.
            save_state(eng_b.run(n_windows=half), ck)
            eng_b = FleetEngine(plan.exps[i:i + sub], params,
                                plan.max_rounds[i:i + sub])
            eng_b.exp_base = i
            st_b = eng_b.run(load_state(eng_b.init_state(), ck),
                             n_windows=n_windows - half)
        else:
            st_b = eng_b.run(n_windows=n_windows)
        jax.block_until_ready(st_b)
        sub_streams.update(_lane_streams(eng_b, st_b))
        for j, m in enumerate(fleet_metrics_per_exp(st_b)):
            sub_metrics[i + j] = m
    ck_dir.cleanup()
    verdict = {"config": path, "experiments": E, "lanes_per_batch": sub,
               "windows": n_windows, "ckpt_cycled": half > 0,
               "streams_compared": len(full_streams)}
    for e in range(E):
        f, s = full_streams.get(e, {}), sub_streams.get(e, {})
        if f != s:
            bad = next((w for w in sorted(f) if f.get(w) != s.get(w)),
                       None)
            verdict.update(
                ok=False, diverged={"exp": e, "window": bad,
                                    "kind": "digest_stream"},
                hint=f"bisect lane {e} solo: python -m shadow1_tpu.tools."
                     f"paritytrace {path} tpu cpu")
            return verdict
        fm = {k: full_metrics[e].get(k, 0) for k in counters}
        sm = {k: sub_metrics[e].get(k, 0) for k in counters}
        if fm != sm:
            diff = {k: [fm[k], sm[k]] for k in counters if fm[k] != sm[k]}
            verdict.update(ok=False,
                           diverged={"exp": e, "kind": "metrics",
                                     "fields": diff})
            return verdict
    verdict["ok"] = True
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.memprobe")
    ap.add_argument("configs", nargs="+", help="YAML experiment file(s)")
    ap.add_argument("--audit", action="store_true",
                    help="estimator-vs-live-bytes audit (default mode)")
    ap.add_argument("--fleet", action="store_true",
                    help="audit the config's sweep: as a fleet state")
    ap.add_argument("--maxfit", action="store_true",
                    help="binary-search max feasible hosts/lanes")
    ap.add_argument("--subbatch", action="store_true",
                    help="sub-batched-fleet == full-fleet parity proof")
    ap.add_argument("--sub", type=int, default=None,
                    help="lanes per sub-batch (default ceil(E/2))")
    ap.add_argument("--windows", type=int, default=None,
                    help="windows for the --subbatch comparison")
    ap.add_argument("--budget", type=int, default=None,
                    help="byte budget for --maxfit (default: backend "
                         "reported / SHADOW1_MEM_BYTES)")
    ap.add_argument("--json-only", action="store_true",
                    help="suppress progress lines; print only the verdict")
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu import mem
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)

    def say(msg):
        if not args.json_only:
            print(f"[memprobe] {msg}", file=sys.stderr, flush=True)

    if not (args.audit or args.maxfit or args.subbatch):
        args.audit = True
    rc = 0
    out: dict = {"ok": True}
    if args.audit:
        rows = []
        for cfg in args.configs:
            say(f"audit {cfg}")
            row = audit_config(cfg, fleet=args.fleet)
            say(f"  estimated {mem.fmt_bytes(row['estimated_resident'])} "
                f"vs measured {mem.fmt_bytes(row['measured_live'])} "
                f"(ratio {row['ratio']}) "
                f"{'ok' if row['ok'] else 'OUT OF TOLERANCE'}")
            rows.append(row)
        out["audit"] = rows
        if not all(r["ok"] for r in rows):
            out["ok"] = False
            rc = EXIT_AUDIT_FAILED
    if args.maxfit:
        budget = args.budget
        if budget is None:
            budget, src = mem.device_budget()
            if budget is None:
                print("memprobe: no budget (cpu backend reports none; "
                      "pass --budget or set SHADOW1_MEM_BYTES)",
                      file=sys.stderr)
                print(json.dumps({"ok": False, "error": "no_budget"}))
                return 2
        out["maxfit"] = [maxfit(cfg, budget) for cfg in args.configs]
    if args.subbatch:
        v = subbatch_parity(args.configs[0], args.sub, args.windows, say)
        out["subbatch"] = v
        if not v["ok"]:
            out["ok"] = False
            rc = EXIT_DIVERGED
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
