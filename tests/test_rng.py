"""RNG invariants: jnp/NumPy twin equality, backend-exactness, statistics.

The determinism contract (docs/SEMANTICS.md) requires every draw to be a
pure function of (seed, purpose, host, counter) with identical values on
every backend and in the eager oracle. The integer pipeline makes that hold
by construction; these tests guard the construction.
"""

import jax.numpy as jnp
import numpy as np

from shadow1_tpu import rng


def _sample_bits(n=50000, seed=99):
    key = rng.base_key(seed)
    key_np = rng.base_key_np(seed)
    host = np.arange(n, dtype=np.int64) % 1000
    ctr = np.arange(n, dtype=np.int64) * 7
    bj = np.asarray(rng.bits(key, 3, jnp.asarray(host), jnp.asarray(ctr)))
    bn = rng.bits_np(key_np, 3, host, ctr)
    return bj, bn


def test_bits_numpy_twin_exact():
    bj, bn = _sample_bits()
    np.testing.assert_array_equal(bj, bn)


def test_exponential_numpy_twin_exact():
    bj, bn = _sample_bits()
    for mean in (1.0, 1e3, 2e6, 1e9, 2.0**40):  # incl. the clamp region
        ej = np.asarray(rng.exponential_ns(jnp.asarray(bj), mean))
        en = rng.exponential_ns_np(bn, mean)
        np.testing.assert_array_equal(ej, en)


def test_randint_numpy_twin_exact():
    bj, bn = _sample_bits()
    for n in (2, 7, 4096, 10_000_019):
        np.testing.assert_array_equal(
            np.asarray(rng.randint(jnp.asarray(bj), n)), rng.randint_np(bn, n)
        )


def test_exponential_matches_float_reference():
    """The fixed-point pipeline tracks -mean*log1p(-u) to ~1e-4 relative
    (away from the 1 ns clamp)."""
    bj, _ = _sample_bits()
    mean = 2e6
    e = np.asarray(rng.exponential_ns(jnp.asarray(bj), mean)).astype(float)
    u = bj.astype(np.float64) / 2.0**32
    ref = np.maximum(-mean * np.log1p(-u), 1)
    big = ref > 1000  # ignore the clamp region
    rel = np.abs(e[big] - ref[big]) / ref[big]
    assert rel.max() < 1e-3, rel.max()
    assert abs(e.mean() / mean - 1) < 0.02


def test_bits_statistics():
    bj, _ = _sample_bits(200000)
    assert abs(bj.mean() / 2.0**32 - 0.5) < 0.005
    # byte-level chi2 well within 4 sigma of the 255-dof expectation
    h = np.bincount(bj & 255, minlength=256)
    chi2 = (((h - h.mean()) ** 2) / h.mean()).sum()
    assert chi2 < 255 + 4 * np.sqrt(2 * 255), chi2
    # no collisions across distinct (host, ctr) in the sample
    assert len(np.unique(bj)) > 0.99 * len(bj)


def test_prob_threshold_bernoulli():
    bj, bn = _sample_bits(200000)
    thr = rng.prob_threshold(0.25)
    got = np.asarray(rng.uniform_lt(jnp.asarray(bj), thr)).mean()
    assert abs(got - 0.25) < 0.005
    assert rng.prob_threshold(0.0) == 0
    assert rng.prob_threshold(1.0) == 1 << 32
