"""shadow1_tpu — a TPU-native discrete-event network simulation framework.

A ground-up rebuild of the capabilities of Shadow v1.x (reference:
``joskid/shadow-1``, surveyed in /root/repo/SURVEY.md): deterministic
simulation of large host networks (Tor, Bitcoin, tgen-style traffic) over
weighted latency/loss/bandwidth topologies with a full virtual TCP stack —
re-expressed as batched tensor computation on TPU.

Architecture (see SURVEY.md §7):

* Per-host event priority queues (reference: ``src/main/core/scheduler/``)
  collapse into fixed-capacity per-host event tensors advanced in
  conservative time windows (lookahead = minimum topology latency),
  mirroring the reference's barrier-round scheduler
  (``src/main/core/master.c`` runahead + ``scheduler.c`` rounds).
* Packet routing/propagation (reference: ``src/main/routing/topology.c``)
  becomes gather over a dense vertex-level latency matrix in HBM plus a
  sorted scatter into destination event buffers once per window.
* The virtual TCP stack (reference: ``src/main/host/descriptor/tcp.c``)
  is vectorized across every socket of every host.
* Multi-chip scaling shards the host axis over an ICI mesh; the one
  cross-shard exchange per window is the batched packet all_to_all.

Two engines implement identical semantics behind one experiment format:
``shadow1_tpu.cpu_engine`` (readable heapq reference — the oracle) and
``shadow1_tpu.core.engine`` (the batched TPU engine). Determinism is a hard
invariant: same seed ⇒ identical event streams on both engines and across
shardings.
"""

import jax

# Simulation time is int64 nanoseconds (the reference's SimulationTime is
# ns-resolution). Enable 64-bit support; every float array in the package is
# explicitly dtyped (f32) so this does not silently promote compute to f64.
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: engine round bodies are large programs
# (minutes to compile); caching makes repeat CLI/bench/test invocations
# start in seconds.
jax.config.update("jax_compilation_cache_dir", "/tmp/shadow1_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

__version__ = "0.1.0"
