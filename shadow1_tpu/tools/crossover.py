"""The dense-scale crossover sweep: batched engine vs eager C++ by host count.

    python -m shadow1_tpu.tools.crossover [--hosts 2000,5000,...]
        [--windows N] [--cpp-windows N] [--json PATH]

The architecture thesis (docs/PERF.md "crossover"): an eager per-event DES
pays per event and collapses as its random-access working set leaves cache;
the batched engine pays per ROUND and rises with density as the fixed round
cost amortizes across SIMD lanes. This tool measures both sides of that
claim on the same workload — the dense tgen mesh of
``configs/dense_tgen50k.yaml`` scaled to each host count — and emits one
JSON row per size:

    {"n_hosts": N, "tpu_events_per_sec": ..., "cpp_events_per_sec": ...,
     "tpu_vs_cpp": ...}

Methodology: each batched run executes in a CHILD process (the tunneled
device faults on long executions and can wedge a process — docs/PERF.md),
timed over chunked 10-window device calls with the compile excluded via a
0-window warmup; the C++ thread-per-core comparator (SURVEY §7.3.5) runs
the same config for ``--cpp-windows`` whole windows (its per-event cost is
stationary, so a shorter slice gives a stable rate). Where both sides run
the same window count the event counters must bit-match (the parity
contract); with different slices the row records both counts.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

DEFAULT_HOSTS = (2000, 5000, 10000, 20000, 50000)
CHUNK = 10


def dense_doc(n_hosts: int) -> dict:
    """configs/dense_tgen50k.yaml scaled to ``n_hosts`` (same per-host
    parameters; only the count changes). Loaded from the yaml so the
    exhibit config has ONE source of truth."""
    import os

    import yaml

    path = os.path.join(os.path.dirname(__file__), "..", "..", "configs",
                        "dense_tgen50k.yaml")
    with open(path) as f:
        doc = yaml.safe_load(f)
    doc["hosts"][0]["count"] = n_hosts
    return doc


def child_main(n_hosts: int, windows: int) -> int:
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax

    from shadow1_tpu.config.experiment import build_experiment
    from shadow1_tpu.core.engine import Engine

    exp, params, _ = build_experiment(dense_doc(n_hosts))
    eng = Engine(exp, params)
    t0 = time.perf_counter()
    jax.block_until_ready(eng.run(eng.init_state(), n_windows=0))
    compile_s = time.perf_counter() - t0

    st = eng.init_state()
    done = 0
    t0 = time.perf_counter()
    while done < windows:
        step = min(CHUNK, windows - done)
        st = eng.run(st, n_windows=step)
        jax.block_until_ready(st)
        done += step
    wall = time.perf_counter() - t0
    m = Engine.metrics_dict(st)
    print(json.dumps({
        "backend": jax.default_backend(),
        "n_hosts": n_hosts,
        "windows": windows,
        "events": m["events"],
        "wall_s": round(wall, 3),
        "compile_s": round(compile_s, 2),
        "events_per_sec": round(m["events"] / wall, 1) if wall else None,
        "rounds_per_window": round(m["rounds"] / max(m["windows"], 1), 1),
        "ev_overflow": m["ev_overflow"],
        "ob_overflow": m["ob_overflow"],
    }))
    return 0


def run_cpp(n_hosts: int, windows: int) -> dict:
    from shadow1_tpu import native
    from shadow1_tpu.config.experiment import build_experiment

    exp, params, _ = build_experiment(dense_doc(n_hosts))
    try:
        native.ensure_built()
        import os

        r = native.run_net(exp, params, windows, n_threads=os.cpu_count() or 1)
    except Exception as e:  # noqa: BLE001 — no toolchain -> no baseline
        return {"cpp_error": repr(e)[:300]}
    return {
        "cpp_windows": windows,
        "cpp_events": r["events"],
        "cpp_wall_s": round(r["wall_s"], 3),
        "cpp_events_per_sec": r["events_per_sec"],
        "cpp_threads": r["n_threads"],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", default=",".join(map(str, DEFAULT_HOSTS)))
    ap.add_argument("--windows", type=int, default=60,
                    help="batched-engine slice (windows)")
    ap.add_argument("--cpp-windows", type=int, default=None,
                    help="C++ slice (default: same as --windows; shrink at "
                         "large sizes where the eager side crawls)")
    ap.add_argument("--json", default=None)
    ap.add_argument("--skip-tpu", action="store_true",
                    help="only measure the C++ side")
    ap.add_argument("--child", type=int, default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.child is not None:
        return child_main(args.child, args.windows)

    rows = []
    for n in (int(x) for x in args.hosts.split(",")):
        row = {"n_hosts": n}
        if not args.skip_tpu:
            try:
                r = subprocess.run(
                    [sys.executable, "-m", "shadow1_tpu.tools.crossover",
                     "--child", str(n), "--windows", str(args.windows)],
                    capture_output=True, text=True, timeout=1800,
                )
                row.update(json.loads(r.stdout.strip().splitlines()[-1]))
            except subprocess.TimeoutExpired:
                # A wedged tunnel hangs child processes forever — bound it
                # and keep sweeping (the C++ side still produces its row).
                row["tpu_error"] = "child exceeded 1800s (wedged device?)"
            except (IndexError, ValueError):
                row["tpu_error"] = (r.stderr[-300:] or f"rc={r.returncode}")
        row.update(run_cpp(n, args.cpp_windows or args.windows))
        if row.get("events_per_sec") and row.get("cpp_events_per_sec"):
            row["tpu_vs_cpp"] = round(
                row["events_per_sec"] / row["cpp_events_per_sec"], 3
            )
            if row.get("windows") == row.get("cpp_windows"):
                row["events_match"] = row["events"] == row["cpp_events"]
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
