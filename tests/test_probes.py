"""Flow-probe plane: watchlist resolution, probe-ring parity, resume.

The probe contract (flow-observability acceptance): the per-window flow
samples are bit-identical cpu-oracle ↔ tpu ↔ sharded(8) ↔ fleet-lane, a
resumed run reproduces the straight run's rows exactly, and probes-off
leaves the state pytree (and thus the traced program) untouched.
"""

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.telemetry.probes import drain_probes
from shadow1_tpu.telemetry.registry import PROBE_FIELDS
from tests.test_net_parity import filexfer_exp

N_WINDOWS = 25
PROBES = ((1, 0), (0, -1))  # the client's flow + the server's host view
PARAMS = EngineParams(metrics_ring=32, probes=PROBES)


def _key(r):
    return (r.get("exp", -1), r.get("window", -1), r.get("host", -1),
            r.get("sock", -1))


def tpu_rows(exp, params=PARAMS, n_windows=N_WINDOWS, st=None, start=0):
    eng = Engine(exp, params)
    st = eng.run(st, n_windows=n_windows)
    return st, sorted(drain_probes(st, eng.window, params.probes,
                                   start=start), key=_key)


def cpu_rows(exp, params=PARAMS, n_windows=N_WINDOWS):
    eng = CpuEngine(exp, params)
    eng.run(n_windows=n_windows)
    return sorted(eng.probe_rows, key=_key)


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_probe_rows_bit_identical_cpu_vs_tpu():
    exp = filexfer_exp()
    _, trows = tpu_rows(exp)
    crows = cpu_rows(exp)
    assert len(trows) == N_WINDOWS * len(PROBES)
    assert trows == crows
    # The rows carry the whole declared schema, as plain ints.
    for r in trows:
        assert all(f in r and isinstance(r[f], int) for f in PROBE_FIELDS)
    # The watched flow actually moved (a parity of all-zeros proves nothing).
    assert any(r["cwnd"] > 0 for r in trows if r["sock"] == 0)
    assert any(r["inflight"] > 0 for r in trows if r["sock"] == 0)


def test_probe_rows_bit_identical_sharded():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from shadow1_tpu.shard.engine import ShardedEngine

    # 8 hosts across 8 shards: every probe is owned by a non-zero shard at
    # least once, so the one-hot psum gather is actually exercised.
    exp = filexfer_exp(n_hosts=8, flow=60_000, end=10 * SEC)
    params = EngineParams(metrics_ring=32, ev_cap=512,
                          probes=((0, -1), (3, 0), (7, 0)))
    _, solo = tpu_rows(exp, params)
    sh = ShardedEngine(exp, params)
    st = sh.run(sh.init_state(), n_windows=N_WINDOWS)
    shrows = sorted(drain_probes(st, sh.window, params.probes), key=_key)
    assert shrows == solo


def test_probe_rows_fleet_lane_vs_solo():
    from shadow1_tpu.fleet.engine import FleetEngine

    exp_a = filexfer_exp(seed=11)
    exp_b = filexfer_exp(seed=12)
    fleet = FleetEngine([exp_a, exp_b], PARAMS)
    st = fleet.run(n_windows=N_WINDOWS)
    recs = fleet.drain_rings(st)
    flows = [r for r in recs if r["type"] == "flow"]
    assert {r["exp"] for r in flows} == {0, 1}
    for gid, exp in ((0, exp_a), (1, exp_b)):
        lane = sorted(
            ({k: v for k, v in r.items() if k != "exp"}
             for r in flows if r["exp"] == gid), key=_key)
        _, solo = tpu_rows(exp)
        assert lane == solo, f"lane {gid} diverged from its solo run"


def test_probe_resume_reproduces_straight_run(tmp_path):
    from shadow1_tpu.ckpt import load_state, save_state

    exp = filexfer_exp()
    _, straight = tpu_rows(exp)
    eng = Engine(exp, PARAMS)
    st = eng.run(n_windows=12)
    first = drain_probes(st, eng.window, PROBES)
    path = str(tmp_path / "probe.ckpt")
    save_state(st, path)
    eng2 = Engine(exp, PARAMS)
    st2 = load_state(eng2.init_state(), path)
    st2 = eng2.run(st2, n_windows=N_WINDOWS - 12)
    rest = drain_probes(st2, eng2.window, PROBES, start=12)
    assert sorted(first + rest, key=_key) == straight


def test_probe_gap_record_when_chunk_exceeds_ring():
    # Ring depth 8 but 25 windows drained in one go: the overwritten
    # windows surface as one flow_gap record, like ring_gap.
    exp = filexfer_exp()
    params = EngineParams(metrics_ring=8, probes=PROBES)
    _, rows = tpu_rows(exp, params)
    eng = Engine(exp, params)
    st = eng.run(n_windows=N_WINDOWS)
    recs = drain_probes(st, eng.window, PROBES)
    gaps = [r for r in recs if r["type"] == "flow_gap"]
    assert len(gaps) == 1
    assert gaps[0]["windows_lost"] == N_WINDOWS - 8
    flows = [r for r in recs if r["type"] == "flow"]
    assert sorted({r["window"] for r in flows}) == list(
        range(N_WINDOWS - 8, N_WINDOWS))


def test_probe_phold_host_view():
    # Model dispatch: phold has no tcp/nic planes — TCP/NIC columns stay 0,
    # pending_events is live, and the oracle mirrors it bit-exactly.
    exp = single_vertex_experiment(
        n_hosts=16, seed=7, end_time=60 * MS, latency_ns=1 * MS,
        model="phold", model_cfg={"mean_delay_ns": float(2 * MS),
                                  "init_events": 2})
    params = EngineParams(metrics_ring=32, probes=((3, -1), (15, -1)))
    _, trows = tpu_rows(exp, params, n_windows=20)
    crows = cpu_rows(exp, params, n_windows=20)
    assert trows == crows
    assert any(r["pending_events"] > 0 for r in trows)
    assert all(r["cwnd"] == 0 and r["nic_tx_bytes"] == 0 for r in trows)


# ---------------------------------------------------------------------------
# off-state and guards
# ---------------------------------------------------------------------------

def test_probes_off_leaves_state_layout_unchanged():
    import jax

    exp = filexfer_exp()
    off = Engine(exp, EngineParams(metrics_ring=32))
    assert off.init_state().probes is None
    # Same treedef as a pre-probe state: checkpoints, sharding specs and
    # the traced program are untouched unless probes are actually on
    # (the --state-digest zero-cost rule; opcensus guards the op counts).
    on = Engine(exp, PARAMS)
    t_off = jax.tree_util.tree_structure(off.init_state())
    t_on = jax.tree_util.tree_structure(on.init_state())
    assert t_off != t_on
    n_off = len(jax.tree_util.tree_leaves(off.init_state()))
    n_on = len(jax.tree_util.tree_leaves(on.init_state()))
    assert n_on == n_off + 1  # exactly the [W, K, F] buffer


def test_probes_require_ring_on_batched_engines():
    exp = filexfer_exp()
    with pytest.raises(ValueError, match="metrics_ring"):
        Engine(exp, EngineParams(probes=PROBES, metrics_ring=0))
    # The oracle has no ring: probes work ringless there.
    eng = CpuEngine(exp, EngineParams(probes=PROBES, metrics_ring=0))
    eng.run(n_windows=5)
    assert len(eng.probe_rows) == 5 * len(PROBES)


def test_probe_ring_shape_and_dtype():
    exp = filexfer_exp()
    st = Engine(exp, PARAMS).init_state()
    assert st.probes.buf.shape == (32, len(PROBES), len(PROBE_FIELDS))
    assert st.probes.buf.dtype == np.int64


# ---------------------------------------------------------------------------
# watchlist resolution (config path)
# ---------------------------------------------------------------------------

def _dns(counts):
    from types import SimpleNamespace

    from shadow1_tpu.config.dns import Dns

    groups, start = [], 0
    for name, n in counts:
        groups.append(SimpleNamespace(name=name, count=n, start=start))
        start += n
    return Dns.from_groups(groups, np.zeros(start, np.int32))


def test_resolve_watchlist_forms():
    from shadow1_tpu.config.experiment import resolve_watchlist

    dns = _dns([("server", 1), ("client", 4)])
    got = resolve_watchlist(
        ["server", "client-2:1", "client[0]:0", 3, {"host": "client[1]"},
         {"host": 0, "sock": 2}],
        dns, sockets_per_host=4)
    assert got == ((0, -1), (3, 1), (1, 0), (3, -1), (2, -1), (0, 2))
    # Duplicates collapse, first occurrence wins the order.
    assert resolve_watchlist(["server", "server", 0], dns, 4) == ((0, -1),)
    # A scalar entry is accepted as a one-element list.
    assert resolve_watchlist("client-0:1", dns, 4) == ((1, 1),)


def test_resolve_watchlist_rejects_typos_with_suggestion():
    from shadow1_tpu.config.experiment import (
        WatchlistError,
        resolve_watchlist,
    )

    dns = _dns([("server", 1), ("client", 4)])
    with pytest.raises(WatchlistError, match="did you mean 'client'"):
        resolve_watchlist(["clinet:0"], dns, 4)
    with pytest.raises(WatchlistError, match="out of range"):
        resolve_watchlist(["client-0:99"], dns, 4)
    with pytest.raises(WatchlistError, match="out of range"):
        resolve_watchlist([99], dns, 4)
    with pytest.raises(WatchlistError, match="socket"):
        resolve_watchlist(["client:x"], dns, 4)
    with pytest.raises(WatchlistError):
        resolve_watchlist([{"hots": "client"}], dns, 4)


def test_probes_config_section_and_engine_key_rejected(tmp_path):
    import textwrap

    from shadow1_tpu.config.experiment import load_experiment

    base = textwrap.dedent("""\
        general: {seed: 1, stop_time: 100 ms}
        engine: {scheduler: tpu}
        network: {single_vertex: {latency: 1 ms}}
        hosts: [{name: h, count: 4}]
        app: {model: phold, params: {mean_delay_ns: 2.0e7}}
    """)
    cfg = tmp_path / "p.yaml"
    cfg.write_text(base + 'probes: ["h-1", "h[3]"]\n')
    _, params, _ = load_experiment(str(cfg))
    assert params.probes == ((1, -1), (3, -1))
    # probes is a top-level section, not an engine knob.
    cfg.write_text(base.replace("scheduler: tpu",
                                "scheduler: tpu, probes: [0]"))
    with pytest.raises(AssertionError, match="probes"):
        load_experiment(str(cfg))


def test_heartbeat_emits_flow_records():
    import io
    import json

    from shadow1_tpu.obs import run_with_heartbeat

    exp = filexfer_exp()
    eng = Engine(exp, PARAMS)
    buf = io.StringIO()
    _, hb = run_with_heartbeat(eng, n_windows=20, every_windows=10,
                               stream=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    flows = [r for r in lines if r["type"] == "flow"]
    assert [r["window"] for r in flows if r["sock"] == 0] == list(range(20))
    assert hb.flow_records == flows
