"""Native thread-per-core comparator: build + run harness.

Builds ``phold_comparator.cpp`` with the system g++ on first use (cached
under ``build/native/`` at the repo root) and runs it on the same
experiment parameters the JAX engine and Python oracle consume. The Q32
log2 table is dumped from shadow1_tpu.rng's numpy source of truth so the
C++ fixed-point exponential is bit-identical to both engines (no libm
rounding drift can enter).

This is the honest baseline mandated by BASELINE.json ("thread-per-core
CPU scheduler", reference scheduler-policy-host-steal.c): an optimized
multi-core C++ DES, not the interpreted oracle. tests/test_native_
comparator.py asserts counter equality against the oracle, which is what
entitles bench.py to use its wall clock as ``vs_baseline``.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

import numpy as np

_DIR = pathlib.Path(__file__).resolve().parent
_REPO = _DIR.parent.parent
_BUILD = _REPO / "build" / "native"
_BIN = _BUILD / "phold_comparator"
_TABLE = _BUILD / "log2_q32.tbl"


class NativeUnavailable(RuntimeError):
    pass


def _dump_table() -> None:
    from shadow1_tpu import rng

    tbl = np.asarray(rng._LOG_TBL_NP, np.uint64)
    assert tbl.shape == (2**rng._LOG_BITS + 1,)
    with open(_TABLE, "wb") as f:
        f.write(tbl.tobytes())
        f.write(np.uint64(rng._LN2_Q32).tobytes())


def ensure_built(force: bool = False) -> pathlib.Path:
    src = _DIR / "phold_comparator.cpp"
    rng_src = _REPO / "shadow1_tpu" / "rng.py"
    _BUILD.mkdir(parents=True, exist_ok=True)
    # Re-dump when rng.py is newer than the table: a stale table would make
    # the comparator silently non-identical to the jnp/numpy engines.
    if force or not _TABLE.exists() or _TABLE.stat().st_mtime < rng_src.stat().st_mtime:
        _dump_table()
    if not force and _BIN.exists() and _BIN.stat().st_mtime >= src.stat().st_mtime:
        return _BIN
    cmd = ["g++", "-O2", "-std=c++17", "-pthread", "-o", str(_BIN), str(src)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        raise NativeUnavailable(f"g++ unavailable: {e!r}") from e
    if out.returncode != 0:
        raise NativeUnavailable(f"g++ failed: {out.stderr[-800:]}")
    return _BIN


def run_phold(
    n_hosts: int,
    seed: int,
    n_windows: int,
    window_ns: int,
    mean_delay_ns: float,
    init_events: int,
    ev_cap: int,
    outbox_cap: int,
    n_threads: int | None = None,
    timeout_s: float = 900.0,
) -> dict:
    """Run the comparator; returns its counters + wall_s + events_per_sec."""
    binary = ensure_built()
    if n_threads is None:
        n_threads = os.cpu_count() or 1
    cmd = [
        str(binary), str(_TABLE), str(n_hosts), str(seed), str(n_windows),
        str(window_ns), str(int(round(mean_delay_ns))), str(init_events),
        str(ev_cap), str(outbox_cap), str(n_threads),
    ]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
    if out.returncode != 0:
        raise NativeUnavailable(
            f"comparator rc={out.returncode}: {out.stderr[-500:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


if __name__ == "__main__":
    print(json.dumps(run_phold(*map(int, sys.argv[1:]))))
