"""Multi-device engine: the host axis sharded over a JAX mesh.

The reference scales by partitioning hosts across worker threads
(src/main/core/scheduler/scheduler-policy-host-steal.c et al., SURVEY §2.5);
the TPU-native equivalent shards the host axis of every state tensor over a
``jax.sharding.Mesh`` with ``jax.shard_map``. Inside a window each device
runs its local block's rounds completely independently (the conservative
lookahead guarantees no mid-window cross-host interaction — the same
invariant the reference's barrier rounds rely on); at the window end the
routed packet batch is exchanged with ONE tiled ``all_gather`` over the mesh
axis and each shard scatters the packets addressed to its hosts. That single
collective per window is the entire communication schedule — it rides ICI
within a slice and DCN across slices, replacing the reference's locked
cross-thread event push (src/main/utility/async-priority-queue.c).

Determinism across shardings: the gathered packet order is shard-major ×
host-major = global host-major — exactly the single-device flatten order —
and all event/tie-break keys are computed from global host ids, so the
delivered event streams are identical for any device count. The
``rounds``/``round_cap_hits`` metrics are the one exception (each shard
counts its own inner rounds; they are summed), so they are performance
counters, not semantic invariants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from shadow1_tpu import rng
from shadow1_tpu.config.compiled import CompiledExperiment
from shadow1_tpu.consts import EngineParams
from shadow1_tpu.core.engine import (
    Ctx,
    Engine,
    SimState,
    _metrics_init,
    _model_module,
    window_step,
)
from shadow1_tpu.core.events import evbuf_init
from shadow1_tpu.core.outbox import outbox_init


class ShardedEngine:
    """Engine running one CompiledExperiment over an n-device host-axis mesh.

    API mirrors core.engine.Engine: init_state() → run() → metrics_dict /
    model_summary. n_hosts must divide evenly by the device count.
    """

    def __init__(
        self,
        exp: CompiledExperiment,
        params: EngineParams | None = None,
        devices=None,
        axis: str = "hosts",
    ):
        exp.validate()
        self.exp = exp
        self.params = params or EngineParams()
        devices = list(devices if devices is not None else jax.devices())
        self.n_dev = len(devices)
        if exp.n_hosts % self.n_dev:
            raise ValueError(
                f"n_hosts={exp.n_hosts} not divisible by {self.n_dev} devices"
            )
        self.h_local = exp.n_hosts // self.n_dev
        self.axis = axis
        self.mesh = jax.make_mesh((self.n_dev,), (axis,), devices=devices)
        self.window = exp.window
        self.n_windows = int(-(-exp.end_time // self.window))
        # Global-view ctx: used for state init (which runs unsharded) and for
        # model summaries. Semantically identical to the single-device ctx.
        self.global_ctx = Ctx(
            n_hosts=exp.n_hosts,
            n_total=exp.n_hosts,
            params=self.params,
            window=self.window,
            key=rng.base_key(exp.seed),
            lat_vv=jnp.asarray(exp.lat_vv, jnp.int64),
            loss_vv=jnp.asarray(exp.loss_vv, jnp.float32),
            host_vertex=jnp.asarray(exp.host_vertex, jnp.int32),
            bw_up=jnp.asarray(exp.bw_up, jnp.int64),
            bw_dn=jnp.asarray(exp.bw_dn, jnp.int64),
            model_cfg=exp.model_cfg,
        )
        self._model = _model_module(exp.model)
        self._run_jit = jax.jit(self._make_run(), static_argnums=1)

    # -- sharding specs ----------------------------------------------------
    def _spec_for(self, leaf) -> P:
        # Every rank≥1 state tensor is host-major by design; scalars are
        # replicated. (Guarded by the n_hosts match so aux leaves of other
        # shapes would fail loudly in shard_map rather than mis-shard.)
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == self.exp.n_hosts:
            return P(self.axis)
        return P()

    def _state_specs(self, st: SimState):
        return jax.tree.map(self._spec_for, st)

    # -- state -------------------------------------------------------------
    def init_state(self) -> SimState:
        evbuf = evbuf_init(self.exp.n_hosts, self.params.ev_cap)
        model, evbuf, seed_over = self._model.init(self.global_ctx, evbuf)
        metrics = _metrics_init()
        st = SimState(
            win_start=jnp.zeros((), jnp.int64),
            evbuf=evbuf,
            outbox=outbox_init(self.exp.n_hosts, self.params.outbox_cap),
            model=model,
            metrics=metrics._replace(ev_overflow=metrics.ev_overflow + seed_over),
        )
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._state_specs(st)
        )
        return jax.device_put(st, shardings)

    # -- the sharded program ----------------------------------------------
    def _make_run(self):
        exp, pr, axis = self.exp, self.params, self.axis
        n_dev, h_local = self.n_dev, self.h_local
        window, model = self.window, self._model
        key = self.global_ctx.key
        lat_vv = self.global_ctx.lat_vv
        loss_vv = self.global_ctx.loss_vv
        loss_thr_vv = self.global_ctx.loss_thr_vv
        host_vertex = self.global_ctx.host_vertex  # full, replicated
        hosts_g = self.global_ctx.hosts
        bw_up_g = self.global_ctx.bw_up
        bw_dn_g = self.global_ctx.bw_dn

        def block(st: SimState, hosts, bw_up, bw_dn, n_windows: int) -> SimState:
            ctx = Ctx(
                n_hosts=h_local,
                n_total=exp.n_hosts,
                params=pr,
                window=window,
                key=key,
                lat_vv=lat_vv,
                loss_vv=loss_vv,
                host_vertex=host_vertex,
                bw_up=bw_up,
                bw_dn=bw_dn,
                model_cfg=exp.model_cfg,
                hosts=hosts,
                loss_thr_vv=loss_thr_vv,
            )
            handlers = model.make_handlers(ctx)

            def exchange(fp):
                # The one collective per window (SURVEY §2.5): tiled gather
                # of every shard's routed packets, shard-major order.
                return jax.tree.map(
                    lambda x: jax.lax.all_gather(x, axis, tiled=True), fp
                )

            init_metrics = st.metrics
            st = jax.lax.fori_loop(
                0, n_windows, lambda _, s: window_step(s, ctx, handlers, exchange), st
            )
            # Each shard accumulated its own partials on top of the (replicated)
            # input metrics; psum then re-subtract the duplicated baseline.
            mfin = jax.tree.map(
                lambda f, i: jax.lax.psum(f, axis) - (n_dev - 1) * i,
                st.metrics,
                init_metrics,
            )
            # ``windows`` advances identically on every shard (replicated, like
            # win_start) — keep the local count rather than the 8× sum.
            return st._replace(metrics=mfin._replace(windows=st.metrics.windows))

        def run(st: SimState, n_windows: int) -> SimState:
            specs = self._state_specs(st)
            f = jax.shard_map(
                lambda s, h, bu, bd: block(s, h, bu, bd, n_windows),
                mesh=self.mesh,
                in_specs=(specs, P(axis), P(axis), P(axis)),
                out_specs=specs,
                check_vma=False,
            )
            return f(st, hosts_g, bw_up_g, bw_dn_g)

        return run

    # -- public ------------------------------------------------------------
    def run(self, st: SimState | None = None, n_windows: int | None = None) -> SimState:
        if st is None:
            st = self.init_state()
        return self._run_jit(st, n_windows if n_windows is not None else self.n_windows)

    metrics_dict = staticmethod(Engine.metrics_dict)

    def model_summary(self, st: SimState):
        return jax.tree.map(np.asarray, self._model.summary(st.model, self.global_ctx))
