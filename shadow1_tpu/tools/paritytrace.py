"""paritytrace — first-divergence bisection between two engine configurations.

The determinism contract says any two executions of the same experiment —
CPU oracle vs TPU engine, sharded vs single-device, pallas vs xla kernels,
checkpoint-resume vs straight-through — produce bit-identical results. When
the contract breaks, the end-of-run parity asserts report one mismatched
counter after millions of windows with zero localization. This tool runs
the two configurations in LOCKSTEP CHUNKS with the determinism flight
recorder on (EngineParams.state_digest, core/digest.py), compares the
per-window per-subsystem digest words as they stream out, and stops at the
FIRST divergent (window, subsystem). It then re-runs both sides to that
window boundary and dumps a structured per-host / per-slot JSONL diff of
the diverging state plane.

    python -m shadow1_tpu.tools.paritytrace CONFIG A B [options]

Side specs (A / B):

    cpu                the sequential oracle
    tpu                single-device batched engine
    sharded[:D]        host-axis sharded over D devices (default: all)
    +pallas            fused pop/push kernels (e.g. tpu+pallas)
    +resume            checkpoint/restore roundtrip at every chunk boundary

Examples:

    paritytrace cfg.yaml tpu cpu                 # engine vs oracle
    paritytrace cfg.yaml tpu sharded:2           # sharding determinism
    paritytrace cfg.yaml tpu tpu+pallas          # kernel A/B
    paritytrace cfg.yaml tpu tpu+resume          # snapshot fidelity

``--inject W[:SUBSYS[:SIDE]]`` corrupts one side's state at the window-W
chunk boundary (default subsystem ``rng``: bump host 0's tie-break
counter; also ``evbuf``/``nic``/``tcp``) — the self-test that the bisector
localizes a single-window corruption to exactly (W, SUBSYS); ci.sh smoke
runs it on the rung-1 config.

Exit codes: 0 = digest streams identical, 3 = divergence found (reported),
2 = usage error.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile

import numpy as np

from shadow1_tpu.core.digest import DIGEST_FIELDS, SUBSYSTEMS


def _pad_p(p, np_cols):
    return tuple(int(p[i]) if i < len(p) else 0 for i in range(np_cols))


# ---------------------------------------------------------------------------
# Sides
# ---------------------------------------------------------------------------

class Side:
    """One configuration under lockstep execution. ``run_to(w)`` advances to
    window w (exclusive); ``digest(w)`` returns that window's digest row;
    ``views()`` returns comparable per-subsystem state views for the dump."""

    spec: str

    def run_to(self, w: int) -> None:
        raise NotImplementedError

    def digest(self, w: int) -> dict:
        raise NotImplementedError

    def views(self) -> dict:
        raise NotImplementedError

    def inject(self, subsys: str) -> None:
        raise NotImplementedError


class OracleSide(Side):
    def __init__(self, exp, params, spec="cpu"):
        from shadow1_tpu.cpu_engine import CpuEngine

        self.spec = spec
        self.params = dataclasses.replace(params, state_digest=1)
        self.eng = CpuEngine(exp, self.params)
        self.done = 0

    def run_to(self, w):
        if w > self.done:
            self.eng.run(n_windows=w)
            self.done = w

    def digest(self, w):
        # digest_rows are appended in window order, one per window.
        return self.eng.digest_rows[w]

    def views(self):
        from shadow1_tpu.core.digest import (TCP_FIELDS_BOOL, TCP_FIELDS_I32,
                                             TCP_FIELDS_I64)
        from shadow1_tpu.consts import NP, TCP_FREE

        eng = self.eng
        ev = {}
        for time, tb, _g, host, kind, p in eng.heap:
            ev[(int(host), int(time), int(tb))] = (int(kind), _pad_p(p, NP))
        rng = {
            "self_ctr": eng.self_ctr.tolist(),
            "pkt_ctr": eng.pkt_ctr.tolist(),
            "cpu_busy": eng.cpu_busy.tolist(),
        }
        nic = tcp = None
        model = eng.model
        if hasattr(model, "socks"):
            nic = {
                "tx_free": model.tx_free.tolist(),
                "rx_free": model.rx_free.tolist(),
                "tx_bytes": model.tx_bytes.tolist(),
                "rx_bytes": model.rx_bytes.tolist(),
                "aqm_ctr": model.aqm_ctr.tolist(),
            }
            tcp = {}
            for h, socks in enumerate(model.socks):
                for s, k in enumerate(socks):
                    if k.st == TCP_FREE:
                        continue
                    d = {f: int(getattr(k, f)) & 0xFFFFFFFF
                         for f in TCP_FIELDS_I32}
                    d.update({f: int(getattr(k, f)) for f in TCP_FIELDS_I64})
                    d.update({f: bool(getattr(k, f)) for f in TCP_FIELDS_BOOL})
                    d["mq"] = sorted(
                        (int(e) & 0xFFFFFFFF, int(m) & 0xFFFFFFFF)
                        for e, m in k.mq
                    )
                    tcp[(h, s)] = d
        elif hasattr(model, "hops"):
            rng["hops"] = model.hops.tolist()
            rng["ctr"] = model.ctr.tolist()
        return {"evbuf": ev, "rng": rng, "nic": nic, "tcp": tcp}

    def inject(self, subsys):
        from shadow1_tpu.core.digest import event_word

        eng = self.eng
        if subsys == "rng":
            eng.self_ctr[0] += 1
        elif subsys == "nic":
            eng.model.tx_bytes[0] += 1
        elif subsys == "tcp":
            for socks in eng.model.socks:
                for k in socks:
                    if k.st:
                        k.ts_seq += 1
                        return
            raise RuntimeError("no live socket to corrupt")
        elif subsys == "evbuf":
            if not eng.heap:
                raise RuntimeError("no pending event to corrupt")
            # Corrupt the latest-time pending event's first payload column
            # (and repair the maintained digest so only the CONTENT changes,
            # exactly like a bit-flip in device memory would).
            i = max(range(len(eng.heap)), key=lambda j: eng.heap[j][:2])
            time, tb, g, host, kind, p = eng.heap[i]
            p = ((int(p[0]) if p else 0) + 1,) + tuple(p[1:])
            eng.heap[i] = (time, tb, g, host, kind, p)
            if eng.digest_on:
                w = event_word(host, time, tb, kind, p)
                eng._ev_dg += w - eng._ev_word[g]
                eng._ev_word[g] = w
        else:
            raise ValueError(subsys)


class BatchSide(Side):
    def __init__(self, exp, params, spec, chunk):
        import jax

        self.spec = spec
        kind, _, mods = spec.partition("+")
        mods = set(mods.split("+")) if mods else set()
        self.resume = "resume" in mods
        mods.discard("resume")
        kw = {}
        if "pallas" in mods:
            kw.update(pop_impl="pallas", push_impl="pallas")
            mods.discard("pallas")
        if mods:
            raise ValueError(f"unknown side modifiers {sorted(mods)!r}")
        # The ring is the digest transport: depth == lockstep chunk so every
        # window drains before it can be overwritten.
        self.params = dataclasses.replace(
            params, state_digest=1, metrics_ring=chunk, **kw
        )
        name, _, ndev = kind.partition(":")
        if name == "tpu":
            from shadow1_tpu.core.engine import Engine

            self.eng = Engine(exp, self.params)
        elif name == "sharded":
            from shadow1_tpu.shard.engine import ShardedEngine

            devices = jax.devices()
            if ndev:
                devices = devices[: int(ndev)]
            self.eng = ShardedEngine(exp, self.params, devices=devices)
        else:
            raise ValueError(f"unknown side kind {kind!r}")
        self.chunk = chunk
        self.st = None
        self.done = 0
        self.rows: dict[int, dict] = {}
        self._tmp = None

    def run_to(self, w):
        from shadow1_tpu.telemetry.ring import drain_ring

        if self.st is None:
            self.st = self.eng.init_state()
        while self.done < w:
            step = min(self.chunk, w - self.done)
            self.st = self.eng.run(self.st, n_windows=step)
            for r in drain_ring(self.st, self.eng.window, start=self.done):
                if r["type"] == "ring":
                    self.rows[r["window"]] = r
            self.done += step
            if self.resume:
                self._roundtrip()

    def _roundtrip(self):
        from shadow1_tpu import ckpt

        if self._tmp is None:
            fd, self._tmp = tempfile.mkstemp(suffix=".npz",
                                             prefix="paritytrace_")
            os.close(fd)
        ckpt.save_state(self.st, self._tmp)
        self.st = ckpt.load_state(self.eng.init_state(), self._tmp)

    def digest(self, w):
        return self.rows[w]

    def _host_state(self):
        import jax

        if self.st is None:  # e.g. --inject 0: corrupt the initial state
            self.st = self.eng.init_state()
        return jax.tree.map(np.asarray, self.st)

    def views(self):
        from shadow1_tpu.core.digest import (TCP_FIELDS_BOOL, TCP_FIELDS_I32,
                                             TCP_FIELDS_I64,
                                             model_host_vectors,
                                             model_vector_names)
        from shadow1_tpu.core.events import tb_join
        from shadow1_tpu.consts import NP, TCP_FREE, K_NONE

        st = self._host_state()
        buf = st.evbuf
        time = np.asarray(tb_join(buf.time_hi, buf.time_lo))
        tb = np.asarray(tb_join(buf.tb_hi, buf.tb_lo))
        ev = {}
        cap, h = buf.kind.shape
        for c, hh in zip(*np.nonzero(buf.kind != K_NONE)):
            ev[(int(hh), int(time[c, hh]), int(tb[c, hh]))] = (
                int(buf.kind[c, hh]),
                tuple(int(buf.p[i, c, hh]) for i in range(NP)),
            )
        rng = {
            "self_ctr": buf.self_ctr.tolist(),
            "pkt_ctr": st.outbox.pkt_ctr.tolist(),
            "cpu_busy": st.cpu_busy.tolist(),
        }
        for name, vec in zip(model_vector_names(st.model),
                             model_host_vectors(st.model)):
            rng[name] = np.asarray(vec).tolist()
        nic = tcp = None
        mf = getattr(st.model, "_fields", ())
        if "nic" in mf and "tcp" in mf:
            n = st.model.nic
            nic = {
                "tx_free": n.tx_free.tolist(),
                "rx_free": n.rx_free.tolist(),
                "tx_bytes": n.tx_bytes.tolist(),
                "rx_bytes": n.rx_bytes.tolist(),
                "aqm_ctr": n.aqm_ctr.tolist(),
            }
            t = st.model.tcp
            tcp = {}
            for s, hh in zip(*np.nonzero(np.asarray(t["st"]) != TCP_FREE)):
                d = {f: int(np.asarray(t[f])[s, hh]) & 0xFFFFFFFF
                     for f in TCP_FIELDS_I32}
                for f in TCP_FIELDS_I64:
                    d[f] = int(np.asarray(
                        tb_join(t[f + "_hi"], t[f + "_lo"]))[s, hh])
                d.update({f: bool(np.asarray(t[f])[s, hh])
                          for f in TCP_FIELDS_BOOL})
                mqv = np.asarray(t["mq_valid"])[:, s, hh]
                d["mq"] = sorted(
                    (int(np.asarray(t["mq_end"])[q, s, hh]) & 0xFFFFFFFF,
                     int(np.asarray(t["mq_meta"])[q, s, hh]) & 0xFFFFFFFF)
                    for q in np.nonzero(mqv)[0]
                )
                tcp[(int(hh), int(s))] = d
        return {"evbuf": ev, "rng": rng, "nic": nic, "tcp": tcp}

    def inject(self, subsys):
        from shadow1_tpu.consts import K_NONE, TCP_FREE

        st = self._host_state()
        if subsys == "rng":
            v = st.evbuf.self_ctr.copy()
            v[0] += 1
            st = st._replace(evbuf=st.evbuf._replace(self_ctr=v))
        elif subsys == "evbuf":
            occ = np.nonzero(st.evbuf.kind != K_NONE)
            if not len(occ[0]):
                raise RuntimeError("no pending event to corrupt")
            p = st.evbuf.p.copy()
            p[0, occ[0][0], occ[1][0]] += 1
            st = st._replace(evbuf=st.evbuf._replace(p=p))
        elif subsys == "nic":
            v = st.model.nic.tx_bytes.copy()
            v[0] += 1
            st = st._replace(model=st.model._replace(
                nic=st.model.nic._replace(tx_bytes=v)))
        elif subsys == "tcp":
            t = dict(st.model.tcp)
            live = np.nonzero(np.asarray(t["st"]) != TCP_FREE)
            if not len(live[0]):
                raise RuntimeError("no live socket to corrupt")
            v = t["ts_seq"].copy()
            v[live[0][0], live[1][0]] += 1
            t["ts_seq"] = v
            st = st._replace(model=st.model._replace(tcp=t))
        else:
            raise ValueError(subsys)
        self.st = self.eng.place_state(st)


def make_side(spec: str, exp, params, chunk: int) -> Side:
    if spec.partition("+")[0] == "cpu":
        if "+" in spec:
            raise ValueError("the cpu oracle takes no modifiers")
        return OracleSide(exp, params, spec)
    return BatchSide(exp, params, spec, chunk)


# ---------------------------------------------------------------------------
# Lockstep bisection
# ---------------------------------------------------------------------------

def bisect(a: Side, b: Side, n_windows: int, chunk: int,
           inject=None, log=lambda *a: None):
    """Run both sides in lockstep chunks; return (window, [subsystems]) of
    the first digest divergence, or None. ``inject`` is (window, subsys,
    side) applied at that window's chunk boundary."""
    done = 0
    injected = False
    while done < n_windows:
        if inject and not injected and done == inject[0]:
            side = a if inject[2] == "a" else b
            side.inject(inject[1])
            injected = True
            log(f"injected {inject[1]} corruption into side "
                f"{inject[2]} ({side.spec}) at window {done}")
        target = min(done + chunk, n_windows)
        if inject and not injected:
            target = min(target, inject[0])
        a.run_to(target)
        b.run_to(target)
        for w in range(done, target):
            da, db = a.digest(w), b.digest(w)
            diff = [s for s, f in zip(SUBSYSTEMS, DIGEST_FIELDS)
                    if int(da[f]) != int(db[f])]
            if diff:
                return w, diff
        log(f"windows [{done}, {target}) identical")
        done = target
    return None


# ---------------------------------------------------------------------------
# Divergence dump (the per-host / per-slot localization)
# ---------------------------------------------------------------------------

def _diff_keyed(sub, va, vb, emit, max_records):
    """Diff two {key: value} views; emit a_only / b_only / changed rows."""
    n = 0
    for key in sorted(set(va) | set(vb)):
        ka = va.get(key)
        kb = vb.get(key)
        if ka == kb:
            continue
        if n >= max_records:  # a further REAL difference exists beyond the cap
            emit({"type": "plane_diff_truncated", "subsystem": sub})
            return n
        rec = {"type": "plane_diff", "subsystem": sub,
               "key": list(key) if isinstance(key, tuple) else key}
        if ka is None:
            rec["side"] = "b_only"
            rec["b"] = kb
        elif kb is None:
            rec["side"] = "a_only"
            rec["a"] = ka
        else:
            rec["side"] = "changed"
            if isinstance(ka, dict):
                rec["fields"] = {
                    f: {"a": ka[f], "b": kb[f]}
                    for f in ka if ka.get(f) != kb.get(f)
                }
            else:
                rec["a"], rec["b"] = ka, kb
        emit(rec)
        n += 1
    return n


def _diff_vectors(sub, va, vb, emit, max_records):
    """Diff two {name: [per-host values]} views; one row per differing host."""
    n = 0
    for name in sorted(set(va) | set(vb)):
        xa = va.get(name, [])
        xb = vb.get(name, [])
        for h in range(max(len(xa), len(xb))):
            ea = xa[h] if h < len(xa) else None
            eb = xb[h] if h < len(xb) else None
            if ea != eb:
                if n >= max_records:  # a further real difference beyond cap
                    emit({"type": "plane_diff_truncated", "subsystem": sub})
                    return n
                emit({"type": "plane_diff", "subsystem": sub, "field": name,
                      "host": h, "a": ea, "b": eb})
                n += 1
    return n


def dump_divergence(a: Side, b: Side, window: int, subsystems, emit,
                    max_records: int = 200) -> int:
    """Re-derive both sides' state at the end of ``window`` (the caller ran
    them there) and emit the structured diff of each diverging plane."""
    va, vb = a.views(), b.views()
    total = 0
    for sub in subsystems:
        if sub == "outbox":
            # The outbox is cleared by the window-end delivery, so its
            # contents cannot be read back from a window-boundary state;
            # the scattered packets ARE next window's evbuf entries.
            emit({"type": "plane_note", "subsystem": "outbox",
                  "note": "outbox sends are consumed at the window-end "
                          "exchange; diffing the evbuf (delivered packets) "
                          "and rng (pkt_ctr) planes instead"})
            total += _diff_keyed("evbuf", va["evbuf"], vb["evbuf"], emit,
                                 max_records)
            total += _diff_vectors("rng", va["rng"], vb["rng"], emit,
                                   max_records)
        elif sub == "evbuf":
            total += _diff_keyed("evbuf", va["evbuf"], vb["evbuf"], emit,
                                 max_records)
        elif sub == "tcp":
            total += _diff_keyed("tcp", va["tcp"] or {}, vb["tcp"] or {},
                                 emit, max_records)
        elif sub == "nic":
            total += _diff_vectors("nic", va["nic"] or {}, vb["nic"] or {},
                                   emit, max_records)
        elif sub == "rng":
            total += _diff_vectors("rng", va["rng"], vb["rng"], emit,
                                   max_records)
    return total


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _parse_inject(s: str | None):
    if s is None:
        return None
    parts = s.split(":")
    w = int(parts[0])
    subsys = parts[1] if len(parts) > 1 else "rng"
    side = parts[2] if len(parts) > 2 else "b"
    if subsys not in SUBSYSTEMS or subsys == "outbox":
        raise SystemExit(f"--inject subsystem must be one of "
                         f"{[s for s in SUBSYSTEMS if s != 'outbox']}")
    if side not in ("a", "b"):
        raise SystemExit("--inject side must be a or b")
    return (w, subsys, side)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shadow1_tpu.tools.paritytrace",
        description="lockstep digest comparison + first-divergence bisection",
    )
    ap.add_argument("config", help="YAML experiment file")
    ap.add_argument("side_a", help="cpu | tpu | sharded[:D] (+pallas/+resume)")
    ap.add_argument("side_b", help="same grammar as side A")
    ap.add_argument("--windows", type=int, default=None,
                    help="compare this many windows (default: the full run)")
    ap.add_argument("--chunk", type=int, default=64,
                    help="lockstep chunk in windows (= digest ring depth)")
    ap.add_argument("--inject", default=None, metavar="W[:SUBSYS[:SIDE]]",
                    help="corrupt one side at window W (self-test; default "
                         "subsystem rng, default side b)")
    ap.add_argument("--dump", default=None, metavar="PATH",
                    help="write the divergence plane diff as JSONL here "
                         "(default: stderr)")
    ap.add_argument("--max-diff", type=int, default=200,
                    help="cap on emitted plane-diff records")
    ap.add_argument("--no-localize", action="store_true",
                    help="report the first divergent (window, subsystem) "
                         "only; skip the re-run and plane dump")
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu.config.experiment import load_experiment

    exp, params, _scheduler = load_experiment(args.config)
    n_windows = args.windows or int(-(-exp.end_time // exp.window))
    chunk = max(1, min(args.chunk, n_windows))
    inject = _parse_inject(args.inject)
    if inject and inject[0] >= n_windows:
        raise SystemExit("--inject window is past the compared range")

    def log(msg):
        print(f"[paritytrace] {msg}", file=sys.stderr, flush=True)

    log(f"A = {args.side_a}, B = {args.side_b}, {n_windows} windows, "
        f"chunk {chunk}")
    a = make_side(args.side_a, exp, params, chunk)
    b = make_side(args.side_b, exp, params, chunk)
    hit = bisect(a, b, n_windows, chunk, inject=inject, log=log)

    result = {
        "type": "paritytrace",
        "config": args.config,
        "sides": [args.side_a, args.side_b],
        "windows_compared": n_windows if hit is None else hit[0] + 1,
        "first_divergence": None,
        "injected": list(inject) if inject else None,
    }
    if hit is None:
        log(f"digest streams identical over {n_windows} windows")
        print(json.dumps(result))
        return 0

    window, subsystems = hit
    result["first_divergence"] = {"window": window, "subsystems": subsystems}
    log(f"FIRST DIVERGENCE at window {window}: {', '.join(subsystems)}")

    if not args.no_localize:
        # Re-run both sides fresh to the divergent window's boundary (the
        # runs are deterministic, so the states reproduce exactly) and dump
        # the diverging plane(s) element by element.
        log(f"re-running both sides to window {window} for the plane dump")
        a2 = make_side(args.side_a, exp, params, chunk)
        b2 = make_side(args.side_b, exp, params, chunk)
        for s2 in (a2, b2):
            side_tag = "a" if s2 is a2 else "b"
            if inject and inject[2] == side_tag:
                s2.run_to(inject[0])
                s2.inject(inject[1])
            s2.run_to(window + 1)
        out = open(args.dump, "w") if args.dump else sys.stderr

        def emit(rec):
            print(json.dumps(rec), file=out, flush=True)

        emit(result)
        n = dump_divergence(a2, b2, window, subsystems, emit,
                            max_records=args.max_diff)
        if args.dump:
            out.close()
            log(f"wrote {n} plane-diff records to {args.dump}")
        result["diff_records"] = n
    print(json.dumps(result))
    return 3


if __name__ == "__main__":
    sys.exit(main())
