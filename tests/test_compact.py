"""Active-host compaction (core/compact.py): bit-parity with the full path.

The compaction contract is strict identity — same pops, same handler
order, same RNG draws, same metrics (including engine-only counters like
``rounds``) — whether or not a window ran compacted, and regardless of the
bucket size. These tests compare compact_cap engines against the plain
engine AND the CPU oracle, on phold (dense-ish, exercises the full-width
fallback) and on the lossy-TCP net model (the sparse workload the knob
exists for).
"""

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine


def _phold_exp(n_hosts=24, seed=11):
    return single_vertex_experiment(
        n_hosts=n_hosts, seed=seed, end_time=1 * SEC, latency_ns=10 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": 20 * MS, "init_events": 2},
    )


@pytest.mark.parametrize("cap", [8, 16])
def test_phold_compact_parity(cap):
    """PHOLD keeps most hosts active — windows straddle the bucket bound,
    exercising both the compact branch and the full-width fallback."""
    exp = _phold_exp()
    base = EngineParams(ev_cap=64, outbox_cap=64)
    plain = Engine(exp, base).run()
    comp_eng = Engine(
        exp, EngineParams(ev_cap=64, outbox_cap=64, compact_cap=cap)
    )
    comp = comp_eng.run()
    pm, cm = Engine.metrics_dict(plain), Engine.metrics_dict(comp)
    assert pm == cm
    np.testing.assert_array_equal(
        np.asarray(comp_eng.model_summary(comp)["hops"]),
        np.asarray(Engine(exp, base).model_summary(plain)["hops"]),
    )
    for a, b in zip(
        [plain.evbuf.abs_time(), plain.evbuf.kind, plain.cpu_busy],
        [comp.evbuf.abs_time(), comp.evbuf.kind, comp.cpu_busy],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _net_doc(n_hosts=40, loss=0.02):
    return {
        "general": {"seed": 29, "stop_time": "4 s"},
        "engine": {
            "scheduler": "tpu", "ev_cap": 64, "outbox_cap": 32,
            "sockets_per_host": 4, "msgq_cap": 8,
        },
        "network": {"single_vertex": {"latency": "25 ms", "loss": loss}},
        "hosts": [
            {"name": "server", "count": 2,
             "bandwidth_up": "10 Mbit", "bandwidth_down": "10 Mbit"},
            {"name": "client", "count": n_hosts - 2,
             "bandwidth_up": "10 Mbit", "bandwidth_down": "10 Mbit"},
        ],
        "app": {
            "model": "filexfer",
            "groups": {
                "server": {"role": 0},
                "client": {"role": 1, "server": "@server",
                           "flow_bytes": 40000, "flow_count": 2,
                           "start_time": "50 ms"},
            },
        },
    }


def test_net_compact_parity_vs_oracle():
    """Lossy TCP file transfers: only a handful of the 40 hosts are active
    per window — the design-point workload. Compact engine must match the
    CPU oracle bit-for-bit on the semantic counter set."""
    from shadow1_tpu.config.experiment import build_experiment

    exp, params, _ = build_experiment(_net_doc())
    import dataclasses

    cparams = dataclasses.replace(params, compact_cap=16)
    cpu = CpuEngine(exp, params)
    cm = cpu.run()
    eng = Engine(exp, cparams)
    st = eng.run()
    tm = Engine.metrics_dict(st)
    assert tm["ev_overflow"] == 0 and cm["ev_overflow"] == 0
    for k in ["events", "pkts_sent", "pkts_delivered", "pkts_lost",
              "tcp_fast_rtx", "tcp_rto", "tcp_ooo_drops"]:
        assert tm[k] == cm[k], (k, tm[k], cm[k])
    ts, cs = eng.model_summary(st), cpu.summary()
    np.testing.assert_array_equal(
        np.asarray(ts["rx_bytes"]), np.asarray(cs["rx_bytes"])
    )


def test_net_compact_matches_plain_engine():
    """Engine-vs-engine: identical final state pytrees (stronger than the
    counter set — catches state corruption in gather/scatter)."""
    from shadow1_tpu.config.experiment import build_experiment
    import dataclasses
    import jax

    exp, params, _ = build_experiment(_net_doc(loss=0.0))
    st_a = Engine(exp, params).run()
    st_b = Engine(exp, dataclasses.replace(params, compact_cap=12)).run()

    def cmp(a, b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    jax.tree.map(cmp, st_a, st_b)


@pytest.mark.slow  # tier-1 wall budget (PR 4): heaviest of its family;
# a faster sibling keeps the coverage in the fast tier; ./ci.sh all runs it.
def test_tor_compact_parity():
    """Tor: the widest model state (relay tables, circuit maps, cell
    streams) through the gather/scatter round-trip, vs the plain engine."""
    import jax
    from tests.test_tor_parity import tor_exp, PARAMS
    import dataclasses

    exp = tor_exp(end=10 * SEC)
    st_a = Engine(exp, PARAMS).run()
    st_b = Engine(exp, dataclasses.replace(PARAMS, compact_cap=12)).run()

    def cmp(a, b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    jax.tree.map(cmp, st_a, st_b)


@pytest.mark.slow  # tier-1 wall budget (PR 4): heaviest of its family;
# a faster sibling keeps the coverage in the fast tier; ./ci.sh all runs it.
def test_sharded_compact_parity():
    """Compaction inside shard_map: each shard compacts its local block;
    results must equal the plain single-device engine. Sparse TCP traffic
    (few active clients per window) so the per-shard compact branch
    genuinely fires (global cap 64 → 8 lanes/shard < h_local 16)."""
    from shadow1_tpu.config.experiment import build_experiment
    import dataclasses
    from tests.test_shard_parity import run_pair, assert_same

    exp, params, _ = build_experiment(_net_doc(n_hosts=128))
    params = dataclasses.replace(params, compact_cap=64)
    m1, s1, m8, s8 = run_pair(exp, params)
    assert_same(m1, s1, m8, s8, ["rx_bytes"])
