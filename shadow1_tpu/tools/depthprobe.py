"""Per-window event-chain depth — sizing data for k-wide round delivery.

    python -m shadow1_tpu.tools.depthprobe CONFIG.yaml [--windows N]

The batched engine pops ONE event per host per round, so a window's round
count is the busiest host's event count (rung-3 Tor: ~47 rounds/window).
The candidate structural fix (VERDICT r4 #4, "k-wide delivery") would pop
one event per (host, chain) per round, where a *chain* is a serially-
dependent event stream — per-socket TCP traffic, the per-host app stream.
Whether that is worth building depends entirely on the chain-depth
distribution: if the busiest host's events mostly sit on ONE socket
(deep chains), k-wide buys little; if they spread across sockets
(shallow, wide), it collapses the round count.

This tool replays the CPU oracle with per-(window, host, chain)
accounting and prints both depth proxies:

    rounds_now   = max events per (host, window)     — today's round count
    rounds_kwide = max chain depth per (host, window) — the k-wide floor

The k-wide floor is OPTIMISTIC: it assumes cross-chain effects on shared
host state (the NIC uplink clock, RNG draw order, app-level shared
buffers) can be made order-insensitive or rank-serialized within a round,
which is exactly the hard part of building it. Chains: packet-delivery /
timer / tx-resume events key by their socket (payload meta), app wakeups
and NIC-batch conversions key to one per-host chain each.
"""

from __future__ import annotations

import argparse
import heapq
import json
from collections import Counter

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("--windows", type=int, default=None)
    args = ap.parse_args()

    # Oracle-only tool: never touch the accelerator (a wedged tunnel
    # hangs jax init — platform.py); the CPU platform is forced before any
    # jax array exists.
    from shadow1_tpu.platform import force_cpu

    force_cpu(1)
    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.consts import (
        K_APP,
        K_PKT,
        K_PKT_DELIVER,
        K_TCP_TIMER,
        K_TX_RESUME,
    )
    from shadow1_tpu.cpu_engine import CpuEngine

    exp, params, _ = load_experiment(args.config)
    eng = CpuEngine(exp, params)
    W = eng.window
    n_win = args.windows if args.windows is not None else eng.n_windows
    end = n_win * W

    rx_batch = getattr(eng.model, "rx_batch", False)
    ev_per_hw: dict[int, Counter] = {}      # window -> Counter[host]
    chain_per_hw: dict[int, Counter] = {}   # window -> Counter[(host, chain)]

    def chain_key(kind, p):
        if kind == K_PKT_DELIVER:
            return ("sock", (p[1] >> 8) & 0xFF)   # dst socket of the segment
        if kind in (K_TCP_TIMER, K_TX_RESUME):
            return ("sock", p[0] & 0xFF)          # event's own socket field
        if kind == K_APP:
            return ("app",)
        if kind == K_PKT:
            return ("nic",)                       # FIFO rx clock is serial
        return ("other", kind)

    heap, model = eng.heap, eng.model
    while heap and heap[0][0] < end:
        time, tb, _g, host, kind, p = heapq.heappop(heap)
        eng.pending[host] -= 1
        if eng.has_stop and eng._down_at(host, time):
            continue
        w = time // W
        if kind == K_PKT and rx_batch:
            model.rx_convert(host, time, tb, p)
            continue
        if eng.has_cpu:
            eff = max(time, int(eng.cpu_busy[host]))
            if eff >= (time // W + 1) * W:
                eng.pending[host] += 1
                heapq.heappush(heap, (eff, tb, eng._gseq, host, kind, p))
                eng._gseq += 1
                continue
            eng.cpu_busy[host] = eff + int(eng.cpu_cost[host])
            time = eff
            w = time // W
        ev_per_hw.setdefault(w, Counter())[host] += 1
        chain_per_hw.setdefault(w, Counter())[(host, chain_key(kind, p))] += 1
        model.handle(host, time, kind, p)

    wins = sorted(ev_per_hw)
    now = np.array([max(ev_per_hw[w].values()) for w in wins])
    kwide = []
    for w in wins:
        per_host: Counter = Counter()
        for (host, _c), n in chain_per_hw[w].items():
            per_host[host] = max(per_host[host], n)
        kwide.append(max(per_host.values()))
    kwide = np.array(kwide)
    pct = lambda a, q: int(np.percentile(a, q)) if len(a) else 0
    print(json.dumps({
        "config": args.config,
        "windows": len(wins),
        "events": int(sum(sum(c.values()) for c in ev_per_hw.values())),
        "rounds_now_mean": round(float(now.mean()), 1) if len(now) else 0,
        "rounds_now_p90": pct(now, 90),
        "rounds_now_max": int(now.max()) if len(now) else 0,
        "rounds_kwide_mean": round(float(kwide.mean()), 1) if len(kwide) else 0,
        "rounds_kwide_p90": pct(kwide, 90),
        "rounds_kwide_max": int(kwide.max()) if len(kwide) else 0,
        "kwide_speedup_mean": round(float(now.sum() / max(kwide.sum(), 1)), 2),
    }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
