"""churnprobe — fault-plane scenario runner and cross-engine verdict.

    python -m shadow1_tpu.tools.churnprobe CONFIG [options]

Runs a faulted experiment (a config with a ``faults:`` section — e.g.
``configs/churn_filexfer.yaml``) on multiple engines with the determinism
flight recorder on, and verifies the two properties a churn experiment
must have before its results mean anything:

1. **digest-stream parity** — the per-window state digests
   (core/digest.py) are bit-identical across every requested side
   (default: cpu, tpu, and sharded over all local devices when >1). The
   fault plane is only trustworthy if killing hosts and links perturbs
   every engine identically; the digest stream is the per-window proof.
2. **drop accounting** — every routed packet is accounted for:
   ``pkts_sent == pkts_delivered + pkts_lost + link_down_pkts + down_pkts
   + ev_overflow_deliveries + x2x_overflow`` (the delivery-side overflow
   share is folded in via the counters). No silent event loss under churn.

Prints one JSON verdict to stdout. Exit codes: 0 = all sides agree and
accounting closes, 3 = divergence or accounting hole, 2 = usage error.

Side specs: ``cpu``, ``tpu``, ``sharded[:D]`` (same grammar as
tools/paritytrace.py; use paritytrace to BISECT a divergence this probe
reports).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from shadow1_tpu.core.digest import DIGEST_FIELDS
# The drop-accounting identity lives in the transactional plane now
# (shadow1_tpu/txn.py) so `--selfcheck` runs it at every chunk/window
# boundary of ANY run; this probe keeps using the same shared check.
from shadow1_tpu.txn import accounting

# Counters every side must agree on (includes the fault-plane set).
VERDICT_KEYS = (
    "events", "pkts_sent", "pkts_delivered", "pkts_lost", "link_down_pkts",
    "down_pkts", "down_events", "host_restarts", "tcp_rto", "tcp_fast_rtx",
    "tcp_ooo_drops", "ev_overflow", "ob_overflow",
)


def _digest_rows_cpu(exp, params, n_windows):
    from shadow1_tpu.cpu_engine import CpuEngine

    eng = CpuEngine(exp, params)
    metrics = eng.run(n_windows=n_windows)
    rows = {r["window"]: tuple(r[f] for f in DIGEST_FIELDS)
            for r in eng.digest_rows}
    return metrics, rows


def _digest_rows_batch(engine, n_windows, chunk):
    """Chunked run draining the telemetry ring each boundary — the full
    per-window digest stream regardless of run length."""
    from shadow1_tpu.ckpt import run_chunked
    from shadow1_tpu.telemetry.ring import drain_ring

    rows: dict[int, tuple] = {}
    start = [0]

    def on_chunk(st, _done):
        for r in drain_ring(st, engine.window, start=start[0]):
            if r["type"] == "ring":
                rows[r["window"]] = tuple(r[f] for f in DIGEST_FIELDS)
        start[0] = int(st.metrics.windows)

    st = run_chunked(engine, n_windows=n_windows, chunk=chunk,
                     on_chunk=on_chunk)
    return type(engine).metrics_dict(st), rows


def run_side(spec, exp, params, n_windows, chunk):
    params = dataclasses.replace(params, state_digest=1,
                                 metrics_ring=max(params.metrics_ring, chunk))
    if spec == "cpu":
        return _digest_rows_cpu(exp, params, n_windows)
    if spec == "tpu":
        from shadow1_tpu.core.engine import Engine

        return _digest_rows_batch(Engine(exp, params), n_windows, chunk)
    if spec.startswith("sharded"):
        import jax

        from shadow1_tpu.shard.engine import ShardedEngine

        _, _, d = spec.partition(":")
        devs = jax.devices()[: int(d)] if d else None
        return _digest_rows_batch(ShardedEngine(exp, params, devices=devs),
                                  n_windows, chunk)
    raise SystemExit(f"unknown side spec {spec!r}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="churnprobe", description=__doc__)
    ap.add_argument("config")
    ap.add_argument("--sides", default=None,
                    help="comma list of cpu|tpu|sharded[:D] "
                         "(default: cpu,tpu[,sharded when >1 device])")
    ap.add_argument("--windows", type=int, default=None)
    ap.add_argument("--chunk", type=int, default=64)
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401 (x64 first)
    from shadow1_tpu.config.experiment import load_experiment

    exp, params, _ = load_experiment(args.config)
    if exp.faults is None:
        print(json.dumps({"error": "config has no faults: section — "
                          "churnprobe verifies the fault plane"}))
        return 2
    n_windows = args.windows
    if n_windows is None:
        n_windows = int(-(-exp.end_time // exp.window))
    sides = args.sides.split(",") if args.sides else None
    if sides is None:
        import jax

        sides = ["cpu", "tpu"]
        if len(jax.devices()) > 1 and exp.n_hosts % len(jax.devices()) == 0:
            sides.append(f"sharded:{len(jax.devices())}")

    results = {}
    for s in sides:
        metrics, rows = run_side(s, exp, params, n_windows, args.chunk)
        results[s] = (dict(metrics), rows)

    ref_spec = sides[0]
    ref_m, ref_rows = results[ref_spec]
    verdict: dict = {
        "config": args.config,
        "windows": n_windows,
        "sides": sides,
        "counters": {s: {k: int(m.get(k, 0)) for k in VERDICT_KEYS}
                     for s, (m, _r) in results.items()},
        "accounting": {s: accounting(m) for s, (m, _r) in results.items()},
    }
    ok = all(v["closes"] for v in verdict["accounting"].values())
    first_div = None
    for s in sides[1:]:
        m, rows = results[s]
        for k in VERDICT_KEYS:
            if int(m.get(k, 0)) != int(ref_m.get(k, 0)):
                ok = False
        common = sorted(set(ref_rows) & set(rows))
        verdict.setdefault("digest_windows_compared", {})[s] = len(common)
        for w in common:
            if rows[w] != ref_rows[w]:
                subs = [DIGEST_FIELDS[i][3:] for i in range(len(DIGEST_FIELDS))
                        if rows[w][i] != ref_rows[w][i]]
                first_div = {"window": w, "side": s, "subsystems": subs}
                ok = False
                break
        if first_div:
            break
    if first_div:
        verdict["first_divergence"] = first_div
        verdict["hint"] = (f"bisect with: python -m shadow1_tpu.tools."
                           f"paritytrace {args.config} {ref_spec} "
                           f"{first_div['side']}")
    verdict["ok"] = ok
    print(json.dumps(verdict))
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
