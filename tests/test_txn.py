"""Overflow-safe execution: transactional chunk retry, structured failure
taxonomy, and in-run self-checks (shadow1_tpu/txn.py).

The contract under test (docs/SEMANTICS.md "Capacities" overflow-recovery):
a deliberately under-capped run under ``--on-overflow retry`` discards every
tainted chunk, grows the offending cap one ladder step, replays the chunk
from the saved chunk-start state — and its digest stream bit-matches a
straight run of the same config at the final (grown) caps, on the cpu, tpu
and sharded engines. ``halt`` raises the structured CapacityExceededError
with paste-ready advice; the supervisor classifies that exit instead of
crash-looping; ``--selfcheck`` guards the drop-accounting identity on
every run.
"""

import json
import os
import subprocess
import sys

import pytest

from shadow1_tpu.ckpt import load_state, run_chunked, snapshot_caps
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.digest import DIGEST_FIELDS
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.obs import run_with_heartbeat
from shadow1_tpu.telemetry.ring import drain_ring
from shadow1_tpu.txn import (
    EXIT_CAPACITY,
    CapacityExceededError,
    OverflowGuard,
    SelfCheckError,
    check_boundary_identity,
)

N_WINDOWS = 40
CHUNK = 10
SMALL_CAP = 8  # overflows this workload (ev_max_fill reaches 14)


def phold_exp():
    """8-host PHOLD whose event concentration overflows ev_cap=8 within the
    first chunk (seed-pinned; init seeds 6 events/host, far under the cap,
    so all overflow is IN-window — the transactional case)."""
    return single_vertex_experiment(
        n_hosts=8, seed=5, end_time=N_WINDOWS * MS, latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 6},
    )


def params(ev_cap, **kw):
    return EngineParams(ev_cap=ev_cap, metrics_ring=CHUNK, state_digest=1,
                        **kw)


def digest_stream(eng, guard=None, n_windows=N_WINDOWS, st=None):
    """(window → digest tuple, final state) via the chunked runner, draining
    the telemetry ring at every COMMITTED boundary."""
    rows, start = {}, [int(st.metrics.windows) if st is not None else 0]

    def on_chunk(s, _done):
        for r in drain_ring(s, eng.window, start=start[0]):
            if r["type"] == "ring":
                rows[r["window"]] = tuple(r[f] for f in DIGEST_FIELDS)
        start[0] = int(s.metrics.windows)

    out = run_chunked(eng, st, n_windows=n_windows, chunk=CHUNK, guard=guard,
                      on_chunk=on_chunk)
    return rows, out


# ---------------------------------------------------------------------------
# The acceptance criterion: retry ≡ straight big-cap, cpu↔tpu↔sharded
# ---------------------------------------------------------------------------

def test_forced_overflow_retry_bitmatches_bigcap_straight():
    exp = phold_exp()
    # Sanity: the workload genuinely overflows the small cap.
    st_lossy = Engine(exp, EngineParams(ev_cap=SMALL_CAP)).run(
        n_windows=N_WINDOWS)
    assert int(st_lossy.metrics.ev_overflow) > 0

    eng = Engine(exp, params(SMALL_CAP))
    guard = OverflowGuard(eng, make_engine=lambda p: Engine(exp, p),
                          mode="retry")
    rows_retry, st_retry = digest_stream(eng, guard)
    assert guard.chunk_retries >= 1
    assert guard.retry_windows_rerun >= CHUNK
    final_cap = guard.final_caps["ev_cap"]
    assert final_cap > SMALL_CAP
    # Every committed chunk is overflow-free — that is what commit means.
    assert int(st_retry.metrics.ev_overflow) == 0
    assert len(rows_retry) == N_WINDOWS

    # Straight big-cap truth, all three engines.
    rows_tpu, st_tpu = digest_stream(Engine(exp, params(final_cap)))
    assert rows_retry == rows_tpu
    assert Engine.metrics_dict(st_retry) == Engine.metrics_dict(st_tpu)

    ce = CpuEngine(exp, params(final_cap))
    cm = ce.run(n_windows=N_WINDOWS)
    rows_cpu = {r["window"]: tuple(r[f] for f in DIGEST_FIELDS)
                for r in ce.digest_rows}
    assert set(rows_cpu) == set(rows_retry)
    assert rows_cpu == rows_retry
    for k in ("events", "pkts_sent", "pkts_delivered", "pkts_lost",
              "ev_overflow"):
        assert cm[k] == Engine.metrics_dict(st_retry)[k], k

    from shadow1_tpu.shard.engine import ShardedEngine

    rows_sh, _ = digest_stream(ShardedEngine(exp, params(final_cap)))
    assert rows_sh == rows_retry


def test_sharded_retry_all_shards_together():
    """The guard drives the sharded engine too: overflow deltas are psum'd
    (every shard agrees on the global count), the grown engine reshards the
    migrated state, and the replayed stream matches the single-device
    retry run exactly."""
    from shadow1_tpu.shard.engine import ShardedEngine

    exp = phold_exp()
    eng = ShardedEngine(exp, params(SMALL_CAP, on_overflow="retry"))
    guard = OverflowGuard(eng, make_engine=lambda p: ShardedEngine(exp, p),
                          mode="retry")
    rows_sh, st_sh = digest_stream(eng, guard)
    assert guard.chunk_retries >= 1
    assert int(st_sh.metrics.ev_overflow) == 0

    eng1 = Engine(exp, params(SMALL_CAP))
    g1 = OverflowGuard(eng1, make_engine=lambda p: Engine(exp, p),
                       mode="retry")
    rows_1, _ = digest_stream(eng1, g1)
    assert guard.final_caps["ev_cap"] == g1.final_caps["ev_cap"]
    assert rows_sh == rows_1


def test_retry_grows_outbox_cap_for_drop_counted_models():
    """ob_overflow drives the same transaction for models whose outbox use
    is drop-counted (PHOLD — the docs/SEMANTICS.md outbox_cap caveat names
    the flow-controlled TCP boundary where this would NOT be bit-exact)."""
    import dataclasses

    exp = phold_exp()
    p_small = dataclasses.replace(params(32), outbox_cap=4)
    st_lossy = Engine(exp, p_small).run(n_windows=N_WINDOWS)
    assert int(st_lossy.metrics.ob_overflow) > 0

    eng = Engine(exp, p_small)
    guard = OverflowGuard(eng, make_engine=lambda p: Engine(exp, p),
                          mode="retry")
    rows, st = digest_stream(eng, guard)
    assert guard.chunk_retries >= 1
    assert int(st.metrics.ob_overflow) == 0
    ob_final = guard.final_caps["outbox_cap"]
    assert ob_final > 4
    rows_ref, _ = digest_stream(
        Engine(exp, dataclasses.replace(params(32), outbox_cap=ob_final)))
    assert rows == rows_ref


# ---------------------------------------------------------------------------
# Checkpoint/resume through a retried run
# ---------------------------------------------------------------------------

def test_resume_from_ckpt_of_retried_run_bit_identical(tmp_path):
    """A checkpoint taken mid-run after retries were replayed is saved at
    the GROWN caps; the respawn recipe (rebuild the engine at the
    snapshot's caps — ckpt.snapshot_caps, as cli.py does under retry) must
    continue the digest stream bit-identically to the straight big-cap
    run."""
    exp = phold_exp()
    path = str(tmp_path / "retry.npz")

    eng = Engine(exp, params(SMALL_CAP))
    guard = OverflowGuard(eng, make_engine=lambda p: Engine(exp, p),
                          mode="retry")
    st, hb = run_with_heartbeat(eng, n_windows=N_WINDOWS // 2,
                                every_windows=CHUNK, stream=False,
                                ckpt_path=path, ckpt_every_s=0.0,
                                guard=guard)
    assert guard.chunk_retries >= 1  # the snapshot postdates a retry
    rows = {r["window"]: tuple(r[f] for f in DIGEST_FIELDS)
            for r in hb.ring_records if r["type"] == "ring"}

    # Supervised-respawn recipe: engine at the snapshot's caps, then resume.
    snap = snapshot_caps(Engine(exp, params(SMALL_CAP)).init_state(), path)
    assert snap is not None and snap[0] > SMALL_CAP
    eng2 = Engine(exp, params(snap[0], outbox_cap=snap[1]))
    st2 = load_state(eng2.init_state(), path)
    guard2 = OverflowGuard(eng2, make_engine=lambda p: Engine(exp, p),
                           mode="retry")
    st2, hb2 = run_with_heartbeat(eng2, st2, n_windows=N_WINDOWS // 2,
                                  every_windows=CHUNK, stream=False,
                                  guard=guard2)
    for r in hb2.ring_records:
        if r["type"] == "ring":
            rows[r["window"]] = tuple(r[f] for f in DIGEST_FIELDS)

    rows_ref, st_ref = digest_stream(
        Engine(exp, params(guard2.final_caps["ev_cap"])))
    assert set(rows) == set(rows_ref) and rows == rows_ref
    for k, v in Engine.metrics_dict(st_ref).items():
        assert Engine.metrics_dict(st2)[k] == v, k


# ---------------------------------------------------------------------------
# halt: the structured failure taxonomy
# ---------------------------------------------------------------------------

def test_halt_raises_structured_capacity_error():
    exp = phold_exp()
    eng = Engine(exp, params(SMALL_CAP, on_overflow="halt"))
    guard = OverflowGuard(eng, mode="halt")
    with pytest.raises(CapacityExceededError) as ei:
        run_chunked(eng, n_windows=N_WINDOWS, chunk=CHUNK, guard=guard)
    e = ei.value
    assert e.knob == "ev_cap" and e.counter == "ev_overflow"
    assert e.cap == SMALL_CAP and e.overflow > 0
    assert e.window_range == (0, CHUNK)  # first chunk is already lossy
    assert e.recommended > SMALL_CAP
    # Paste-ready advice: an engine: YAML block plus the sizing tool.
    assert e.advice.startswith("engine:")
    assert f"ev_cap: {e.recommended}" in e.advice
    assert "captune" in str(e) and "--on-overflow retry" in str(e)


def test_cpu_oracle_halt_same_boundary_check():
    exp = phold_exp()
    with pytest.raises(CapacityExceededError) as ei:
        CpuEngine(exp, EngineParams(ev_cap=SMALL_CAP,
                                    on_overflow="halt")).run(
            n_windows=N_WINDOWS)
    e = ei.value
    assert e.knob == "ev_cap" and e.overflow > 0
    # Window-granularity attribution on the oracle (vs chunk on batch).
    assert e.window_range[1] - e.window_range[0] == 1


def test_retry_aborts_at_ladder_top_with_diagnosis():
    """A cap that cannot grow (policy max) must abort with the structured
    error, not loop forever."""
    exp = phold_exp()
    eng = Engine(exp, params(SMALL_CAP))
    guard = OverflowGuard(eng, make_engine=lambda p: Engine(exp, p),
                          mode="retry", max_cap=SMALL_CAP)
    with pytest.raises(CapacityExceededError, match="ladder top"):
        run_chunked(eng, n_windows=N_WINDOWS, chunk=CHUNK, guard=guard)


# ---------------------------------------------------------------------------
# Self-check: the drop-accounting identity on every run
# ---------------------------------------------------------------------------

def test_selfcheck_clean_on_every_engine():
    exp = phold_exp()
    run_chunked(Engine(exp, EngineParams(ev_cap=32)), n_windows=N_WINDOWS,
                chunk=CHUNK, selfcheck=True)
    CpuEngine(exp, EngineParams(ev_cap=32, selfcheck=1)).run(
        n_windows=N_WINDOWS)


def test_selfcheck_violation_names_counters():
    with pytest.raises(SelfCheckError) as ei:
        check_boundary_identity(
            {"pkts_sent": 10, "pkts_delivered": 4, "pkts_lost": 1,
             "ev_overflow": 2}, where="window 7")
    e = ei.value
    assert e.gap == 5 and e.where == "window 7"
    assert e.terms["pkts_sent"] == 10 and e.terms["pkts_delivered"] == 4
    msg = str(e)
    assert "pkts_sent" in msg and "uncounted" in msg and "window 7" in msg
    # Over-explained direction (double count) is named distinctly.
    with pytest.raises(SelfCheckError, match="counted twice"):
        check_boundary_identity({"pkts_sent": 3, "pkts_delivered": 4})


# ---------------------------------------------------------------------------
# Autocap interplay: the controller absorbs retry-driven grows
# ---------------------------------------------------------------------------

def test_controller_absorbs_retry_grow_never_shrinks_back():
    from shadow1_tpu.tune.autocap import CapController, CapPolicy

    exp = phold_exp()
    ctl = CapController(Engine(exp, params(SMALL_CAP)),
                        lambda p: Engine(exp, p),
                        policy=CapPolicy(shrink_patience=1))
    ctl.note_lossy("ev_cap", 24)
    assert ctl._floor["ev_cap"] == 24
    # A shrink decision for a low high-water must clamp at the floor, not
    # fall back into the proven-overflowing range.
    assert ctl._decide("ev_cap", high_water=4, cap=24) == 24
    # And the guard shares the controller's engine cache.
    eng24 = ctl.engine_for(params(24))
    guard = OverflowGuard(eng24, mode="retry", controller=ctl)
    assert guard._engine_for(params(24)) is eng24


def test_retry_with_autocaps_attached_converges():
    """retry + --auto-caps in one run: the guard grows through the
    controller's cache and ratchets its lossless floor, so the pair
    converges to an overflow-free cap with no grow/shrink oscillation —
    the controller's shrink side (patience 1, maximally eager) never
    re-enters the proven-overflowing range."""
    from shadow1_tpu.tune.autocap import CapController, CapPolicy

    exp = phold_exp()
    eng = Engine(exp, params(SMALL_CAP))
    ctl = CapController(eng, lambda p: Engine(exp, p),
                        policy=CapPolicy(shrink_patience=1))
    guard = OverflowGuard(eng, mode="retry", controller=ctl)
    st = run_chunked(eng, n_windows=N_WINDOWS, chunk=CHUNK, guard=guard,
                     retune=ctl)
    assert guard.chunk_retries >= 1
    assert int(st.metrics.ev_overflow) == 0
    # The guard grew at least one ladder step and the floor absorbed it.
    assert ctl._floor["ev_cap"] >= 12
    # Every controller resize respected the lossy floor — no oscillation.
    assert all(rec["ev_cap"][1] >= 12 for rec in ctl.resizes)


# ---------------------------------------------------------------------------
# CLI + supervisor (subprocess): exit taxonomy and reporting
# ---------------------------------------------------------------------------

def _write_undercapped_cfg(tmp_path) -> str:
    cfg = tmp_path / "of_phold.yaml"
    cfg.write_text(
        "general: {seed: 5, stop_time: 40 ms}\n"
        "engine: {scheduler: tpu, ev_cap: 8}\n"
        "network: {single_vertex: {latency: 1 ms}}\n"
        "hosts:\n"
        "  - {name: h, count: 8}\n"
        "app:\n"
        "  model: phold\n"
        "  params: {mean_delay_ns: 2000000.0, init_events: 6}\n"
    )
    return str(cfg)


def test_cli_retry_reports_counters_and_halt_exit_code(tmp_path):
    """The acceptance reporting: chunk_retries ≥ 1 in the heartbeat
    ``retries`` block AND the final JSON; halt exits EXIT_CAPACITY with a
    parseable error record."""
    cfg = _write_undercapped_cfg(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", cfg, "--on-overflow", "retry",
         "--heartbeat", "10"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-800:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["retries"]["chunk_retries"] >= 1
    assert out["retries"]["caps"]["ev_cap"] > SMALL_CAP
    assert out["metrics"]["chunk_retries"] >= 1
    assert out["metrics"]["ev_overflow"] == 0  # committed stream is clean
    hb = [json.loads(x) for x in r.stderr.splitlines()
          if x.startswith("{") and '"heartbeat"' in x]
    assert any(b.get("retries", {}).get("chunk_retries", 0) >= 1 for b in hb)

    r = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", cfg, "--on-overflow", "halt"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_CAPACITY, (r.returncode, r.stderr[-600:])
    err = json.loads(r.stdout.strip().splitlines()[-1])
    assert err["error"] == "capacity_exceeded" and err["knob"] == "ev_cap"
    assert err["recommended"] > SMALL_CAP
    assert "Paste-ready fix" in r.stderr and "engine:" in r.stderr


def test_supervisor_classifies_capacity_halt_without_crash_loop(tmp_path):
    """--ckpt supervision over a halting child: EXIT_CAPACITY is a
    deterministic config condition — the supervisor must classify and stop,
    never respawn (mirrors the PR 4 no-progress classifier)."""
    cfg = _write_undercapped_cfg(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0"}
    r = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", cfg, "--on-overflow", "halt",
         "--ckpt", str(tmp_path / "ck.npz"), "--heartbeat", "10"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_CAPACITY, (r.returncode, r.stderr[-600:])
    assert "halted on a capacity policy" in r.stderr
    assert "respawning (" not in r.stderr  # zero respawn attempts


def test_cli_rejects_retry_on_cpu_engine(tmp_path, capsys):
    from shadow1_tpu.cli import main

    cfg = _write_undercapped_cfg(tmp_path)
    with pytest.raises(SystemExit) as ei:
        main([cfg, "--engine", "cpu", "--on-overflow", "retry"])
    assert ei.value.code == 2  # argparse usage error, like the other flags
    assert "batched engine" in capsys.readouterr().err
