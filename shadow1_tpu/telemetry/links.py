"""On-device link-telemetry accumulator — per-edge counters of the topology.

The telemetry ring sees the ENGINE (counter deltas), the probe ring sees
FLOWS (watched sockets); neither can answer "which link saturated, lost,
or went dark" — the nine global drop reasons have no topology coordinates.
This module gives the routing plane per-edge eyes without breaking the
zero-mid-window-host-sync contract:

* a device-resident ``[V, V, F]`` i64 accumulator (``registry.LINK_FIELDS``
  columns, keyed (src_vertex, dst_vertex)) rides in ``SimState.links``;
* ``route_outbox`` scatter-adds every routed packet's contribution at the
  window-end route phase (one ``.at[].add`` + one ``.at[].max``, entirely
  inside the jitted loop), and the NIC tx sites scatter drop-tail drops
  onto their egress edge as they happen (``link_nic_drops``);
* at chunk boundaries the host drains CUMULATIVE per-edge snapshots into
  JSONL ``link`` records (``drain_links``) — one record per active edge,
  running totals, so a drain is a pure function of device state and every
  engine's stream at the same boundary is bit-identical. Consumers diff
  consecutive snapshots per edge for rates (tools/netreport.py).

Every column except ``queued_ns_max`` is additive: under sharding each
shard accumulates its own hosts' packets (routing runs per-shard BEFORE
the all_to_all exchange, and NIC drops happen on the source shard), so the
per-window psum of the deltas reconstructs the exact single-device tensor
(shard/engine.py link_reduce); ``queued_ns_max`` max-reduces like the fill
gauges. Fleet lanes vmap to [E, V, V, F] with exp-tagged records. The
plane defaults off: ``link_init`` returns None, no pytree leaf exists, and
the traced program is bit-identical to a link-less build (the
``--state-digest`` rule); the accumulator is never digested, so enabling
it is digest-neutral by construction.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from shadow1_tpu.consts import SEC
from shadow1_tpu.telemetry.registry import (
    LINK_FIELDS,
    LINK_MAX_COL,
    REC_LINK,
    REC_LINK_GAP,
)

# Dense [V, V, F] memory bound: the plane targets PoP-level topologies
# (the GraphML vertex graph), not per-host meshes. 1024 vertices is 56 MB
# of i64 accumulator — beyond it the top-K variant this plane reserves
# ``link_telem > 1`` for is the right tool, and we refuse loudly instead
# of silently OOM-ing the device.
MAX_DENSE_VERTICES = 1024


class LinkAccum(NamedTuple):
    """The device-resident accumulator: running totals per directed edge."""

    buf: "jnp.ndarray"  # i64 [V, V, len(LINK_FIELDS)]


def check_link_params(params, n_vertices: int) -> None:
    """Config-time guards for the link plane (engine constructors)."""
    if not getattr(params, "link_telem", 0):
        return
    if int(params.link_telem) != 1:
        raise ValueError(
            f"link_telem={params.link_telem}: only the dense [V, V] "
            f"accumulator (link_telem=1) is implemented; top-K edge "
            f"tracking is reserved for a follow-up")
    if n_vertices > MAX_DENSE_VERTICES:
        raise ValueError(
            f"link_telem: {n_vertices} vertices exceeds the dense "
            f"accumulator bound ({MAX_DENSE_VERTICES}); the [V, V] tensor "
            f"would not fit the observability budget")


def link_init(link_telem: int, n_vertices: int) -> LinkAccum | None:
    """A zeroed [V, V, F] accumulator, or None when the plane is off.

    None contributes no pytree leaf, so a link-less state keeps the
    historic leaf layout — checkpoints and sharding specs are unaffected
    unless the plane is actually on."""
    if not link_telem:
        return None
    import jax.numpy as jnp

    return LinkAccum(
        buf=jnp.zeros((int(n_vertices), int(n_vertices), len(LINK_FIELDS)),
                      jnp.int64)
    )


def link_route_accum(links: LinkAccum, vs, vd, fmask, lost, linkdown,
                     queued, wire) -> LinkAccum:
    """Scatter one window's routed packets onto their edges (traced).

    Called from ``route_outbox`` with the flat per-slot vectors it already
    computed: ``vs``/``vd`` the endpoint vertices, ``fmask`` the occupied
    slots (the offered population), ``lost``/``linkdown`` the drop masks
    (subsets of fmask), ``queued`` the per-packet NIC queueing ns and
    ``wire`` the wire bytes. Dead slots collapse onto edge (0, 0) with
    all-zero contributions — a no-op by construction."""
    import jax.numpy as jnp

    buf = links.buf
    v = buf.shape[0]
    ek = jnp.where(fmask, vs.astype(jnp.int32) * v + vd.astype(jnp.int32), 0)
    one = fmask.astype(jnp.int64)
    q = jnp.where(fmask, queued, 0).astype(jnp.int64)
    adds = jnp.stack([
        one,                                    # pkts
        jnp.where(fmask, wire, 0).astype(jnp.int64),
        lost.astype(jnp.int64),
        linkdown.astype(jnp.int64),
        jnp.zeros_like(one),                    # nic drops accrue at tx sites
        q,
    ], axis=-1)                                 # [N, LINK_MAX_COL]
    flat = buf.reshape(v * v, len(LINK_FIELDS))
    flat = flat.at[ek, :LINK_MAX_COL].add(adds)
    # max col: dead slots contribute max(old, 0) on edge 0 — a no-op,
    # every entry is >= 0.
    flat = flat.at[ek, LINK_MAX_COL].max(q)
    return links._replace(buf=flat.reshape(buf.shape))


def link_nic_drops(links: LinkAccum | None, ctx, drops, dst
                   ) -> LinkAccum | None:
    """Scatter NIC uplink drop-tail drops onto their egress edge (traced).

    ``drops`` is the per-host drop count (bool mask or int counts, [H]
    local hosts), ``dst`` the per-host GLOBAL destination host id (garbage
    where drops == 0 — guarded here). No-op (and zero traced ops) when the
    plane is off. Mirrors the ``nic_tx_drops`` metric sites exactly:
    RED/AQM early drops are NOT backlog and stay off the edge tensor."""
    if links is None:
        return None
    import jax.numpy as jnp

    buf = links.buf
    v = buf.shape[0]
    n = drops.astype(jnp.int64)
    hit = n > 0
    vs = ctx.host_vertex[ctx.hosts]
    vd = ctx.host_vertex[jnp.where(hit, dst, 0)]
    ek = jnp.where(hit, vs.astype(jnp.int32) * v + vd.astype(jnp.int32), 0)
    col = LINK_FIELDS.index("nic_backlog_drops")
    flat = buf.reshape(v * v, len(LINK_FIELDS))
    flat = flat.at[ek, col].add(jnp.where(hit, n, 0))
    return links._replace(buf=flat.reshape(buf.shape))


def drain_links(st, window_ns: int, start: int = 0) -> list[dict]:
    """Host-side drain: cumulative per-edge snapshots at the current
    window boundary (one device→host fetch; chunk boundaries only).

    Emits one ``link`` record per edge with any nonzero column, in
    (src, dst) order — running totals, so re-draining the same boundary
    is idempotent and the ``start`` cursor (the last drained boundary)
    guarantees resume never re-emits. A cursor REGRESSION (the state's
    window count fell below ``start`` — a fleet lane rebound to a new
    experiment mid-sweep) emits one ``link_gap`` rebase marker instead."""
    links = getattr(st, "links", None)
    if links is None:
        return []
    done = int(st.metrics.windows)
    if done < start:
        return [{
            "type": REC_LINK_GAP,
            "window": done,
            "expected_window": start,
        }]
    if done <= start:
        return []
    buf = np.asarray(links.buf)
    v = buf.shape[0]
    t = round(done * window_ns / SEC, 9)
    recs: list[dict] = []
    for s, d in zip(*np.nonzero(buf.any(axis=-1))):
        rec = {
            "type": REC_LINK,
            "window": done - 1,
            "sim_time_s": t,
            "src_vertex": int(s),
            "dst_vertex": int(d),
        }
        rec.update({f: int(x) for f, x in zip(LINK_FIELDS, buf[s, d])})
        recs.append(rec)
    return recs
