"""Run the five-rung BASELINE benchmark ladder and record the results.

    python bench_ladder.py [rung ...] [--windows N] [--json PATH]

For each rung config (configs/rung*.yaml): run the batched engine on the
default backend (TPU when alive) with chunked timing — compile excluded,
overflow counters recorded (the parity contract requires them to be 0; a
nonzero count means the rung's capacity knobs need retuning, and the row
says so) — and the sequential CPU oracle on a bounded slice of the same
experiment for the events/sec comparison (the oracle is O(events) Python;
its slice and the extrapolation basis are recorded in the row).

Output: one JSON line per rung on stdout (plus a human table on stderr),
and with ``--json`` the rows are also written to a file. BASELINE.md's
results table is generated from these rows.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# rung -> (config, initial chunk). Heavy net rungs start with small chunks:
# the tunneled device faults on long single executions, and tor/bitcoin
# windows are orders of magnitude heavier than phold/tgen ones.
RUNGS = {
    "rung1": ("configs/rung1_filexfer.yaml", 100),
    "rung2": ("configs/rung2_tgen100.yaml", 100),
    "rung3": ("configs/rung3_tor1k.yaml", 20),
    "rung4": ("configs/rung4_tor10k.yaml", 10),
    "rung5": ("configs/rung5_bitcoin5k.yaml", 20),
}
ORACLE_EVENT_BUDGET = 200_000  # stop the oracle slice near this many events


def run_rung(name: str, path: str, windows_override: int | None,
             chunk0: int = 100) -> dict:
    import jax

    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.consts import SEC
    from shadow1_tpu.core.engine import Engine

    exp, params, _scheduler = load_experiment(path)
    eng = Engine(exp, params)
    total = windows_override or eng.n_windows

    # n_windows is traced, so a zero-window call compiles the exact program
    # every chunk reuses — compile never rides a long device execution.
    t0 = time.perf_counter()
    jax.block_until_ready(eng.run(eng.init_state(), n_windows=0))
    compile_wall = time.perf_counter() - t0

    # Adaptive chunking: the tunneled device faults on long single
    # executions (round-2 postmortem; reproduced on rung3's bootstrap-heavy
    # tor windows). On a runtime fault, shrink the chunk and retry — the
    # input state is host-managed and intact.
    t0 = time.perf_counter()
    st = eng.init_state()
    done, chunk, faults = 0, chunk0, 0
    while done < total:
        step = min(chunk, total - done)
        try:
            nxt = eng.run(st, n_windows=step)
            jax.block_until_ready(nxt)
            st, done = nxt, done + step
        except Exception as e:  # noqa: BLE001 — jax runtime faults
            faults += 1
            if chunk <= 5 or faults > 6:
                raise RuntimeError(
                    f"device faulted at {done}/{total} windows "
                    f"(chunk {step}): {e!r}"
                ) from e
            chunk = max(5, chunk // 4)
    wall = time.perf_counter() - t0
    m = Engine.metrics_dict(st)
    summary = eng.model_summary(st)
    sim_s = total * exp.window / SEC

    row = {
        "rung": name,
        "config": path,
        "n_hosts": exp.n_hosts,
        "windows": total,
        "sim_s": round(sim_s, 3),
        "backend": jax.default_backend(),
        "engine": "tpu-batched",
        "events": m["events"],
        "events_per_sec": round(m["events"] / wall, 1),
        "sim_per_wall": round(sim_s / wall, 4),
        "wall_s": round(wall, 2),
        "compile_s": round(compile_wall, 2),
        "ev_overflow": m["ev_overflow"],
        "ob_overflow": m["ob_overflow"],
        "round_cap_hits": m["round_cap_hits"],
        "rounds_per_window": round(m["rounds"] / max(m["windows"], 1), 2),
        "chunk_final": chunk,
        "device_faults_recovered": faults,
    }
    for k in ("total_flows_done", "total_streams_done", "clients_done",
              "total_cells_fwd", "total_rx_bytes", "total_seen"):
        if k in summary:
            row[k] = int(summary[k])
    return row


def run_oracle_slice(name: str, path: str, tpu_row: dict) -> dict:
    """Bounded oracle run: whole windows until the event budget is hit."""
    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.cpu_engine import CpuEngine

    exp, params, _ = load_experiment(path)
    cpu = CpuEngine(exp, params)
    t0 = time.perf_counter()
    done = 0
    cm = {"events": 0}
    while done < tpu_row["windows"]:
        step = max(1, tpu_row["windows"] // 50)
        cm = cpu.run(n_windows=done + step)
        done += step
        if cm["events"] >= ORACLE_EVENT_BUDGET or time.perf_counter() - t0 > 120:
            break
    wall = time.perf_counter() - t0
    return {
        "oracle_windows": done,
        "oracle_events": cm["events"],
        "oracle_wall_s": round(wall, 2),
        "oracle_events_per_sec": round(cm["events"] / wall, 1) if wall else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("rungs", nargs="*", default=None)
    ap.add_argument("--windows", type=int, default=None)
    ap.add_argument("--json", default=None)
    ap.add_argument("--no-oracle", action="store_true")
    args = ap.parse_args()

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)

    names = args.rungs or list(RUNGS)
    rows = []
    for name in names:
        path, chunk0 = RUNGS[name]
        try:
            row = run_rung(name, path, args.windows, chunk0)
            if not args.no_oracle:
                row.update(run_oracle_slice(name, path, row))
                if row.get("oracle_events_per_sec"):
                    row["vs_oracle"] = round(
                        row["events_per_sec"] / row["oracle_events_per_sec"], 2
                    )
        except Exception as e:  # noqa: BLE001 — record the failure, keep going
            import traceback

            row = {"rung": name, "config": path, "error": repr(e)[:400],
                   "traceback": traceback.format_exc()[-1500:]}
        rows.append(row)
        print(json.dumps(row), flush=True)
        ok = "error" not in row
        print(
            f"[{name}] " + (
                f"{row['events_per_sec']:>12,.0f} ev/s  sim/wall "
                f"{row['sim_per_wall']:.3f}  wall {row['wall_s']}s  "
                f"overflow {row['ev_overflow']}+{row['ob_overflow']}"
                if ok else f"FAILED: {row['error']}"
            ),
            file=sys.stderr, flush=True,
        )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
