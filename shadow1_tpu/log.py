"""Structured simulation logger: levels, sim-time context, per-host records.

The reference runs an async logger thread with per-thread buffers; every
record carries sim time, wall time and host context, and verbosity is a CLI
level (src/main/core/logger/shadow-logger.c, logrecord.c). The batched
engine cannot log from inside a traced window, so the stream is emitted at
chunk boundaries instead: engine-level records (heartbeats, drops) plus —
at the configured tracker interval — one record per host with its counter
snapshot (the Tracker stream, src/main/host/tracker.c).

Records are JSON lines: ``{"t": <wall iso>, "sim_s": .., "level": ..,
"host": .. | null, "msg": .., ...fields}``. A ``level`` filter plays the
reference's --log-level flag.
"""

from __future__ import annotations

import json
import sys
import time

LEVELS = {"error": 40, "warning": 30, "message": 20, "info": 10, "debug": 0}


def _level_value(level: str) -> int:
    """LEVELS lookup that fails usefully — the reference's --log-level flag
    rejects unknown names with the valid set, not a bare KeyError."""
    try:
        return LEVELS[level]
    except KeyError:
        raise ValueError(
            f"unknown log level {level!r}; valid levels: "
            f"{', '.join(LEVELS)}"
        ) from None


class SimLogger:
    """JSON-lines logger with level filtering and sim-time context."""

    def __init__(self, stream=None, level: str = "message"):
        self.stream = stream if stream is not None else sys.stderr
        self.threshold = _level_value(level)
        self.t0 = time.perf_counter()
        self.n_dropped = 0

    def log(self, level: str, msg: str, sim_ns: int | None = None,
            host: int | None = None, **fields) -> None:
        if _level_value(level) < self.threshold:
            self.n_dropped += 1
            return
        rec = {
            "wall_s": round(time.perf_counter() - self.t0, 3),
            "level": level,
            "msg": msg,
        }
        if sim_ns is not None:
            rec["sim_s"] = round(sim_ns / 1e9, 6)
        if host is not None:
            rec["host"] = int(host)
        rec.update(fields)
        print(json.dumps(rec), file=self.stream, flush=True)

    def error(self, msg, **kw):
        self.log("error", msg, **kw)

    def warning(self, msg, **kw):
        self.log("warning", msg, **kw)

    def message(self, msg, **kw):
        self.log("message", msg, **kw)

    def info(self, msg, **kw):
        self.log("info", msg, **kw)

    def debug(self, msg, **kw):
        self.log("debug", msg, **kw)


def tracker_records(engine, st) -> list[dict]:
    """Per-host tracker snapshot (host/tracker.c heartbeat analogue).

    Pulls the per-host counter columns off-device ONCE and emits one dict
    per host: NIC byte counters, queued events, cpu busy-time, plus every
    per-host column the model summary exposes. Counters are lifetime
    absolutes; interval deltas are tools/heartbeat_report.py's job."""
    import numpy as np

    sim_ns = int(st.win_start)
    cols: dict[str, np.ndarray] = {}
    # evbuf.kind is [ev_cap, H] (host-minor layout): reduce the slot axis.
    cols["pending_events"] = np.asarray(
        (np.asarray(st.evbuf.kind) != 0).sum(axis=0)
    )
    cols["cpu_busy_ns"] = np.asarray(st.cpu_busy)
    # Model summaries own their key namespace (net exports nic_tx_bytes /
    # nic_rx_bytes per host; apps export their per-host counters).
    for k, v in engine.model_summary(st).items():
        v = np.asarray(v)
        if v.ndim == 1 and v.shape[0] == engine.exp.n_hosts:
            cols[k] = v
    from shadow1_tpu.telemetry.registry import REC_TRACKER

    return [
        {"type": REC_TRACKER, "sim_s": round(sim_ns / 1e9, 6), "host": h,
         **{k: int(v[h]) for k, v in cols.items()}}
        for h in range(engine.exp.n_hosts)
    ]
