"""Checkpoint lineage — rotated snapshot generations with a manifest.

A single snapshot file has a single point of failure: corrupt the newest
(only) snapshot and the whole run restarts from scratch — PR 4's integrity
digest *detects* the corruption but can only discard. This module keeps a
rotated generation set instead:

* the NEWEST generation always lives at the bare checkpoint path (so every
  existing consumer — ``--resume``, the supervisor's fingerprint/meta logic,
  the tests — keeps reading the same file);
* older generations rotate to ``<path>.gNNNNNN`` (monotonic sequence
  numbers), pruned to ``--ckpt-keep`` total;
* a ``<path>.lineage`` manifest (write-then-rename atomic, like every
  sidecar) lists generation → win_start / done_windows / caps / format;
* :meth:`Lineage.resolve` walks newest→oldest and returns the first
  generation that passes ``ckpt.verify_file`` — a torn or bit-flipped head
  now costs ONE generation of progress instead of the whole run.

Rotation order makes any kill instant bit-safe: the new snapshot is fully
written to a temp file first, the old head is renamed to its generation
slot, then the temp is renamed in. A kill between the two renames leaves no
head but an intact previous generation; a kill mid-write leaves the old
head untouched. (``SHADOW1_LINEAGE_CRASH_BETWEEN`` / ``_TORN_HEAD`` are the
chaos-harness injection hooks for exactly those instants — each names a
flag file so the injected death fires once, not on every respawn.)

numpy-only at load/verify time (via ckpt): the supervisor resolves lineage
host-side without touching an accelerator.
"""

from __future__ import annotations

import json
import os
from typing import NamedTuple


def write_json_atomic(path: str, obj) -> None:
    """Write-then-rename JSON sidecar write. Every sidecar the supervisor
    reads (.progress, .meta, .lineage) goes through here: a process killed
    mid-write must never leave a torn sidecar that makes the supervisor
    misread progress or abandon a perfectly resumable snapshot."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _fire_once(env_var: str) -> bool:
    """Injection-hook latch: the env var names a flag file; the hook fires
    only while the file is absent, creating it first — so a supervised
    respawn (which inherits the env) proceeds instead of re-dying."""
    flag = os.environ.get(env_var)
    if not flag or os.path.exists(flag):
        return False
    with open(flag, "w") as f:
        f.write(env_var)
    return True


class ResolvedCkpt(NamedTuple):
    path: str | None     # the newest VALID generation file; None when
    #                      candidates existed but none passed verification
    seq: int             # its sequence number (-1 = unknown legacy head)
    meta: dict | None    # its manifest entry, when the manifest has one
    skipped: list        # newer-but-invalid candidates, newest first:
    #                      [{"file", "seq", "reason"}]


class Lineage:
    """Rotated generation set rooted at one checkpoint path."""

    def __init__(self, path: str, keep: int = 3):
        assert keep >= 1, keep
        self.path = path
        self.keep = keep
        self.manifest_path = path + ".lineage"

    # -- manifest ----------------------------------------------------------

    def _load_manifest(self) -> dict:
        try:
            with open(self.manifest_path) as f:
                m = json.load(f)
            if isinstance(m, dict) and isinstance(m.get("generations"), list):
                return m
        except (OSError, ValueError):
            pass
        return {"generations": []}

    def _gen_file(self, seq: int) -> str:
        return f"{self.path}.g{seq:06d}"

    def _scan_gens(self) -> list[tuple[int, str]]:
        """(seq, file) of on-disk rotated generations, oldest first — disk
        is the source of truth; the manifest only enriches."""
        d = os.path.dirname(self.path) or "."
        base = os.path.basename(self.path) + ".g"
        out = []
        try:
            names = os.listdir(d)
        except OSError:
            return []
        for name in names:
            if name.startswith(base):
                tail = name[len(base):]
                if tail.isdigit():
                    out.append((int(tail), os.path.join(d, name)))
        return sorted(out)

    def generations(self) -> list[dict]:
        """Manifest entries whose files still exist, oldest first (the
        head entry last). For reporting — resolve() does the verifying."""
        man = self._load_manifest()
        by_seq = {e.get("seq"): e for e in man["generations"]}
        out = []
        for seq, file in self._scan_gens():
            e = dict(by_seq.get(seq) or {"seq": seq})
            e["file"] = file
            out.append(e)
        if os.path.exists(self.path):
            head_seq = man.get("head_seq")
            e = dict(by_seq.get(head_seq) or {"seq": head_seq})
            e["file"] = self.path
            out.append(e)
        return out

    # -- save / rotate -----------------------------------------------------

    def save(self, st, meta: dict | None = None) -> int:
        """Snapshot ``st`` as the new head generation; rotate, prune, and
        update the manifest. Returns the new sequence number.

        ``meta`` (win_start / done_windows / total) rides the manifest entry
        so resume tooling and heartbeat_report can line generations up with
        sim time without opening the .npz files. EXTRA meta keys pass
        through verbatim — the fleet recovery plane stores the surviving
        lane ids (``lanes``) and the sub-batch cursor (``batch`` /
        ``batch_summaries``) this way, so a resume knows which sub-fleet a
        generation snapshots without a second sidecar that could go stale
        against it (cli._fleet_main / _fleet_subbatched)."""
        import numpy as np

        from shadow1_tpu import ckpt as _ckpt

        man = self._load_manifest()
        head_seq = man.get("head_seq")
        if head_seq is None and os.path.exists(self.path):
            # Legacy single-file checkpoint (pre-lineage): adopt it as the
            # generation before this one.
            gens = self._scan_gens()
            head_seq = gens[-1][0] + 1 if gens else 0
        seq = (head_seq + 1) if head_seq is not None else 0
        # 1) Fully write the new snapshot beside the head (atomic within).
        new_tmp = self.path + ".new"
        _ckpt.save_state(st, new_tmp)
        # 2) Rotate the current head to its generation slot — even at
        # keep=1: the prune below removes it AFTER the new head installs,
        # so no instant ever has zero snapshots on disk.
        if os.path.exists(self.path):
            os.replace(self.path, self._gen_file(head_seq))
        if _fire_once("SHADOW1_LINEAGE_CRASH_BETWEEN"):
            # Chaos hook: die exactly between rotate and install — the
            # worst mid-checkpoint-write instant (no head on disk).
            os._exit(137)
        # 3) Install the new head.
        os.replace(new_tmp, self.path)
        entries = [e for e in man["generations"]
                   if e.get("seq") is not None and e.get("seq") != seq]
        entry = {
            "seq": seq,
            "win_start": int(meta.get("win_start", 0)) if meta else 0,
            "done_windows": int(meta.get("done_windows", 0)) if meta else 0,
            "format": _ckpt.CKPT_FORMAT,
            "caps": {
                "ev_cap": int(np.asarray(st.evbuf.kind).shape[-2]),
                "outbox_cap": int(np.asarray(st.outbox.dst).shape[-2]),
            },
        }
        if meta:
            # Extra keys (fleet lanes / sub-batch cursor) ride verbatim;
            # the canonical ints above stay canonical.
            entry.update({k: v for k, v in meta.items()
                          if k not in entry})
        entries.append(entry)
        entries.sort(key=lambda e: e["seq"])
        # 4) Prune beyond ``keep`` (head included in the count).
        gens = self._scan_gens()
        while len(gens) > self.keep - 1:
            old_seq, old_file = gens.pop(0)
            try:
                os.remove(old_file)
            except OSError:
                pass
            entries = [e for e in entries if e["seq"] != old_seq]
        live = {s for s, _ in gens} | {seq}
        entries = [e for e in entries if e["seq"] in live]
        write_json_atomic(self.manifest_path,
                          {"keep": self.keep, "head_seq": seq,
                           "generations": entries})
        if _fire_once("SHADOW1_LINEAGE_TORN_HEAD"):
            # Chaos hook: simulate a torn head write (non-atomic fs / power
            # cut): truncate the freshly installed head, then die. The next
            # resolve() must skip it and fall back one generation.
            size = os.path.getsize(self.path)
            with open(self.path, "r+b") as f:
                f.truncate(max(size // 2, 1))
            os._exit(137)
        return seq

    # -- resolve -----------------------------------------------------------

    def resolve(self, discard_invalid: bool = False) -> ResolvedCkpt | None:
        """The newest generation that passes its integrity check.

        Returns None when no candidate file exists at all (fresh start);
        a ResolvedCkpt with ``path=None`` when candidates existed but none
        verified (every generation corrupt — ``skipped`` says why); else
        the newest valid generation with the invalid newer ones listed in
        ``skipped``.

        Walks head → rotated generations newest-first, verifying each with
        ``ckpt.verify_file``. With ``discard_invalid`` (the CLI child's
        mode), invalid candidates NEWER than the chosen one are deleted so
        a later save can never rotate a corrupt file into the generation
        set (when NO generation verifies, every candidate is deleted — the
        fresh start must not adopt a garbage head as a legacy snapshot);
        without it (the supervisor's read-only pre-spawn check), nothing
        on disk is touched."""
        from shadow1_tpu.ckpt import verify_file

        man = self._load_manifest()
        by_seq = {e.get("seq"): e for e in man["generations"]}
        head_seq = man.get("head_seq")
        candidates: list[tuple[int, str]] = []
        if os.path.exists(self.path):
            candidates.append((head_seq if head_seq is not None else -1,
                               self.path))
        candidates.extend(reversed(self._scan_gens()))
        if not candidates:
            return None
        skipped: list[dict] = []
        for seq, file in candidates:
            ok, why = verify_file(file)
            if ok:
                if discard_invalid:
                    for s in skipped:
                        try:
                            os.remove(s["file"])
                        except OSError:
                            pass
                return ResolvedCkpt(file, seq, by_seq.get(seq), skipped)
            skipped.append({"file": file, "seq": seq, "reason": why})
        if discard_invalid:
            for s in skipped:
                try:
                    os.remove(s["file"])
                except OSError:
                    pass
        return ResolvedCkpt(None, -1, None, skipped)

    # -- cleanup -----------------------------------------------------------

    def sidecar_paths(self) -> list[str]:
        """Every lineage-owned file: head, rotated generations, manifest —
        what the supervisor deletes on a finished run or a stale config."""
        return ([self.path] + [f for _, f in self._scan_gens()]
                + [self.manifest_path])

    def remove_all(self) -> None:
        for p in self.sidecar_paths():
            try:
                os.remove(p)
            except OSError:
                pass
