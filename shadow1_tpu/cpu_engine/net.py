"""CPU oracle mirror of the "net" model: NIC + TCP/UDP + model apps.

A readable per-host, per-socket object implementation of exactly the
semantics in docs/SEMANTICS.md and shadow1_tpu/tcp/tcp.py — same operation
order, same integer arithmetic, same capacity gates — so event streams and
all counters match the batched engine bit-for-bit. Structurally this is the
shape of the reference's C host stack (one Host object owning NIC state and
a descriptor table, SURVEY §2.3); the batched engine is the same machine
transposed to SoA tensors.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from shadow1_tpu.consts import (
    F_ACK,
    F_DGRAM,
    F_FIN,
    F_SYN,
    K_APP,
    K_PKT,
    K_PKT_DELIVER,
    K_TCP_TIMER,
    K_TX_RESUME,
    N_ACCEPTED,
    N_CLOSED,
    N_DATA,
    N_DGRAM,
    N_ESTABLISHED,
    N_MSG,
    N_PEER_FIN,
    N_SPACE,
    R_AQM,
    TCP_CLOSE_WAIT,
    TCP_CLOSING,
    TCP_ESTABLISHED,
    TCP_FIN_WAIT_1,
    TCP_FIN_WAIT_2,
    TCP_FREE,
    TCP_LAST_ACK,
    TCP_LISTEN,
    TCP_SYN_RCVD,
    TCP_SYN_SENT,
    CWND_MAX,
    SSTHRESH_INIT,
    TCP_CONN_STATES,
    TCP_RCV_STATES,
    TCP_SENDABLE_STATES,
    WIRE_OVERHEAD,
    ser_delay_ns,
    seq_add,
    seq_le,
    seq_lt,
    seq_sub,
)

SENDABLE = set(TCP_SENDABLE_STATES)
CONN_STATES = set(TCP_CONN_STATES)
RCV_STATES = set(TCP_RCV_STATES)


class CpuSock:
    __slots__ = (
        "st", "peer_host", "peer_sock", "snd_una", "snd_nxt", "snd_max", "rcv_nxt",
        "app_end", "fin_pend", "cwnd", "ssthresh", "peer_wnd", "dupacks",
        "recover", "srtt", "rttvar", "rto", "rtx_t", "timer_armed",
        "ts_act", "ts_seq", "ts_time", "txr", "mq",
    )

    def __init__(self):
        self.st = TCP_FREE
        self.peer_host = 0
        self.peer_sock = 0
        self.snd_una = 0
        self.snd_nxt = 0
        self.snd_max = 0
        self.rcv_nxt = 0
        self.app_end = 0
        self.fin_pend = 0
        self.cwnd = 0
        self.ssthresh = 0
        self.peer_wnd = 0
        self.dupacks = 0
        self.recover = 0
        self.srtt = 0
        self.rttvar = 0
        self.rto = 0
        self.rtx_t = 0
        self.timer_armed = False
        self.ts_act = False
        self.ts_seq = 0
        self.ts_time = 0
        self.txr = 0
        self.mq: list[tuple[int, int]] = []  # (end_seq, meta)

    def init_conn(self, pr, peer_host, peer_sock, state, rcv_nxt):
        self.st = state
        self.peer_host = peer_host
        self.peer_sock = peer_sock
        self.snd_una = 0
        self.snd_nxt = 0
        self.snd_max = 0
        self.rcv_nxt = rcv_nxt
        self.app_end = 1
        self.fin_pend = 0
        self.cwnd = pr.init_cwnd_mss * pr.mss
        self.ssthresh = SSTHRESH_INIT
        self.peer_wnd = pr.mss
        self.srtt = 0
        self.rttvar = 0
        self.rto = pr.rto_init
        self.rtx_t = 0
        self.dupacks = 0
        self.recover = 0
        self.ts_act = False
        self.txr = 0
        self.mq = []


class CpuNetModel:
    def __init__(self, eng):
        self.eng = eng
        self.pr = eng.params
        h = eng.exp.n_hosts
        self.n_hosts = h
        self.tx_free = np.zeros(h, np.int64)
        self.rx_free = np.zeros(h, np.int64)
        self.tx_bytes = np.zeros(h, np.int64)
        self.rx_bytes = np.zeros(h, np.int64)
        # Finite NIC queues (router.c drop-tail; mirror of net/nic.py).
        from shadow1_tpu.core.engine import aqm_tables_np, qlen_ns_np

        self.tx_qlen_ns = qlen_ns_np(eng.exp.tx_qlen_bytes, eng.exp.bw_up)
        self.rx_qlen_ns = qlen_ns_np(eng.exp.rx_qlen_bytes, eng.exp.bw_dn)
        self.has_tx_qlen = bool(np.asarray(eng.exp.tx_qlen_bytes).max() > 0)
        self.has_rx_qlen = bool(np.asarray(eng.exp.rx_qlen_bytes).max() > 0)
        # Without an rx queue bound, NIC arrival processing is plumbing, not
        # an event: the engine run loop short-circuits K_PKT to rx_convert
        # (mirror of net.make_pre_window's batched conversion). Virtual-CPU
        # configs keep the per-event path so arrivals charge cpu time
        # exactly as pre-round-3 semantics did (round-3 advisor finding).
        self.rx_batch = not (self.has_rx_qlen
                             or bool(np.asarray(eng.exp.cpu_ns_per_event).max() > 0))
        # RED AQM on the uplink (mirror of net/nic.py tx_stamp — identical
        # integer thresholds from the one shared table builder).
        self.aqm_min_ns, self.aqm_span_ns, self.aqm_pmax_thr = aqm_tables_np(
            eng.exp
        )
        self.has_aqm = bool(np.asarray(eng.exp.aqm_max_bytes).max() > 0)
        self.aqm_ctr = np.zeros(h, np.int64)
        self.socks = [
            [CpuSock() for _ in range(self.pr.sockets_per_host)] for _ in range(h)
        ]
        for k in ("tcp_fast_rtx", "tcp_rto", "tcp_ooo_drops"):
            eng.metrics[k] = 0
        name = eng.exp.model_cfg["app"]
        if name == "filexfer":
            self.app = CpuFilexfer(self)
        elif name == "dgram":
            self.app = CpuDgram(self)
        elif name == "tgen":
            from shadow1_tpu.cpu_engine.apps import CpuTgen

            self.app = CpuTgen(self)
        elif name == "tor":
            from shadow1_tpu.cpu_engine.apps import CpuTor

            self.app = CpuTor(self)
        elif name == "bitcoin":
            from shadow1_tpu.cpu_engine.apps import CpuBitcoin

            self.app = CpuBitcoin(self)
        else:
            raise ValueError(name)

    def start(self):
        self.app.start()

    # ------------------------------------------------------------------
    # Fault-plane restart (mirror of fault/plane.reset_host_columns over
    # the batched engines' init-model capture: NIC clocks/counters, every
    # socket — listen state included — and all per-host app state restore
    # to their post-start values; engine-level event/tb counters and the
    # pending heap are deliberately NOT touched, on either engine).
    # ------------------------------------------------------------------
    def snapshot_host_state(self):
        from shadow1_tpu.cpu_engine.engine import snap_host_arrays

        socks = [
            [
                {f: (list(getattr(k, f)) if f == "mq" else getattr(k, f))
                 for f in CpuSock.__slots__}
                for k in per_host
            ]
            for per_host in self.socks
        ]
        return {
            "nic": snap_host_arrays(self, self.n_hosts),
            "app": snap_host_arrays(self.app, self.n_hosts),
            "socks": socks,
        }

    def reset_host(self, host: int, snap) -> None:
        from shadow1_tpu.cpu_engine.engine import reset_host_arrays

        reset_host_arrays(self, snap["nic"], host)
        reset_host_arrays(self.app, snap["app"], host)
        for s, d in enumerate(snap["socks"][host]):
            k = self.socks[host][s]
            for f, v in d.items():
                setattr(k, f, list(v) if f == "mq" else v)

    # ------------------------------------------------------------------
    # NIC + packet emission (mirror of tcp.py _emit / net.udp_send)
    # ------------------------------------------------------------------
    def rx_convert(self, host: int, time: int, tb: int, p: tuple) -> None:
        """NIC arrival (rx_batch path): reserve the downlink FIFO and push
        the deliver event with the PACKET's tie-break — bit-identical to the
        batched engine's window-start conversion (net.make_pre_window)."""
        wire = p[4] + WIRE_OVERHEAD
        ready = max(time, int(self.rx_free[host]))
        self.rx_free[host] = ready + ser_delay_ns(wire, int(self.eng.exp.bw_dn[host]))
        self.rx_bytes[host] += wire
        self.eng.schedule_packet(host, ready, tb, K_PKT_DELIVER, p)

    def _tx(self, host: int, wire: int, now: int, dst: int) -> int | None:
        """Reserve the uplink; None = dropped (RED early-drop, then
        drop-tail on the queue bound — the order tx_stamp uses). ``dst``
        is the destination host, for the link plane's egress-edge
        attribution of drop-tail drops."""
        if self.has_aqm:
            ctr = int(self.aqm_ctr[host])
            self.aqm_ctr[host] += 1
            pmax_thr = int(self.aqm_pmax_thr[host])
            if pmax_thr > 0:
                backlog = max(int(self.tx_free[host]) - now, 0)
                span = int(self.aqm_span_ns[host])
                delta = min(max(backlog - int(self.aqm_min_ns[host]), 0), span)
                if delta >= span:
                    thr = 1 << 32  # ≥ max threshold: certain drop
                else:
                    thr = (pmax_thr * ((delta << 16) // span)) >> 16
                if int(self.eng.draws.bits(R_AQM, host, ctr)) < thr:
                    self.eng.metrics["nic_aqm_drops"] += 1
                    return None
        if self.has_tx_qlen and (int(self.tx_free[host]) - now) > int(self.tx_qlen_ns[host]):
            self.eng.metrics["nic_tx_drops"] += 1
            self.eng._link_nic_drop(host, dst)
            return None
        depart = max(now, int(self.tx_free[host]))
        self.tx_free[host] = depart + ser_delay_ns(wire, int(self.eng.exp.bw_up[host]))
        self.tx_bytes[host] += wire
        return depart

    def emit(self, h, s, flags, seq, length, mend, mmeta, now):
        k = self.socks[h][s]
        p = (
            h,
            s | (k.peer_sock << 8) | (flags << 16),
            seq,
            k.rcv_nxt,
            length,
            self.pr.rcvbuf,
            mend,
            mmeta,
            0,
            0,
        )
        depart = self._tx(h, length + WIRE_OVERHEAD, now, k.peer_host)
        if depart is None:  # queue-dropped: behaves like loss, rtx recovers
            return
        self.eng.send(h, k.peer_host, K_PKT, depart, p, now=now)

    def udp_send(self, h, dst_host, dst_sock, length, meta, meta2, now):
        p = (h, (dst_sock << 8) | (F_DGRAM << 16), 0, 0, length, 0, 0, meta, meta2, 0)
        depart = self._tx(h, length + WIRE_OVERHEAD, now, dst_host)
        if depart is None:
            return
        self.eng.send(h, dst_host, K_PKT, depart, p, now=now)

    # ------------------------------------------------------------------
    # TCP sender machinery (mirror of tcp.py tcp_flush/_ack_now)
    # ------------------------------------------------------------------
    def flush(self, h, s, now):
        pr = self.pr
        k = self.socks[h][s]
        for _ in range(pr.send_burst):
            total_end = seq_add(k.app_end, k.fin_pend)
            pending = seq_lt(k.snd_nxt, total_end)
            flight = seq_sub(k.snd_nxt, k.snd_una)
            limit = min(k.cwnd, k.peer_wnd)
            can = (
                k.st in SENDABLE
                and pending
                and flight < limit
                and self.eng.outbox_space(h, now) > 0
            )
            if not can:
                break
            if k.snd_nxt == 0:
                flags, length = (F_SYN | F_ACK if k.st == TCP_SYN_RCVD else F_SYN), 0
                seg_syn, seg_fin = True, False
            elif k.snd_nxt == k.app_end and k.fin_pend:
                flags, length = F_FIN | F_ACK, 0
                seg_syn, seg_fin = False, True
            else:
                flags = F_ACK
                length = min(pr.mss, seq_sub(k.app_end, k.snd_nxt), limit - flight)
                seg_syn, seg_fin = False, False
            mend = mmeta = 0
            if not seg_syn and not seg_fin:
                # Message-framed segmentation (mirror of tcp.py): truncate at
                # the first boundary in range so one segment = one message end.
                seg_hi = seq_add(k.snd_nxt, length)
                best = None
                for end, meta in k.mq:
                    if seq_lt(k.snd_nxt, end) and seq_le(end, seg_hi):
                        d = seq_sub(end, k.snd_nxt)
                        if best is None or d < best[0]:
                            best = (d, end, meta)
                if best is not None:
                    mend, mmeta = best[1], best[2]
                    length = best[0]
            self.emit(h, s, flags, k.snd_nxt, length, mend, mmeta, now)
            k.snd_nxt = seq_add(k.snd_nxt, length + (1 if (seg_syn or seg_fin) else 0))
            if seq_lt(k.snd_max, k.snd_nxt):
                k.snd_max = k.snd_nxt
            if not k.ts_act:
                k.ts_act = True
                k.ts_seq = k.snd_nxt
                k.ts_time = now
            if k.rtx_t == 0:
                k.rtx_t = now + k.rto
                if not k.timer_armed:
                    k.timer_armed = True
                    self.eng.schedule_local(h, now + k.rto, K_TCP_TIMER, (s,))
        # TX_RESUME if still pending (mirror ordering: checked after the burst).
        total_end = seq_add(k.app_end, k.fin_pend)
        pending = seq_lt(k.snd_nxt, total_end)
        wnd_ok = seq_sub(k.snd_nxt, k.snd_una) < min(k.cwnd, k.peer_wnd)
        blocked_outbox = self.eng.outbox_space(h, now) <= 0
        if k.st in SENDABLE and pending and wnd_ok and not k.txr:
            k.txr = 1
            t_resume = (now // self.eng.window + 1) * self.eng.window if blocked_outbox else now
            self.eng.schedule_local(h, t_resume, K_TX_RESUME, (s,))

    def ack_now(self, h, s, now):
        if self.eng.outbox_space(h, now) > 0:
            k = self.socks[h][s]
            self.emit(h, s, F_ACK, k.snd_nxt, 0, 0, 0, now)

    # ------------------------------------------------------------------
    # App-facing API (mirror of tcp.py tcp_listen/connect/send/close)
    # ------------------------------------------------------------------
    def listen(self, h, s):
        self.socks[h][s].st = TCP_LISTEN

    def connect(self, h, s, dst_host, dst_sock, now):
        self.socks[h][s].init_conn(self.pr, dst_host, dst_sock, TCP_SYN_SENT, 0)
        self.flush(h, s, now)

    def tcp_send(self, h, s, nbytes, meta, now) -> int:
        pr = self.pr
        k = self.socks[h][s]
        buffered = seq_sub(k.app_end, k.snd_una) - (1 if k.snd_una == 0 else 0)
        space = max(pr.sndbuf - buffered, 0)
        accepted = max(0, min(nbytes, space))
        if accepted > 0:
            k.app_end = seq_add(k.app_end, accepted)
            if accepted == nbytes and meta != 0 and len(k.mq) < pr.msgq_cap:
                k.mq.append((k.app_end, meta))
            self.flush(h, s, now)
        return accepted

    def close(self, h, s, now):
        k = self.socks[h][s]
        if k.st == TCP_ESTABLISHED:
            k.st = TCP_FIN_WAIT_1
        elif k.st == TCP_CLOSE_WAIT:
            k.st = TCP_LAST_ACK
        else:
            return
        k.fin_pend = 1
        self.flush(h, s, now)

    # ------------------------------------------------------------------
    # Event dispatch
    # ------------------------------------------------------------------
    def handle(self, host, time, kind, p):
        if kind == K_PKT:
            # Only the rx-drop-tail path reaches here (rx_batch otherwise
            # short-circuits in CpuEngine.run before event accounting).
            wire = p[4] + WIRE_OVERHEAD
            if self.has_rx_qlen and (int(self.rx_free[host]) - time) > int(self.rx_qlen_ns[host]):
                self.eng.metrics["nic_rx_drops"] += 1  # downlink drop-tail
                return
            ready = max(time, int(self.rx_free[host]))
            self.rx_free[host] = ready + ser_delay_ns(wire, int(self.eng.exp.bw_dn[host]))
            self.rx_bytes[host] += wire
            self.eng.schedule_local(host, ready, K_PKT_DELIVER, p)
        elif kind == K_PKT_DELIVER:
            flags = (p[1] >> 16) & 0xFF
            if flags & F_DGRAM:
                self.app.on_notify(
                    host, (p[1] >> 8) & 0xFF, N_DGRAM, p[7], p[8], p[4], 0, time
                )
            else:
                self.tcp_rx(host, p, time)
        elif kind == K_TCP_TIMER:
            self.tcp_timer(host, p[0], time)
        elif kind == K_TX_RESUME:
            s = p[0]
            self.socks[host][s].txr = 0
            self.flush(host, s, time)
        elif kind == K_APP:
            self.app.on_wakeup(host, time, p)

    # ------------------------------------------------------------------
    # TCP receive path (mirror of tcp.py tcp_rx, same sequencing)
    # ------------------------------------------------------------------
    def tcp_rx(self, h, p, now):
        pr = self.pr
        src, packed, seq, ackno, length, wnd, mend, mmeta = p[:8]
        ss = packed & 0xFF
        ds = (packed >> 8) & 0xFF
        flags = (packed >> 16) & 0xFF
        is_syn = bool(flags & F_SYN)
        is_ack = bool(flags & F_ACK)
        is_fin = bool(flags & F_FIN)
        socks = self.socks[h]
        k = socks[ds]
        notifs = 0
        n_meta = n_meta2 = n_dlen = n_space = 0
        n_sock = ds

        # passive open
        if is_syn and not is_ack and k.st == TCP_LISTEN:
            dup = any(
                c.peer_host == src and c.peer_sock == ss
                and c.st not in (TCP_FREE, TCP_LISTEN)
                for c in socks
            )
            # Highest free slot (mirror of tcp.py: low slots are app-owned).
            child = next(
                (i for i in range(len(socks) - 1, -1, -1) if socks[i].st == TCP_FREE),
                None,
            )
            if not dup and child is not None:
                socks[child].init_conn(pr, src, ss, TCP_SYN_RCVD, 1)
                socks[child].peer_wnd = wnd
                self.flush(h, child, now)
            return

        learn_peer = k.st == TCP_SYN_SENT and is_syn and is_ack
        v = (
            k.st in CONN_STATES
            and k.peer_host == src
            and (k.peer_sock == ss or learn_peer)
        )
        if not v:
            return
        if learn_peer:
            k.peer_sock = ss
        if is_ack:
            k.peer_wnd = max(wnd, 1)

        state = k.st  # pre-transition snapshot (mirrors the vector code)
        snd_una0, snd_nxt0 = k.snd_una, k.snd_nxt
        snd_max0 = k.snd_max
        a = is_ack
        # Acceptance tests against snd_max (highest ever sent), NOT the
        # possibly-rewound snd_nxt — mirror of tcp.py (outage deadlock).
        new_ack = a and seq_lt(snd_una0, ackno) and seq_le(ackno, snd_max0)
        est_ss = a and is_syn and state == TCP_SYN_SENT and ackno == 1
        frx = False
        if new_ack:
            if k.ts_act and seq_le(k.ts_seq, ackno):
                rtt = max(now - k.ts_time, 1)
                if k.srtt == 0:
                    k.srtt, k.rttvar = rtt, rtt // 2
                else:
                    err = rtt - k.srtt
                    k.srtt = k.srtt + (err >> 3)
                    k.rttvar = k.rttvar + ((abs(err) - k.rttvar) >> 2)
                k.rto = min(max(k.srtt + max(4 * k.rttvar, 1_000_000), pr.rto_min), pr.rto_max)
                k.ts_act = False
            grow = pr.mss if k.cwnd < k.ssthresh else max((pr.mss * pr.mss) // max(k.cwnd, 1), 1)
            k.cwnd = min(k.cwnd + grow, CWND_MAX)
            k.snd_una = ackno
            if seq_lt(k.snd_nxt, ackno):
                k.snd_nxt = ackno  # acked bytes were sent pre-rewind
            k.dupacks = 0
            k.mq = [(e, m) for (e, m) in k.mq if seq_lt(ackno, e)]
            outstanding = seq_lt(ackno, snd_max0)
            k.rtx_t = (now + k.rto) if outstanding else 0
            if state == TCP_SYN_RCVD:
                k.st = TCP_ESTABLISHED
                notifs |= N_ACCEPTED
        if est_ss:
            k.st = TCP_ESTABLISHED
            k.rcv_nxt = 1
            notifs |= N_ESTABLISHED
        if new_ack:
            total_end = seq_add(k.app_end, k.fin_pend)
            fin_acked = k.fin_pend == 1 and ackno == total_end
            closed_by_ack = False
            if fin_acked and state == TCP_FIN_WAIT_1:
                k.st = TCP_FIN_WAIT_2
            if fin_acked and state in (TCP_CLOSING, TCP_LAST_ACK):
                closed_by_ack = True
                notifs |= N_CLOSED
            if state in (TCP_ESTABLISHED, TCP_CLOSE_WAIT) and not closed_by_ack:
                notifs |= N_SPACE
                n_space = pr.sndbuf - seq_sub(k.app_end, ackno)
        else:
            closed_by_ack = False
        dup_a = (
            a and not new_ack and ackno == snd_una0 and seq_lt(ackno, snd_max0)
            and length == 0 and not is_syn and not is_fin
        )
        if dup_a:
            k.dupacks += 1
            if k.dupacks == pr.dupack_thresh and seq_le(k.recover, snd_una0):
                frx = True
                flight = seq_sub(snd_nxt0, snd_una0)
                k.ssthresh = max(flight // 2, 2 * pr.mss)
                k.cwnd = k.ssthresh
                k.recover = snd_nxt0
                k.snd_nxt = snd_una0
                k.ts_act = False
                self.eng.metrics["tcp_fast_rtx"] += 1
        if new_ack or frx:
            self.flush(h, ds, now)

        # payload + FIN
        state2 = k.st
        can_rcv = state2 in RCV_STATES
        has_data = can_rcv and length > 0
        in_order = has_data and seq == k.rcv_nxt
        if in_order:
            k.rcv_nxt = seq_add(k.rcv_nxt, length)
            notifs |= N_DATA
            n_dlen = length
            if mend != 0:
                notifs |= N_MSG
                n_meta = mmeta
        elif has_data:
            self.eng.metrics["tcp_ooo_drops"] += 1
        fin_here = (
            is_fin
            and seq_add(seq, length) == k.rcv_nxt
            and state2 in (TCP_ESTABLISHED, TCP_FIN_WAIT_1, TCP_FIN_WAIT_2)
        )
        closed_by_fin = False
        if fin_here:
            k.rcv_nxt = seq_add(k.rcv_nxt, 1)
            if state2 == TCP_ESTABLISHED:
                k.st = TCP_CLOSE_WAIT
                notifs |= N_PEER_FIN
            elif state2 == TCP_FIN_WAIT_1:
                k.st = TCP_CLOSING
            elif state2 == TCP_FIN_WAIT_2:
                closed_by_fin = True
                notifs |= N_CLOSED
        if closed_by_ack or closed_by_fin:
            k.st = TCP_FREE
            k.rtx_t = 0
        if has_data or is_fin or est_ss:
            self.ack_now(h, ds, now)
        if notifs:
            self.app.on_notify(h, n_sock, notifs, n_meta, n_meta2, n_dlen, n_space, now)

    def tcp_timer(self, h, s, now):
        pr = self.pr
        k = self.socks[h][s]
        k.timer_armed = False
        if k.rtx_t == 0:
            return
        if now < k.rtx_t:
            k.timer_armed = True
            self.eng.schedule_local(h, k.rtx_t, K_TCP_TIMER, (s,))
            return
        outstanding = seq_lt(k.snd_una, k.snd_max)
        if outstanding and k.st in SENDABLE:
            flight = seq_sub(k.snd_nxt, k.snd_una)
            k.ssthresh = max(flight // 2, 2 * pr.mss)
            k.cwnd = pr.mss
            k.rto = min(k.rto * 2, pr.rto_max)
            k.snd_nxt = k.snd_una
            k.ts_act = False
            k.dupacks = 0
            k.recover = k.snd_una
            k.rtx_t = now + k.rto
            k.timer_armed = True
            self.eng.metrics["tcp_rto"] += 1
            self.eng.schedule_local(h, k.rtx_t, K_TCP_TIMER, (s,))
            self.flush(h, s, now)
        else:
            k.rtx_t = 0

    def summary(self) -> dict[str, Any]:
        d = {
            "nic_tx_bytes": self.tx_bytes,
            "nic_rx_bytes": self.rx_bytes,
        }
        d.update(self.app.summary())
        return d


# --------------------------------------------------------------------------
# App mirrors
# --------------------------------------------------------------------------
class CpuFilexfer:
    """Mirror of shadow1_tpu/apps/filexfer.py."""

    FLOW_DONE = 1
    OP_START = 1

    def __init__(self, model: CpuNetModel):
        self.m = model
        cfg = model.eng.exp.model_cfg
        h = model.n_hosts
        self.role = np.asarray(cfg["role"], np.int32)
        self.server = np.asarray(cfg["server"], np.int32)
        self.flow_bytes = np.asarray(cfg["flow_bytes"], np.int32)
        self.start_time = np.asarray(cfg["start_time"], np.int64)
        self.flows_left = np.asarray(cfg["flow_count"], np.int32).copy()
        self.remaining = np.zeros(h, np.int32)
        self.closed_sent = np.zeros(h, bool)
        self.rx_bytes = np.zeros(h, np.int64)
        self.flows_done = np.zeros(h, np.int32)
        self.done_time = np.zeros(h, np.int64)

    def start(self):
        for h in range(self.m.n_hosts):
            if self.role[h] == 0:
                self.m.listen(h, 0)
            elif self.role[h] == 1:
                self.m.eng.schedule_local(h, int(self.start_time[h]), K_APP, (self.OP_START,))

    def _client_start(self, h, now):
        self.remaining[h] = self.flow_bytes[h]
        self.closed_sent[h] = False
        self.m.connect(h, 0, int(self.server[h]), 0, now)

    def _client_pump(self, h, now):
        if self.remaining[h] > 0:
            accepted = self.m.tcp_send(h, 0, int(self.remaining[h]), self.FLOW_DONE, now)
            self.remaining[h] -= accepted
        # Zero-byte flows close right at establishment (mirror of filexfer.py).
        if self.remaining[h] == 0 and not self.closed_sent[h]:
            self.closed_sent[h] = True
            self.m.close(h, 0, now)

    def on_wakeup(self, h, now, p):
        if p[0] == self.OP_START:
            self._client_start(h, now)

    def on_notify(self, h, sock, flags, meta, meta2, dlen, space, now):
        if self.role[h] == 1:
            if flags & (N_ESTABLISHED | N_SPACE):
                self._client_pump(h, now)
        if self.role[h] == 0:
            if flags & N_DATA:
                self.rx_bytes[h] += dlen
            if (flags & N_MSG) and meta == self.FLOW_DONE:
                self.flows_done[h] += 1
            if flags & N_PEER_FIN:
                self.m.close(h, sock, now)
        if self.role[h] == 1 and (flags & N_CLOSED):
            self.flows_left[h] -= 1
            if self.flows_left[h] > 0:
                self._client_start(h, now)
            else:
                self.done_time[h] = now

    def summary(self):
        return {
            "rx_bytes": self.rx_bytes,
            "flows_done": self.flows_done,
            "done_time": self.done_time,
            "total_rx_bytes": int(self.rx_bytes.sum()),
            "total_flows_done": int(self.flows_done.sum()),
        }


class CpuDgram:
    """Mirror of shadow1_tpu/apps/dgram.py."""

    OP_TICK = 1

    def __init__(self, model: CpuNetModel):
        self.m = model
        cfg = model.eng.exp.model_cfg
        h = model.n_hosts
        self.dst = np.asarray(cfg["dst"], np.int32)
        self.payload = np.asarray(cfg["payload"], np.int32)
        self.interval = np.asarray(cfg["interval"], np.int64)
        self.left = np.asarray(cfg["count"], np.int32).copy()
        self.start_time = np.asarray(cfg["start_time"], np.int64)
        self.rx_count = np.zeros(h, np.int64)
        self.rx_bytes = np.zeros(h, np.int64)

    def start(self):
        for h in range(self.m.n_hosts):
            if self.left[h] > 0:
                self.m.eng.schedule_local(h, int(self.start_time[h]), K_APP, (self.OP_TICK,))

    def on_wakeup(self, h, now, p):
        if p[0] != self.OP_TICK or self.left[h] <= 0:
            return
        self.m.udp_send(h, int(self.dst[h]), 0, int(self.payload[h]), 1, 0, now)
        self.left[h] -= 1
        if self.left[h] > 0:
            self.m.eng.schedule_local(h, now + int(self.interval[h]), K_APP, (self.OP_TICK,))

    def on_notify(self, h, sock, flags, meta, meta2, dlen, space, now):
        if flags & N_DGRAM:
            self.rx_count[h] += 1
            self.rx_bytes[h] += dlen

    def summary(self):
        return {
            "rx_count": self.rx_count,
            "rx_bytes": self.rx_bytes,
            "total_rx": int(self.rx_count.sum()),
        }
