"""Per-window active-host statistics — sizing data for sparse compaction.

    python -m shadow1_tpu.tools.activeprobe CONFIG.yaml [--windows N]

The batched engine pays every inner round as a full [C, H] tensor pass
regardless of how many hosts actually execute events — on sparse rungs the
round path is mostly dead lanes. If the per-WINDOW active-host set is small,
the engine can gather active hosts into a narrow static bucket at window
start, run the rounds compact, and scatter back (exact: the active set of a
window is closed under round execution, because cross-host packets defer to
the window-end exchange — handlers only self-push). This tool runs the CPU
oracle and prints the distribution that sizes that bucket:

    {"windows": N, "active_mean": ..., "active_p50/p90/p99/max": ...,
     "events_mean": ..., "rounds_mean (= max events/host + deliver…)": ...}

"active" counts hosts executing ≥1 model event in the window (NIC-batch
rx conversions count toward the host's activity too: converted arrivals
become K_PKT_DELIVER rounds in-window). "rounds" approximates the batch
engine's per-window inner-round count as max events per (host, window) —
the quantity the while_loop runs to.
"""

from __future__ import annotations

import argparse
import heapq
import json
from collections import Counter

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("--windows", type=int, default=None)
    args = ap.parse_args()

    # Oracle-only tool: never touch the accelerator (a wedged tunnel
    # hangs jax init — platform.py); the CPU platform is forced before any
    # jax array exists.
    from shadow1_tpu.platform import force_cpu

    force_cpu(1)
    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.consts import K_PKT
    from shadow1_tpu.cpu_engine import CpuEngine

    exp, params, _ = load_experiment(args.config)
    eng = CpuEngine(exp, params)
    W = eng.window
    n_win = args.windows if args.windows is not None else eng.n_windows
    end = n_win * W

    rx_batch = getattr(eng.model, "rx_batch", False)
    win_hosts: dict[int, set] = {}
    win_events: Counter = Counter()
    win_hostev: dict[int, Counter] = {}

    # Mirror CpuEngine.run()'s loop with per-window accounting; the oracle
    # engine itself stays untouched (no probe cost on the parity path).
    heap, model = eng.heap, eng.model
    while heap and heap[0][0] < end:
        time, tb, _g, host, kind, p = heapq.heappop(heap)
        eng.pending[host] -= 1
        if eng.has_stop and eng._down_at(host, time):
            continue
        w = time // W
        if kind == K_PKT and rx_batch:
            model.rx_convert(host, time, tb, p)
            win_hosts.setdefault(w, set()).add(host)
            continue
        if eng.has_cpu:
            eff = max(time, int(eng.cpu_busy[host]))
            if eff >= (time // W + 1) * W:
                eng.pending[host] += 1
                heapq.heappush(heap, (eff, tb, eng._gseq, host, kind, p))
                eng._gseq += 1
                continue
            eng.cpu_busy[host] = eff + int(eng.cpu_cost[host])
            time = eff
            w = time // W
        win_hosts.setdefault(w, set()).add(host)
        win_events[w] += 1
        win_hostev.setdefault(w, Counter())[host] += 1
        model.handle(host, time, kind, p)

    wins = sorted(win_hosts)
    act = np.array([len(win_hosts[w]) for w in wins])
    evs = np.array([win_events.get(w, 0) for w in wins])
    rnds = np.array([
        max(win_hostev[w].values()) if w in win_hostev else 0 for w in wins
    ])
    pct = lambda a, q: int(np.percentile(a, q)) if len(a) else 0
    print(json.dumps({
        "config": args.config,
        "n_hosts": exp.n_hosts,
        "windows": len(wins),
        "events": int(evs.sum()),
        "active_mean": round(float(act.mean()), 1) if len(act) else 0,
        "active_p50": pct(act, 50),
        "active_p90": pct(act, 90),
        "active_p99": pct(act, 99),
        "active_max": int(act.max()) if len(act) else 0,
        "events_per_window_mean": round(float(evs.mean()), 1) if len(evs) else 0,
        "rounds_proxy_mean": round(float(rnds.mean()), 1) if len(rnds) else 0,
        "rounds_proxy_max": int(rnds.max()) if len(rnds) else 0,
    }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
