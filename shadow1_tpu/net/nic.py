"""NIC model: per-host token-bucket-style serialization on both directions.

The reference's NetworkInterface (src/main/host/network-interface.c) gives
each host token-bucket up/down bandwidth with a FIFO send queue. The tensor
model keeps one "link free at" timestamp per direction per host: a packet of
wire length L departs at ``max(now, tx_free)`` and occupies the link for
``ceil(8·L / bw)`` ns; the receive side delays packet *processing* the same
way (SURVEY §3.3–3.4). This reproduces serialization/queueing delay exactly
for FIFO order, which is how both engines process packets.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from shadow1_tpu.consts import SEC


class NicState(NamedTuple):
    tx_free: jnp.ndarray   # i64 [H] uplink busy until
    rx_free: jnp.ndarray   # i64 [H] downlink busy until
    tx_bytes: jnp.ndarray  # i64 [H]
    rx_bytes: jnp.ndarray  # i64 [H]


def nic_init(n_hosts: int) -> NicState:
    z = lambda: jnp.zeros(n_hosts, jnp.int64)
    return NicState(z(), z(), z(), z())


def ser_delay(wire_bytes, bw_bits):
    """ceil(8e9 · bytes / bw) ns — identical integer math in both engines."""
    w = jnp.asarray(wire_bytes, jnp.int64)
    return (w * (8 * SEC) + bw_bits - 1) // bw_bits


def tx_stamp(nic: NicState, mask, wire_bytes, now, bw_up, qlen_ns=None):
    """Reserve the uplink: returns (nic', depart_time[H], ok[H]).

    With a finite queue (``qlen_ns``, the bound expressed as serialization
    backlog time — src/main/routing/router.c's upstream drop-tail queue),
    a packet is DROPPED (ok=False, link not reserved) when the backlog
    already exceeds the bound."""
    if qlen_ns is not None:
        mask = mask & ((nic.tx_free - jnp.asarray(now, jnp.int64)) <= qlen_ns)
    depart = jnp.maximum(now, nic.tx_free)
    busy = depart + ser_delay(wire_bytes, bw_up)
    w = jnp.asarray(wire_bytes, jnp.int64)
    return (
        nic._replace(
            tx_free=jnp.where(mask, busy, nic.tx_free),
            tx_bytes=nic.tx_bytes + jnp.where(mask, w, 0),
        ),
        depart,
        mask,
    )


def rx_stamp(nic: NicState, mask, wire_bytes, now, bw_dn, qlen_ns=None):
    """Reserve the downlink: returns (nic', ready_time[H], ok[H]) — the time
    the packet clears the receive queue; drop-tail like tx_stamp."""
    if qlen_ns is not None:
        mask = mask & ((nic.rx_free - jnp.asarray(now, jnp.int64)) <= qlen_ns)
    ready = jnp.maximum(now, nic.rx_free)
    busy = ready + ser_delay(wire_bytes, bw_dn)
    w = jnp.asarray(wire_bytes, jnp.int64)
    return (
        nic._replace(
            rx_free=jnp.where(mask, busy, nic.rx_free),
            rx_bytes=nic.rx_bytes + jnp.where(mask, w, 0),
        ),
        ready,
        mask,
    )
