"""pcap capture — write simulated packets as a standard .pcap file.

The reference can capture per-NIC traffic to pcap for wireshark-grade
debugging (src/main/utility/pcap-writer.c, per-interface capture flag).
Packets here carry no real bytes (payload is modeled as lengths), so the
writer synthesizes IPv4 + TCP/UDP headers from the packet record — host id
→ 10.x.y.z address, socket id → port, real seq/ack/flags/window — and pads
the payload with zeros (``snaplen`` caps what is written; ``orig_len``
keeps the true size, exactly how truncated captures work).

Capture runs on the CPU oracle (``CpuEngine(capture=...)``): the eager
engine sees every packet at routing time, which is the fidelity-debugging
context pcap serves; the batched engine's device loop intentionally never
surfaces per-packet records (tools/pcapdump.py is the CLI).
"""

from __future__ import annotations

import struct

from shadow1_tpu.consts import F_ACK, F_DGRAM, F_FIN, F_RST, F_SYN

LINKTYPE_RAW = 101  # raw IPv4


def _ip(host_id: int) -> bytes:
    return bytes([10, (host_id >> 16) & 0xFF, (host_id >> 8) & 0xFF, host_id & 0xFF])


class PcapWriter:
    """Streaming pcap writer; use as the CpuEngine ``capture`` callback."""

    def __init__(self, path: str, snaplen: int = 128):
        self.f = open(path, "wb")
        self.snaplen = snaplen
        self.n_packets = 0
        self.f.write(struct.pack(
            "<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, snaplen, LINKTYPE_RAW
        ))

    def __call__(self, time_ns: int, src: int, dst: int, p: tuple,
                 dropped: bool) -> None:
        """CpuEngine capture hook: one routed packet (dropped = lost)."""
        if dropped:
            return  # what the wire delivered, like a receiver-side capture
        packed = int(p[1])
        ss, ds, flags = packed & 0xFF, (packed >> 8) & 0xFF, (packed >> 16) & 0xFF
        length = int(p[4])
        if flags & F_DGRAM:
            l4 = struct.pack(
                ">HHHH", 10000 + ss, 10000 + ds, min(8 + length, 0xFFFF), 0
            )
            proto = 17
        else:
            tcp_flags = (
                (0x02 if flags & F_SYN else 0)
                | (0x10 if flags & F_ACK else 0)
                | (0x01 if flags & F_FIN else 0)
                | (0x04 if flags & F_RST else 0)
            )
            l4 = struct.pack(
                ">HHIIBBHHH", 10000 + ss, 10000 + ds,
                int(p[2]) & 0xFFFFFFFF, int(p[3]) & 0xFFFFFFFF,
                5 << 4, tcp_flags, int(p[5]) & 0xFFFF, 0, 0,
            )
            proto = 6
        total = 20 + len(l4) + length
        ip = struct.pack(
            ">BBHHHBBH", 0x45, 0, min(total, 0xFFFF), self.n_packets & 0xFFFF,
            0, 64, proto, 0,
        ) + _ip(src) + _ip(dst)
        # Pad only what the snaplen keeps; orig_len carries the true size.
        head = ip + l4
        orig = len(head) + length
        incl = min(orig, self.snaplen)
        frame = (head + b"\x00" * max(incl - len(head), 0))[:incl]
        ts_sec, rem = divmod(int(time_ns), 10**9)
        self.f.write(struct.pack("<IIII", ts_sec, rem // 1000, incl, orig))
        self.f.write(frame)
        self.n_packets += 1

    def close(self) -> None:
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FilteredPcap:
    """Watchlist filter in front of a PcapWriter (tools/pcapdump --host /
    --edge).

    ``watchlist`` is the probe plane's resolved (host, sock) tuple
    (config/experiment.resolve_watchlist — the same targets --watch
    accepts): a packet passes when its src OR dst endpoint matches an
    entry; sock == -1 entries match every socket on the host.

    ``edges`` is the link plane's resolved (src_vertex, dst_vertex) tuple
    (config/experiment.resolve_edges — the same edges link records key
    on), matched against the packet's attachment vertices via
    ``host_vertex``: the pcap of a hot edge and its link-record stream
    point at the same topology object. Directional, like link records.

    Both filters empty passes everything (filterless pcapdump unchanged);
    both given means EITHER may pass a packet (host-view OR edge-view).
    Drop-in for the CpuEngine ``capture`` hook — n_packets counts only
    what passed, like a capture filter on a real interface."""

    def __init__(self, writer: PcapWriter, watchlist: tuple = (),
                 edges: tuple = (), host_vertex=None):
        self.writer = writer
        self.watchlist = tuple(watchlist)
        self.edges = tuple(edges)
        if self.edges and host_vertex is None:
            raise ValueError("edge filtering needs the host_vertex map")
        self.host_vertex = host_vertex

    @property
    def n_packets(self) -> int:
        return self.writer.n_packets

    def _match(self, host: int, sock: int) -> bool:
        return any(h == host and (s < 0 or s == sock)
                   for h, s in self.watchlist)

    def _match_edge(self, src: int, dst: int) -> bool:
        vs = int(self.host_vertex[src])
        vd = int(self.host_vertex[dst])
        return (vs, vd) in self.edges

    def __call__(self, time_ns: int, src: int, dst: int, p: tuple,
                 dropped: bool) -> None:
        if self.watchlist or self.edges:
            packed = int(p[1])
            ss, ds = packed & 0xFF, (packed >> 8) & 0xFF
            ok = (self.watchlist
                  and (self._match(src, ss) or self._match(dst, ds)))
            ok = ok or (self.edges and self._match_edge(src, dst))
            if not ok:
                return
        self.writer(time_ns, src, dst, p, dropped)

    def close(self) -> None:
        self.writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
