"""Fault schedule — config parsing and the engine-neutral compiled tables.

One compilation path feeds every engine: the YAML ``faults:`` section
parses into a :class:`FaultSchedule` (raw nanosecond times, host/vertex
ids resolved), which rides ``CompiledExperiment.faults``; the three table
builders below turn it into the dense numpy arrays BOTH engines consume —
the TPU engine wraps them in device constants, the CPU oracle indexes them
directly — so the two can never disagree about when a host is down.

Deliberately jax-free (config loading and the oracle must not pay a jax
import); the traced twins live in ``fault/plane.py``.

Semantics (docs/SEMANTICS.md §"Fault plane"):

* **host churn** — a host is *down* during each ``[down, up)`` interval.
  Down times are exact event-time predicates; up times are quantized UP to
  the next conservative-window boundary, because the restart reset (state
  re-initialization) is applied at window starts. The legacy per-group
  ``stop_time`` knob compiles into the same tables as a final
  ``[stop_time, never)`` interval.
* **link outage** — packets whose NIC departure time falls inside a
  ``[from, until)`` window on a listed (src_vertex, dst_vertex) path are
  dropped deterministically (counted ``link_down_pkts``), before the loss
  draw. No quantization: the predicate is a pure function of the packet.
* **loss ramp** — during ``[from, until)`` the path's Bernoulli loss
  threshold is replaced by the ramp's (entries apply in file order, later
  entries win). The per-packet coin is drawn from the same
  ``(R_LOSS, src, pkt_ctr)`` stream either way, so toggling a ramp cannot
  shift any other draw.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from shadow1_tpu.config.compiled import NO_STOP


@dataclasses.dataclass
class FaultSchedule:
    """Raw (unquantized) fault entries; all times int64 ns, ids resolved.

    Host entries are flat (host_id, down, up) triples — multiple entries
    per host express repeated down/up cycles. ``up == NO_STOP`` means the
    host never restarts (a permanent kill, like the legacy stop_time).
    Link/ramp entries are vertex-pair keyed, already expanded to directed
    pairs (the parser duplicates bidirectional entries)."""

    host_id: np.ndarray = None    # i32 [E]
    host_down: np.ndarray = None  # i64 [E]
    host_up: np.ndarray = None    # i64 [E] (NO_STOP = never)
    link_src: np.ndarray = None   # i32 [L] vertex ids
    link_dst: np.ndarray = None   # i32 [L]
    link_t0: np.ndarray = None    # i64 [L]
    link_t1: np.ndarray = None    # i64 [L]
    ramp_src: np.ndarray = None   # i32 [R]
    ramp_dst: np.ndarray = None   # i32 [R]
    ramp_t0: np.ndarray = None    # i64 [R]
    ramp_t1: np.ndarray = None    # i64 [R]
    ramp_loss: np.ndarray = None  # f64 [R] loss probability during the ramp

    def __post_init__(self):
        for f, dt in (("host_id", np.int32), ("host_down", np.int64),
                      ("host_up", np.int64), ("link_src", np.int32),
                      ("link_dst", np.int32), ("link_t0", np.int64),
                      ("link_t1", np.int64), ("ramp_src", np.int32),
                      ("ramp_dst", np.int32), ("ramp_t0", np.int64),
                      ("ramp_t1", np.int64), ("ramp_loss", np.float64)):
            v = getattr(self, f)
            setattr(self, f, np.asarray(v if v is not None else [], dt))

    def validate(self, n_hosts: int, n_vertices: int) -> None:
        assert len(self.host_id) == len(self.host_down) == len(self.host_up)
        if len(self.host_id):
            assert self.host_id.min() >= 0 and self.host_id.max() < n_hosts
            assert (self.host_down > 0).all(), \
                "host down time must be > 0 (hosts cannot start dead)"
            assert (self.host_up > self.host_down).all()
        for src, dst, t0, t1 in ((self.link_src, self.link_dst,
                                  self.link_t0, self.link_t1),
                                 (self.ramp_src, self.ramp_dst,
                                  self.ramp_t0, self.ramp_t1)):
            assert len(src) == len(dst) == len(t0) == len(t1)
            if len(src):
                assert src.min() >= 0 and src.max() < n_vertices
                assert dst.min() >= 0 and dst.max() < n_vertices
                assert (t1 > t0).all() and (t0 >= 0).all()
        if len(self.ramp_loss):
            assert ((self.ramp_loss >= 0) & (self.ramp_loss <= 1)).all()

    @property
    def empty(self) -> bool:
        return not (len(self.host_id) or len(self.link_src)
                    or len(self.ramp_src))


# ---------------------------------------------------------------------------
# Engine-facing table builders (the ONE compilation both engines share)
# ---------------------------------------------------------------------------

def host_interval_tensors(exp) -> tuple[np.ndarray, np.ndarray]:
    """``(down, up)`` i64 ``[K, H]`` host down-interval tensors.

    Merges the legacy ``exp.stop_time`` (one ``[stop, never)`` interval)
    with ``exp.faults`` host entries; quantizes finite up times UP to the
    next window boundary (restart resets apply at window starts); pads to
    the max interval count K with ``[NO_STOP, NO_STOP)`` — an empty
    interval no time can satisfy. Intervals per host must not overlap
    AFTER quantization (validated here, loudly). ``down(h, t)`` is then
    ``any_k(down[k,h] <= t < up[k,h])`` on every engine."""
    h, w = exp.n_hosts, exp.window
    per_host: list[list[tuple[int, int]]] = [[] for _ in range(h)]
    st = np.asarray(exp.stop_time, np.int64)
    for i in range(h):
        if st[i] < NO_STOP:
            per_host[i].append((int(st[i]), NO_STOP))
    fs = getattr(exp, "faults", None)
    if fs is not None:
        for hid, d, u in zip(fs.host_id, fs.host_down, fs.host_up):
            uq = NO_STOP if u >= NO_STOP else int(-(-int(u) // w) * w)
            per_host[int(hid)].append((int(d), uq))
    k = max(max((len(v) for v in per_host), default=0), 1)
    down = np.full((k, h), NO_STOP, np.int64)
    up = np.full((k, h), NO_STOP, np.int64)
    for i, iv in enumerate(per_host):
        iv.sort()
        prev_up = 0
        for j, (d, u) in enumerate(iv):
            if d < prev_up:
                raise ValueError(
                    f"faults: host {i} down intervals overlap after "
                    f"window-quantizing up times (down={d} < previous "
                    f"up={prev_up}; window={w} ns) — space the cycles at "
                    f"least one window apart"
                )
            prev_up = u
            down[j, i] = d
            up[j, i] = u
    return down, up


def link_tables(exp) -> tuple[np.ndarray, ...] | None:
    """``(src, dst, t0, t1)`` link-outage arrays, or None when none are
    configured (the engines then trace/execute zero outage ops)."""
    fs = getattr(exp, "faults", None)
    if fs is None or not len(fs.link_src):
        return None
    return fs.link_src, fs.link_dst, fs.link_t0, fs.link_t1


def ramp_tables(exp) -> tuple[np.ndarray, ...] | None:
    """``(src, dst, t0, t1, thr)`` loss-ramp arrays (thr = the u64
    Bernoulli threshold via rng.prob_threshold — the identical integer both
    engines compare the shared coin bits against), or None."""
    fs = getattr(exp, "faults", None)
    if fs is None or not len(fs.ramp_src):
        return None
    from shadow1_tpu.rng import prob_threshold

    return (fs.ramp_src, fs.ramp_dst, fs.ramp_t0, fs.ramp_t1,
            prob_threshold(fs.ramp_loss))


def hosts_down_at_np(down: np.ndarray, up: np.ndarray, host: int,
                     t: int) -> bool:
    """Oracle-side down predicate (python ints; K is small)."""
    return bool(((t >= down[:, host]) & (t < up[:, host])).any())


# ---------------------------------------------------------------------------
# YAML ``faults:`` section → FaultSchedule
# ---------------------------------------------------------------------------

def parse_faults(doc: dict | None, groups, vertex_names) -> FaultSchedule | None:
    """Parse the config's ``faults:`` section.

    Schema (durations accept the usual "<num> <unit>" strings):

        faults:
          hosts:                       # repeated entries = repeated cycles
            - group: client            # host group name, or host: <id>,
              down_at: 2 s             #   or hosts: [ids]
              up_at: 3 s               # omit = never restarts (a kill)
          links:
            - src_vertex: pop_west     # vertex name (graphml id) or int
              dst_vertex: pop_east
              down_at: 4 s
              up_at: 4.5 s
              bidirectional: true      # default true; expands both ways
          loss:
            - src_vertex: pop_west
              dst_vertex: pop_east
              from: 1 s
              until: 2 s
              loss: 0.3                # replaces the path loss prob
              bidirectional: true

    ``groups`` is the expanded HostGroup list (for group-name resolution),
    ``vertex_names`` the topology's vertex-id list."""
    if not doc:
        return None
    from shadow1_tpu.config.experiment import parse_time_ns

    by_name = {g.name: g for g in groups}
    vidx = {str(n): i for i, n in enumerate(vertex_names)}

    def vertex(v):
        return int(v) if isinstance(v, int) else vidx[str(v)]

    hid, hdown, hup = [], [], []
    for e in doc.get("hosts", []):
        extra = set(e) - {"group", "host", "hosts", "down_at", "up_at"}
        assert not extra, f"unknown faults.hosts keys: {extra}"
        if "group" in e:
            ids = by_name[e["group"]].ids
        elif "hosts" in e:
            ids = [int(x) for x in e["hosts"]]
        else:
            ids = [int(e["host"])]
        down = parse_time_ns(e["down_at"])
        up = parse_time_ns(e["up_at"]) if "up_at" in e else NO_STOP
        for i in ids:
            hid.append(i)
            hdown.append(down)
            hup.append(up)

    def pairs(entries, t0_key, t1_key, known):
        src, dst, t0, t1, extras = [], [], [], [], []
        for e in entries:
            extra = set(e) - known
            assert not extra, f"unknown faults keys: {extra}"
            vs, vd = vertex(e["src_vertex"]), vertex(e["dst_vertex"])
            a, b = parse_time_ns(e[t0_key]), parse_time_ns(e[t1_key])
            dirs = [(vs, vd)]
            if e.get("bidirectional", True) and vs != vd:
                dirs.append((vd, vs))
            for s, d in dirs:
                src.append(s)
                dst.append(d)
                t0.append(a)
                t1.append(b)
                extras.append(e)
        return src, dst, t0, t1, extras

    base = {"src_vertex", "dst_vertex", "bidirectional"}
    lsrc, ldst, lt0, lt1, _ = pairs(doc.get("links", []), "down_at", "up_at",
                                    base | {"down_at", "up_at"})
    rsrc, rdst, rt0, rt1, rents = pairs(doc.get("loss", []), "from", "until",
                                        base | {"from", "until", "loss"})
    rloss = [float(e["loss"]) for e in rents]

    fs = FaultSchedule(
        host_id=hid, host_down=hdown, host_up=hup,
        link_src=lsrc, link_dst=ldst, link_t0=lt0, link_t1=lt1,
        ramp_src=rsrc, ramp_dst=rdst, ramp_t0=rt0, ramp_t1=rt1,
        ramp_loss=rloss,
    )
    return None if fs.empty else fs
