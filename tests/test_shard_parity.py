"""Sharding parity: the 8-device host-axis mesh vs the single-device engine.

Determinism across shardings is a hard invariant inherited from the
reference ("same config ⇒ same results regardless of worker count",
SURVEY §4): every semantic metric and model summary must be bit-identical
between the single-device engine and the shard_map engine on the virtual
8-device CPU mesh. Round counters are excluded — each shard runs its own
inner round loop, so their sum legitimately differs from the global count.
"""

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.shard.engine import ShardedEngine

SEMANTIC_KEYS = [
    "events", "windows", "pkts_sent", "pkts_delivered", "pkts_lost",
    "ev_overflow", "ob_overflow", "tcp_fast_rtx", "tcp_rto", "tcp_ooo_drops",
    "nic_tx_drops", "nic_rx_drops", "nic_aqm_drops",
    "x2x_overflow",  # all_to_all bucket drops: must be 0 (== single-device)
]


def run_pair(exp, params=None):
    params = params or EngineParams()
    eng = Engine(exp, params)
    st1 = eng.run()
    sh = ShardedEngine(exp, params)
    assert sh.n_dev == 8, "conftest must provide 8 virtual devices"
    st8 = sh.run()
    return (
        Engine.metrics_dict(st1),
        eng.model_summary(st1),
        ShardedEngine.metrics_dict(st8),
        sh.model_summary(st8),
    )


def assert_same(m1, s1, m8, s8, summary_keys):
    for k in SEMANTIC_KEYS:
        assert m8[k] == m1[k], (k, m8[k], m1[k])
    for k in summary_keys:
        np.testing.assert_array_equal(np.asarray(s8[k]), np.asarray(s1[k]), err_msg=k)


def test_phold_sharded_parity():
    exp = single_vertex_experiment(
        n_hosts=64,
        seed=7,
        end_time=50 * MS,
        latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 2},
    )
    m1, s1, m8, s8 = run_pair(exp)
    assert m1["events"] > 500  # the workload actually ran
    assert_same(m1, s1, m8, s8, summary_keys=("hops",))


def test_phold_sharded_parity_pallas():
    """The fused Pallas pop/push/outbox kernels inside shard_map on the
    8-device mesh (interpret mode on CPU): prerequisite for ever flipping
    the pop_impl/push_impl defaults — the driver's multichip gate and the
    sharded engine must run them, not just the single-device path."""
    exp = single_vertex_experiment(
        n_hosts=64,
        seed=7,
        end_time=50 * MS,
        latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 2},
    )
    params = EngineParams(pop_impl="pallas", push_impl="pallas")
    m1, s1, m8, s8 = run_pair(exp, params)
    assert m1["events"] > 500
    assert_same(m1, s1, m8, s8, summary_keys=("hops",))


def test_x2x_bucket_overflow_is_counted():
    """A deliberately tiny all_to_all bucket must DROP (not corrupt), count
    every dropped packet in x2x_overflow, and fail loudly by default."""
    import pytest

    exp = single_vertex_experiment(
        n_hosts=64, seed=7, end_time=50 * MS, latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 4},
    )
    sh_full = ShardedEngine(exp, EngineParams())
    full = sh_full.run()
    fm = ShardedEngine.metrics_dict(full)
    assert fm["x2x_overflow"] == 0
    # Occupancy observability: the busiest-bucket high-water mark is
    # recorded, positive (traffic flowed), and within the cap that held.
    assert 0 < fm["x2x_max_fill"] <= sh_full._x2x_cap
    with pytest.raises(RuntimeError, match="x2x_cap"):
        ShardedEngine(exp, EngineParams(x2x_cap=1)).run()
    tiny = ShardedEngine(exp, EngineParams(x2x_cap=1)).run(check_x2x=False)
    tm = ShardedEngine.metrics_dict(tiny)
    assert tm["x2x_overflow"] > 0
    # The high-water mark records DEMANDED fill, so it exceeds the cap of 1
    # exactly when overflow happens — users can read the needed cap off it.
    assert tm["x2x_max_fill"] > 1
    assert tm["x2x_max_fill"] == fm["x2x_max_fill"]  # demand is cap-independent
    # sent minus (lost + delivered + dropped buckets + full-evbuf drops) = 0
    assert (
        tm["pkts_sent"]
        == tm["pkts_lost"] + tm["pkts_delivered"] + tm["x2x_overflow"]
        + tm["ev_overflow"]
    ), tm


def test_x2x_auto_retry_convergent_traffic():
    """Convergent (all clients → one server) traffic overflows the uniform
    auto cap by design; run() must escalate to the worst-case cap and
    produce results bit-identical to the single-device engine — the exact
    failure shape that broke the round-3 multichip gate."""
    import __graft_entry__ as ge

    # The gate's own flagship shape (4 hosts/shard), auto cap instead of
    # the gate's pinned one so the escalation path is what runs.
    exp = ge._flagship_exp(32, 1 * SEC)
    params = EngineParams(ev_cap=64, outbox_cap=16, sockets_per_host=4)
    assert params.x2x_cap == 0  # auto-sized: the path under test
    sh = ShardedEngine(exp, params)
    start_cap = sh._x2x_cap
    st8 = sh.run(n_windows=4)
    m8 = ShardedEngine.metrics_dict(st8)
    assert m8["x2x_overflow"] == 0
    # The workload converges on shard 0, so the retry must actually fire —
    # otherwise this test is not exercising the escalation path.
    assert sh._x2x_cap == sh._full_cap > start_cap
    eng = Engine(exp, params)
    st1 = eng.run(n_windows=4)
    m1 = Engine.metrics_dict(st1)
    for k in SEMANTIC_KEYS:
        assert m8[k] == m1[k], (k, m8[k], m1[k])


def test_dryrun_multichip_gate():
    """Execute the driver's own multichip gate (__graft_entry__) so its exact
    parameterization is covered by CI — round 3 shipped a gate-only failure
    because nothing in tests/ ran this path, and round 4 left this test in
    the slow tier only, so the default ``./ci.sh`` could still go green while
    the gate drifted. It costs ~5 sharded-program compiles (minutes) and is
    budgeted into the fast tier deliberately."""
    import __graft_entry__ as ge  # repo root is on pythonpath (pyproject)

    ge.dryrun_multichip(8)


@pytest.mark.slow  # tier-1 wall budget (PR 4): heaviest of its family;
# a faster sibling keeps the coverage in the fast tier; ./ci.sh all runs it.
def test_tor_sharded_parity():
    """The flagship multi-chip workload (rung 4 is sharded Tor): clients,
    weighted relays and dirauths spread across all 8 shards; every semantic
    counter and per-host summary must bit-match the single-device engine."""
    from tests.test_tor_parity import TOR_KEYS, tor_exp

    exp = tor_exp(seed=11, end=30 * SEC)
    m1, s1, m8, s8 = run_pair(exp, EngineParams(ev_cap=256, sockets_per_host=32))
    assert int(s1["clients_done"]) == 12  # the workload actually completed
    assert_same(m1, s1, m8, s8, summary_keys=TOR_KEYS)


def _filexfer_exp(end_s: int, loss: float):
    n = 8
    role = np.full(n, 1, np.int64)
    role[0] = 0
    return single_vertex_experiment(
        n_hosts=n,
        seed=3,
        end_time=end_s * SEC,
        latency_ns=10 * MS,
        loss=loss,
        bw_bits=10**7,
        model="net",
        model_cfg={
            "app": "filexfer",
            "role": role,
            "server": np.zeros(n, np.int64),
            "flow_bytes": np.full(n, 30_000, np.int64),
            "start_time": np.full(n, 1 * MS, np.int64),
            "flow_count": np.where(role == 1, 1, 0),
        },
    )


def test_filexfer_sharded_parity_fast():
    """Tier-1 wall sibling (PR 9 budget pass): the same convergent
    filexfer-on-a-mesh contract on a quarter of the window count — every
    flow still completes and every counter/summary bit-matches."""
    m1, s1, m8, s8 = run_pair(_filexfer_exp(5, 0.01), EngineParams(ev_cap=256))
    assert int(s1["total_flows_done"]) == 7
    assert_same(m1, s1, m8, s8, summary_keys=("rx_bytes", "flows_done", "done_time"))


@pytest.mark.slow  # tier-1 wall budget (PR 9): the 20-sim-second horizon;
# the fast sibling above keeps the contract in the fast tier.
def test_filexfer_sharded_parity():
    m1, s1, m8, s8 = run_pair(_filexfer_exp(20, 0.01), EngineParams(ev_cap=256))
    assert int(s1["total_flows_done"]) == 7
    assert_same(m1, s1, m8, s8, summary_keys=("rx_bytes", "flows_done", "done_time"))


@pytest.mark.slow  # tier-1 wall budget (PR 4): RED parity is covered by
# test_fidelity.test_red_aqm_parity; the sharded combination runs in all.
def test_filexfer_red_aqm_sharded_parity():
    """RED AQM under sharding: the per-host aqm columns (thresholds, coin
    counters) ride the mesh like every other [H] tensor; drops must land on
    the exact same packets as the single-device engine."""
    n = 8
    role = np.full(n, 1, np.int64)
    role[0] = 0
    exp = single_vertex_experiment(
        n_hosts=n,
        seed=3,
        end_time=20 * SEC,
        latency_ns=10 * MS,
        bw_bits=10**6,
        model="net",
        model_cfg={
            "app": "filexfer",
            "role": role,
            "server": np.zeros(n, np.int64),
            "flow_bytes": np.full(n, 60_000, np.int64),
            "start_time": np.full(n, 1 * MS, np.int64),
            "flow_count": np.where(role == 1, 1, 0),
        },
        aqm_min_bytes=np.full(n, 2_000, np.int64),
        aqm_max_bytes=np.full(n, 12_000, np.int64),
        aqm_pmax=np.full(n, 0.3, np.float64),
    )
    m1, s1, m8, s8 = run_pair(exp, EngineParams(ev_cap=256))
    assert m1["nic_aqm_drops"] > 0  # RED actually fired
    assert_same(m1, s1, m8, s8, summary_keys=("rx_bytes", "flows_done"))
