"""tgen — Markov/flow traffic-generator model over the virtual TCP stack.

The model-application analogue of the reference's tgen plugin
(shadow-plugin-tgen, SURVEY §2.4/§7.1: "tgen configs are literally
Markov/flow state machines — faithful to re-express"). Every host serves on
socket 0; hosts with ``active`` set additionally run a client loop on
socket 1: pick a uniform random peer, stream an exponentially-sized payload
with a STREAM_DONE message boundary, close, think an exponential pause,
repeat — the classic tgen mesh/bulk workload (BASELINE ladder rung 2).

All randomness is counter-based (R_APP, host, 3*stream + k): k=0 peer draw,
k=1 size draw, k=2 think draw — so the CPU oracle reproduces identical
streams in any execution order.

model_cfg (numpy arrays, [H] unless noted):
  active         1 = runs the client loop, 0 = serves only
  streams        sequential streams per active host
  mean_bytes     mean stream size (exponential, clipped to [1, 2^30])
  mean_think_ns  mean pause between streams (exponential, ≥ 1 ns)
  start_time     first-stream time (ns)
  fixed_size     (python bool, optional) stream size = mean_bytes exactly
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from shadow1_tpu import rng
from shadow1_tpu.consts import (
    K_APP,
    N_CLOSED,
    N_DATA,
    N_ESTABLISHED,
    N_MSG,
    N_PEER_FIN,
    N_SPACE,
    NP,
    R_APP,
    TCP_LISTEN,
)
from shadow1_tpu.core.engine import push_local_event
from shadow1_tpu.core.events import push_local
from shadow1_tpu.tcp import tcp as T

STREAM_DONE = 1
OP_START = 1
SIZE_MAX = 1 << 30


def init(ctx, evbuf, tcpd):
    cfg = ctx.model_cfg
    active = jnp.asarray(cfg["active"], jnp.int32)
    app = {
        "active": active,
        "streams_left": jnp.asarray(cfg["streams"], jnp.int32),
        "mean_bytes": jnp.asarray(cfg["mean_bytes"], jnp.float32),
        "mean_think": jnp.asarray(cfg["mean_think_ns"], jnp.float32),
        "remaining": jnp.zeros(ctx.n_hosts, jnp.int32),
        "closed_sent": jnp.zeros(ctx.n_hosts, bool),
        "ctr": jnp.zeros(ctx.n_hosts, jnp.int64),  # stream index
        "rx_bytes": jnp.zeros(ctx.n_hosts, jnp.int64),
        "streams_served": jnp.zeros(ctx.n_hosts, jnp.int32),
        "streams_done": jnp.zeros(ctx.n_hosts, jnp.int32),
        "done_time": jnp.zeros(ctx.n_hosts, jnp.int64),
    }
    # Every host serves on socket 0.
    tcpd = dict(tcpd)
    tcpd["st"] = tcpd["st"].at[0].set(TCP_LISTEN)
    starts = (active == 1) & (app["streams_left"] > 0)
    p = jnp.zeros((NP, ctx.n_hosts), jnp.int32).at[0].set(OP_START)
    k = jnp.full(ctx.n_hosts, K_APP, jnp.int32)
    evbuf, over = push_local(
        evbuf, starts, jnp.asarray(cfg["start_time"], jnp.int64), k, p
    )
    return app, evbuf, over.sum(dtype=jnp.int64), tcpd


def _draw(ctx, app, k_off):
    """One u32 per host for sub-draw ``k_off`` of the current stream index."""
    return rng.bits_v(ctx.key, R_APP, ctx.hosts, 3 * app["ctr"] + k_off)


def _start_stream(st, ctx, mask, now):
    """Draw (peer, size) for the next stream and connect socket 1 to it."""
    app = dict(st.model.app)
    draw_dst = rng.randint(_draw(ctx, app, 0), ctx.n_total - 1)
    dst = draw_dst + (draw_dst >= ctx.hosts).astype(jnp.int32)
    if ctx.model_cfg.get("fixed_size"):
        size = jnp.maximum(app["mean_bytes"].astype(jnp.int32), 1)
    else:
        size = jnp.clip(
            rng.exponential_ns(_draw(ctx, app, 1), app["mean_bytes"]), 1, SIZE_MAX
        ).astype(jnp.int32)
    app["remaining"] = jnp.where(mask, size, app["remaining"])
    app["closed_sent"] = jnp.where(mask, False, app["closed_sent"])
    app["ctr"] = app["ctr"] + mask.astype(jnp.int64)
    st = st._replace(model=st.model._replace(app=app))
    one = jnp.ones(ctx.n_hosts, jnp.int32)
    zero = jnp.zeros(ctx.n_hosts, jnp.int32)
    return T.tcp_connect(st, ctx, mask, one, dst, zero, now)


def _client_pump(st, ctx, mask, now):
    app = st.model.app
    m = mask & (app["remaining"] > 0)
    one = jnp.ones(ctx.n_hosts, jnp.int32)
    meta = jnp.full(ctx.n_hosts, STREAM_DONE, jnp.int32)
    st, accepted = T.tcp_send(st, ctx, m, one, app["remaining"], meta, now)
    app = dict(st.model.app)
    app["remaining"] = app["remaining"] - accepted
    done = mask & (app["remaining"] == 0) & ~app["closed_sent"]
    app["closed_sent"] = app["closed_sent"] | done
    st = st._replace(model=st.model._replace(app=app))
    return T.tcp_close(st, ctx, done, one, now)


def on_wakeup(st, ctx, ev, mask):
    start = mask & (ev.p[0] == OP_START)
    return _start_stream(st, ctx, start, ev.time)


def on_notify(st, ctx, nf: T.Notif, now, mask):
    f = nf.flags
    is_client_sock = nf.sock == 1

    # Client: connection up or buffer space → pump the stream.
    pump = mask & is_client_sock & (((f & N_ESTABLISHED) != 0) | ((f & N_SPACE) != 0))
    st = _client_pump(st, ctx, pump, now)

    # Server (listener children live on high sockets): count bytes/streams.
    app = dict(st.model.app)
    srv = mask & ~is_client_sock
    data = srv & ((f & N_DATA) != 0)
    app["rx_bytes"] = app["rx_bytes"] + jnp.where(data, nf.dlen.astype(jnp.int64), 0)
    msg = srv & ((f & N_MSG) != 0) & (nf.meta == STREAM_DONE)
    app["streams_served"] = app["streams_served"] + msg.astype(jnp.int32)
    st = st._replace(model=st.model._replace(app=app))

    # Server: peer finished → close our side. Teardown blocks are lax.cond-
    # gated out of steady-state rounds (exact: all writes masked).
    peer_fin = srv & ((f & N_PEER_FIN) != 0)
    st = jax.lax.cond(
        peer_fin.any(),
        lambda s: T.tcp_close(s, ctx, peer_fin, nf.sock, now),
        lambda s: s, st,
    )

    # Client: stream fully closed → think, then next stream (or done).
    closed = mask & is_client_sock & ((f & N_CLOSED) != 0)

    def _closed(st):
        app = dict(st.model.app)
        app["streams_left"] = app["streams_left"] - closed.astype(jnp.int32)
        app["streams_done"] = app["streams_done"] + closed.astype(jnp.int32)
        again = closed & (app["streams_left"] > 0)
        app["done_time"] = jnp.where(
            closed & (app["streams_left"] == 0), now, app["done_time"]
        )
        # Think draw belongs to the stream just completed: ctr was advanced
        # at start, so its index is ctr - 1.
        think_ctr = 3 * (app["ctr"] - 1) + 2
        think = rng.exponential_ns(
            rng.bits_v(ctx.key, R_APP, ctx.hosts, think_ctr), app["mean_think"]
        )
        st = st._replace(model=st.model._replace(app=app))
        return push_local_event(st, ctx, again, now + think, K_APP, p0=OP_START)

    return jax.lax.cond(closed.any(), _closed, lambda s: s, st)


def summary(app) -> dict:
    return {
        "rx_bytes": app["rx_bytes"],
        "streams_served": app["streams_served"],
        "streams_done": app["streams_done"],
        "done_time": app["done_time"],
        "total_rx_bytes": app["rx_bytes"].sum(),
        "total_streams_served": app["streams_served"].sum(),
        "total_streams_done": app["streams_done"].sum(),
    }
