"""Simulation-as-a-service — the persistent multi-tenant engine daemon.

The batch CLI pays the dominant cost — engine trace + compile, 3.3 s once
vs 25.3 s per-seed on the measured E=16 fleet (BENCH_r06) — on EVERY
invocation. This package turns the engine into a long-lived server so
repeat-shape traffic never pays it again:

* ``python -m shadow1_tpu serve --spool DIR``   — the daemon
  (:mod:`serve.daemon`): accepts standard YAML experiment configs over a
  filesystem spool + Unix-socket JSON-line protocol, admits them against
  the live HBM budget (:mod:`shadow1_tpu.mem` pre-flight, BEFORE any
  compile), packs shape-compatible jobs into fleet lanes
  (:mod:`shadow1_tpu.fleet`), and streams per-job telemetry into the
  spool;
* ``python -m shadow1_tpu submit CONFIG --spool DIR`` — the client
  (:mod:`serve.client`): submits, streams status, awaits the result, and
  exits the solo CLI's taxonomy codes (EXIT_CONFIG / EXIT_MEMORY for
  rejections, EXIT_CAPACITY for a quarantined lane);
* the **hot engine cache** (:mod:`serve.cache`): compiled fleet engines
  keyed by (shape class, caps, engine knobs, lane count, backend) — a
  repeat-shape batch REBINDS its per-job variants into the cached
  program (``FleetEngine.rebind``) and skips trace + compile entirely.

The serving contract (docs/SEMANTICS.md §"Serving contract"): a job run
through the daemon produces a digest stream and parity counters
bit-identical to the same config run through the solo CLI — lanes are
vmap-independent, so cohabitation is observable only in wall time.
``tools/serveprobe.py`` proves it end-to-end per invocation.
"""

from shadow1_tpu.serve.protocol import Spool, new_job_id  # noqa: F401
