"""Unit tests for the batched event-buffer primitives."""

import jax.numpy as jnp
import numpy as np

from shadow1_tpu.consts import NP, K_PHOLD
from shadow1_tpu.core.events import (
    deliver_batch,
    evbuf_init,
    pop_until,
    push_local,
    rebase,
    tb_join,
    tb_split,
)

ZP = lambda h: jnp.zeros((NP, h), jnp.int32)


def test_push_pop_order():
    buf = evbuf_init(2, 8)
    k = jnp.full(2, K_PHOLD, jnp.int32)
    both = jnp.ones(2, bool)
    # Push times out of order; same-time pushes must pop FIFO (by tb).
    for t in [50, 10, 30, 10]:
        buf, over = push_local(buf, both, jnp.full(2, t, jnp.int64), k, ZP(2))
        assert not bool(over.any())
    seen = []
    for _ in range(4):
        buf, ev = pop_until(buf, jnp.int64(10**9))
        assert bool(ev.mask.all())
        seen.append(int(ev.time[0]))
    assert seen == [10, 10, 30, 50]
    buf, ev = pop_until(buf, jnp.int64(10**9))
    assert not bool(ev.mask.any())


def test_pop_respects_until():
    buf = evbuf_init(1, 4)
    one = jnp.ones(1, bool)
    k = jnp.full(1, K_PHOLD, jnp.int32)
    buf, _ = push_local(buf, one, jnp.full(1, 100, jnp.int64), k, ZP(1))
    buf, ev = pop_until(buf, jnp.int64(100))  # window end exclusive
    assert not bool(ev.mask[0])
    buf, ev = pop_until(buf, jnp.int64(101))
    assert bool(ev.mask[0]) and int(ev.time[0]) == 100


def test_push_overflow_counts():
    buf = evbuf_init(1, 2)
    one = jnp.ones(1, bool)
    k = jnp.full(1, K_PHOLD, jnp.int32)
    for i in range(3):
        buf, over = push_local(buf, one, jnp.full(1, i + 1, jnp.int64), k, ZP(1))
        assert bool(over[0]) == (i == 2)


def test_deliver_batch_ranks_and_overflow():
    buf = evbuf_init(3, 2)
    n = 5
    dst = jnp.array([1, 1, 1, 2, 0], jnp.int32)  # 3 packets to host 1 (cap 2)
    time = jnp.array([10, 20, 30, 40, 50], jnp.int64)
    tb = jnp.arange(n, dtype=jnp.int64) + (1 << 62)
    kind = jnp.full(n, K_PHOLD, jnp.int32)
    p = jnp.zeros((NP, n), jnp.int32)
    mask = jnp.ones(n, bool)
    buf, n_over = deliver_batch(buf, dst, time, tb, kind, p, mask)
    assert int(n_over) == 1
    counts = np.asarray((buf.kind != 0).sum(axis=0))
    assert counts.tolist() == [1, 2, 1]
    # deliver_batch writes absolute times only; the window-start rebase
    # refreshes the i32 pop keys before the next round loop reads them
    # (core/engine.py window_step order).
    buf = rebase(buf, 0)
    # Host 1 keeps its two earliest-listed packets (rank order), pops in time order.
    buf, ev = pop_until(buf, jnp.int64(10**9))
    assert ev.time.tolist()[1] == 10 and ev.time.tolist()[2] == 40


def test_far_future_event_beyond_i32_horizon():
    """An event scheduled past the 2**31-ns rebase horizon saturates the i32
    pop key (ineligible) until the epoch catches up, then pops at its exact
    time — the Tor bootstrap / long-RTO shape (core/events.py t32)."""
    buf = evbuf_init(1, 4)
    one = jnp.ones(1, bool)
    k = jnp.full(1, K_PHOLD, jnp.int32)
    t_far = 5 * 10**9  # +5 s, ~2.3x past the horizon at epoch 0
    buf, over = push_local(buf, one, jnp.full(1, t_far, jnp.int64), k, ZP(1))
    assert not bool(over[0])
    # Windows advance in 1-second steps; the event must stay invisible even
    # to a generous until bound while clamped.
    for epoch in range(0, 5 * 10**9, 10**9):
        buf = rebase(buf, epoch)
        buf, ev = pop_until(buf, jnp.int64(epoch + 10**9))
        assert not bool(ev.mask[0]), epoch
    buf = rebase(buf, 5 * 10**9 - 1)
    buf, ev = pop_until(buf, jnp.int64(5 * 10**9 + 1))
    assert bool(ev.mask[0]) and int(ev.time[0]) == t_far


def test_past_due_events_keep_exact_time_and_order():
    """Events left eligible by a max_rounds cap-hit window rebase to a LATER
    epoch: their reconstructed pop times must stay exact and their (time,
    tb) order must survive — t32 goes negative rather than clamping to 0
    (core/events.py I32_PASTDUE; round-5 review finding)."""
    from shadow1_tpu.core.popk import pop_until_fused

    buf = evbuf_init(1, 4)
    one = jnp.ones(1, bool)
    k = jnp.full(1, K_PHOLD, jnp.int32)
    # Three events, all before the NEXT window's start (past-due there).
    for t in (300, 100, 200):
        buf, _ = push_local(buf, one, jnp.full(1, t, jnp.int64), k, ZP(1))
    for fused in (False, True):
        b = rebase(buf, 1000, 2000)  # epoch has moved past all three
        seen = []
        for _ in range(3):
            if fused:
                b, ev2 = pop_until_fused(b, jnp.int64(2000))
            else:
                b, ev2 = pop_until(b, jnp.int64(2000))
            assert bool(ev2.mask[0])
            seen.append(int(ev2.time[0]))
        assert seen == [100, 200, 300], (fused, seen)


def test_tb_split_join_order():
    """tb_split is an order-preserving bijection into lexicographic
    (hi, lo) i32 — including low words with the top bit set (the sign-flip
    encoding) and the packet-tb range."""
    vals = np.array(
        [0, 1, 2**31 - 1, 2**31, 2**32 - 1, 2**32, (1 << 62) + 7,
         (1 << 62) + (5 << 32) + 0xFFFFFFFF, (1 << 62) + (6 << 32)],
        dtype=np.int64,
    )
    hi, lo = tb_split(jnp.asarray(vals))
    back = np.asarray(tb_join(hi, lo))
    np.testing.assert_array_equal(back, vals)
    # Lexicographic (hi, signed lo) order == numeric order.
    pairs = list(zip(np.asarray(hi).tolist(), np.asarray(lo).tolist()))
    order = sorted(range(len(vals)), key=lambda i: pairs[i])
    assert order == sorted(range(len(vals)), key=lambda i: int(vals[i]))


def test_pop_fused_pallas_matches_xla():
    """The Pallas fused pop kernel (core/popk.py, interpret mode on CPU) is
    bit-identical to the XLA reduction chain — buffer planes and every
    Popped field, across a drain of a randomly seeded buffer with time and
    tie-break collisions."""
    from shadow1_tpu.core.popk import pop_until_fused

    rng = np.random.default_rng(11)
    h, c = 7, 12
    buf = evbuf_init(h, c)
    k = jnp.full(h, K_PHOLD, jnp.int32)
    for _ in range(c - 2):
        m = jnp.asarray(rng.random(h) < 0.85)
        # Narrow time range to force same-time ties (tb must break them).
        t = jnp.asarray(rng.integers(1, 6, h), jnp.int64)
        p = jnp.asarray(rng.integers(0, 99, (NP, h)), jnp.int32)
        buf, _ = push_local(buf, m, t, k, p)
    a, b = buf, buf
    for _ in range(c):
        a, ea = pop_until(a, jnp.int64(10**9))
        b, eb = pop_until_fused(b, jnp.int64(10**9), interpret=True)
        for fa, fb in zip(ea, eb):
            np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    for fa, fb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_pop_extract_gather_matches_sum():
    """The two pop_until extraction modes are bit-identical (perf A/B knob,
    EngineParams.pop_extract)."""
    rng = np.random.default_rng(3)
    h, c = 5, 8
    buf = evbuf_init(h, c)
    k = jnp.full(h, K_PHOLD, jnp.int32)
    for _ in range(c - 1):
        m = jnp.asarray(rng.random(h) < 0.8)
        t = jnp.asarray(rng.integers(1, 1000, h), jnp.int64)
        p = jnp.asarray(rng.integers(0, 99, (NP, h)), jnp.int32)
        buf, _ = push_local(buf, m, t, k, p)
    a, b = buf, buf
    for _ in range(c):
        a, ea = pop_until(a, jnp.int64(10**9), extract="sum")
        b, eb = pop_until(b, jnp.int64(10**9), extract="gather")
        for fa, fb in zip(ea, eb):
            np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    for fa, fb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_payload_matches_at_chain():
    """dense.payload (stacked rows) is bit-identical to the .at[i].set chain
    it replaced in the packet builders, including None planes, scalar
    broadcast, and the over-NP guard."""
    import pytest

    from shadow1_tpu.core.dense import payload

    rng = np.random.default_rng(7)
    h = 6
    rows = [jnp.asarray(rng.integers(0, 99, h), jnp.int32), None,
            jnp.int32(41), None, jnp.asarray(rng.integers(0, 9, h), jnp.int32)]
    p = payload(h, *rows)
    ref = jnp.zeros((NP, h), jnp.int32)
    for i, r in enumerate(rows):
        if r is not None:
            ref = ref.at[i].set(r)
    np.testing.assert_array_equal(np.asarray(p), np.asarray(ref))
    assert p.dtype == jnp.int32 and p.shape == (NP, h)
    with pytest.raises(ValueError, match="rows > NP"):
        payload(h, *([jnp.int32(0)] * (NP + 1)))


def test_pallas_preflight_fallback_shapes():
    """popk.preflight accepts in-VMEM shapes and rejects over-VMEM ones on
    TPU; off-TPU (this suite) it must be a no-op so interpret-mode tests
    keep exercising the kernels at any shape."""
    import jax

    from shadow1_tpu.core import popk

    # Off-TPU the preflight never raises (interpret mode has no VMEM). On a
    # TPU-attached run of this suite the same call MUST raise.
    if jax.default_backend() == "tpu":
        with np.testing.assert_raises(ValueError):
            popk.preflight(4096, 4096, 100_000,
                           pop_pallas=True, push_pallas=True)
    else:
        popk.preflight(4096, 4096, 100_000, pop_pallas=True, push_pallas=True)
    # The underlying check itself rejects over-VMEM and accepts small.
    popk._check_vmem(64, 1000, planes=popk.POP_PLANES)
    import pytest

    with pytest.raises(ValueError, match="outbox_cap=4096"):
        popk._check_vmem(4096, 50_000, planes=popk.OBOX_PLANES,
                         knob="outbox_cap")
