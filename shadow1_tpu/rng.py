"""Counter-based deterministic randomness shared by both engines.

The reference gives every host its own seeded RNG (src/main/host/host.c) so
results are independent of worker scheduling. We go one step further: every
draw is a pure function of ``(seed, purpose, host, counter)`` — order
independent, so the eager CPU oracle and the batched TPU engine produce
bit-identical streams no matter when each computes its draws.

Backend-exactness (round-2 postmortem): the original implementation used
Threefry ``fold_in`` chains plus a float ``log1p`` transform; the float
transcendental evaluates differently on the axon TPU than on CPU, silently
breaking the determinism invariant on the target hardware (142,577 vs
142,576 events over the same 50-window program). Every transform here is
now **pure integer arithmetic** (or a single IEEE-exact f64 round for the
mean scaling), identical on every XLA backend by construction:

* ``bits`` — a splitmix64-style avalanche hash of the packed
  (seed, purpose, host, ctr) tuple: ~10 u64 ops instead of 3 chained
  Threefry blocks (~8x cheaper on the hot path, and elementwise — no vmap).
* ``exponential_ns`` — fixed-point −ln(1−u) via count-leading-zeros + a
  4096-entry Q32 log2 table with linear interpolation (relative error
  ~1e-7), times an integer-rounded mean.
* ``uniform_lt`` — probability compares as an integer threshold on the raw
  bits, never a float comparison.

The DieHarder-grade quality of the splitmix64 finalizer is far beyond what
a DES needs (the reference uses GLib's Mersenne/rand per host).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_U64 = jnp.uint64

# splitmix64 finalizer constants (public domain, Stafford mix13).
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
# Odd multipliers decorrelating the (purpose, host, ctr) lanes.
_P1 = np.uint64(0x9E3779B97F4A7C15)  # golden-ratio increment
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)


def base_key(seed: int) -> jax.Array:
    """The per-experiment key: a u64 scalar derived from the seed."""
    return jnp.asarray(base_key_np(seed), _U64)


def _mix(z):
    z = z ^ (z >> np.uint64(30))
    z = z * _C1
    z = z ^ (z >> np.uint64(27))
    z = z * _C2
    z = z ^ (z >> np.uint64(31))
    return z


def bits(seed_key, purpose, host, ctr) -> jax.Array:
    """One u32 of raw randomness for (purpose, host, ctr).

    Elementwise over any broadcastable host/ctr shapes (u64 wraparound
    arithmetic; exact on every backend)."""
    z = (
        jnp.asarray(seed_key, _U64)
        + jnp.asarray(purpose, _U64) * _P1
        + jnp.asarray(host, jnp.int64).astype(_U64) * _P2
        + jnp.asarray(ctr, jnp.int64).astype(_U64) * _P3
    )
    z = _mix(_mix(z))
    return (z >> np.uint64(32)).astype(jnp.uint32)


# Historical alias: the Threefry version needed an explicit vmap; the hash is
# natively vectorized. Signature: (key, purpose, host[H], ctr[H]) -> u32 [H].
bits_v = bits


def uniform01(b: jax.Array) -> jax.Array:
    """u32 bits → float32 in [0, 1). Single exact multiply (display/summary
    use only — probability *decisions* must use uniform_lt)."""
    return b.astype(jnp.float32) * jnp.float32(2.0 ** -32)


def prob_threshold(p) -> np.ndarray:
    """Probability (numpy array/scalar, host-side) → u64 threshold such that
    ``bits < threshold`` occurs with probability p (exact at 2^-32)."""
    return (np.round(np.asarray(p, np.float64) * 2.0 ** 32)).astype(np.uint64)


def uniform_lt(b: jax.Array, threshold) -> jax.Array:
    """Integer Bernoulli: True with probability threshold / 2^32."""
    return b.astype(_U64) < jnp.asarray(threshold, _U64)


# --- fixed-point −ln(1−u) ---------------------------------------------------
# x = 2^32 − b ∈ [1, 2^32] is uniform; −ln(x/2^32) = (32 − log2 x)·ln2.
# log2 x = k + log2(1+f) with k = floor(log2 x): table the fraction in Q32.
_LOG_BITS = 12
# Kept as numpy so importing this module never initializes a JAX backend
# (platform probing must run first; see shadow1_tpu.platform). jnp.asarray
# inside the traced function embeds it as a compile-time constant.
_LOG_TBL_NP = np.round(
    np.log2(1.0 + np.arange(2 ** _LOG_BITS + 1) / 2 ** _LOG_BITS) * 2.0 ** 32
).astype(np.uint64)
_LN2_Q32 = np.uint64(round(np.log(2.0) * 2 ** 32))


def _neg_log1m_q32(b: jax.Array) -> jax.Array:
    """u32 bits → Q32 fixed-point −ln(1 − b/2^32), exact integer pipeline."""
    x = (np.uint64(1) << np.uint64(32)) - b.astype(_U64)   # [1, 2^32]
    k = np.uint64(63) - jax.lax.clz(x.astype(jnp.int64)).astype(_U64)
    m = x << (np.uint64(63) - k)                            # top bit at 63
    frac = (m << np.uint64(1)) >> np.uint64(1)              # low 63 = fraction
    idx = (frac >> np.uint64(63 - _LOG_BITS)).astype(jnp.int32)
    rem = (frac >> np.uint64(63 - _LOG_BITS - 24)) & np.uint64((1 << 24) - 1)
    tbl = jnp.asarray(_LOG_TBL_NP, _U64)
    lo = tbl[idx]
    hi = tbl[idx + 1]
    log2_frac_q32 = lo + (((hi - lo) * rem) >> np.uint64(24))
    log2_x_q32 = (k << np.uint64(32)) + log2_frac_q32
    e2_q32 = (np.uint64(32) << np.uint64(32)) - log2_x_q32  # (32 − log2 x)
    # × ln2 at Q27 (e2 ≤ 2^37, so the product stays under 2^64; ln2's Q27
    # floor costs ~6e-9 relative — no e2 truncation at all).
    return (e2_q32 * (_LN2_Q32 >> np.uint64(5))) >> np.uint64(27)


def exponential_ns(b: jax.Array, mean_ns) -> jax.Array:
    """u32 bits → int64 ns exponential with the given mean.

    Integer pipeline: Q32 −ln(1−u) times the rounded mean; clamped to ≥1 ns
    so events always advance time. The mean scaling is one f64 multiply +
    round (IEEE-exact, backend-identical); everything else is integer."""
    e_q32 = _neg_log1m_q32(b)
    mean = jnp.round(jnp.asarray(mean_ns, jnp.float64)).astype(_U64)
    # Means are clamped to 2^38 ns (~4.6 simulated minutes, outside any
    # ladder config) to keep the integer pipeline overflow-free rather than
    # silently wrapping.
    mean = jnp.minimum(mean, np.uint64(1) << np.uint64(38))
    # d = mean · e_q32 / 2^32 via a hi/lo split so nothing overflows u64 and
    # the only truncation is 7 low bits of the Q32 fraction (~3e-8 of e):
    # mean·e_hi ≤ 2^38·22.2 and mean·(e_lo>>7) ≤ 2^38·2^25 = 2^63.
    e_hi = e_q32 >> np.uint64(32)
    e_lo = e_q32 & np.uint64(0xFFFFFFFF)
    d = mean * e_hi + ((mean * (e_lo >> np.uint64(7))) >> np.uint64(25))
    return jnp.maximum(d.astype(jnp.int64), 1)


def randint(b: jax.Array, n) -> jax.Array:
    """u32 bits → integer in [0, n) via 64-bit multiply-shift (exact, no bias
    for n ≪ 2^32 beyond the standard multiply-shift approximation; identical
    in both engines)."""
    n = jnp.asarray(n).astype(jnp.uint64)  # scalar or per-element array
    return ((b.astype(jnp.uint64) * n) >> jnp.uint64(32)).astype(jnp.int32)


# --------------------------------------------------------------------------
# NumPy twins — bit-exact reimplementations for the eager CPU oracle.
#
# Because every transform above is pure integer arithmetic, it has an exact
# host-side twin (no device dispatch per draw — the oracle used to issue
# eager jnp calls, each a device roundtrip). tests/test_rng guards
# jnp-vs-numpy equality draw-for-draw. All constants are np.uint64 to dodge
# NumPy's uint64+int -> float64 promotion trap.
# --------------------------------------------------------------------------
_U64_1 = np.uint64(1)


def base_key_np(seed: int) -> np.uint64:
    z = (int(seed) * 0x9E3779B97F4A7C15 + 0x94D049BB133111EB) & ((1 << 64) - 1)
    return np.uint64(z)


def _mix_np(z):
    z = z ^ (z >> np.uint64(30))
    z = z * _C1
    z = z ^ (z >> np.uint64(27))
    z = z * _C2
    z = z ^ (z >> np.uint64(31))
    return z


def bits_np(seed_key: np.uint64, purpose, host, ctr) -> np.ndarray:
    with np.errstate(over="ignore"):  # u64 wraparound is the point
        z = (
            np.uint64(seed_key)
            + np.uint64(purpose) * _P1
            + np.asarray(host, np.uint64) * _P2
            + np.asarray(ctr, np.uint64) * _P3
        )
        z = _mix_np(_mix_np(z))
    return (z >> np.uint64(32)).astype(np.uint32)


def _neg_log1m_q32_np(b: np.ndarray) -> np.ndarray:
    x = (_U64_1 << np.uint64(32)) - b.astype(np.uint64)
    # floor(log2 x) via frexp (exact: x <= 2^32 is exactly representable).
    _, e = np.frexp(x.astype(np.float64))
    k = (e - 1).astype(np.uint64)
    m = x << (np.uint64(63) - k)
    frac = (m << _U64_1) >> _U64_1
    idx = (frac >> np.uint64(63 - _LOG_BITS)).astype(np.int64)
    rem = (frac >> np.uint64(63 - _LOG_BITS - 24)) & np.uint64((1 << 24) - 1)
    lo = _LOG_TBL_NP[idx]
    hi = _LOG_TBL_NP[idx + 1]
    log2_frac_q32 = lo + (((hi - lo) * rem) >> np.uint64(24))
    log2_x_q32 = (k << np.uint64(32)) + log2_frac_q32
    e2_q32 = (np.uint64(32) << np.uint64(32)) - log2_x_q32
    return (e2_q32 * (_LN2_Q32 >> np.uint64(5))) >> np.uint64(27)


def exponential_ns_np(b: np.ndarray, mean_ns) -> np.ndarray:
    e_q32 = _neg_log1m_q32_np(np.asarray(b))
    mean = np.round(np.asarray(mean_ns, np.float64)).astype(np.uint64)
    mean = np.minimum(mean, _U64_1 << np.uint64(38))
    e_hi = e_q32 >> np.uint64(32)
    e_lo = e_q32 & np.uint64(0xFFFFFFFF)
    d = mean * e_hi + ((mean * (e_lo >> np.uint64(7))) >> np.uint64(25))
    return np.maximum(d.astype(np.int64), 1)


def randint_np(b, n) -> np.ndarray:
    return (
        (np.asarray(b, np.uint64) * np.uint64(n)) >> np.uint64(32)
    ).astype(np.int32)
