"""Cross-BACKEND determinism: the engine on the real accelerator vs the
CPU oracle (docs/SEMANTICS.md `Randomness`).

The rest of the suite forces the CPU platform (conftest), so the round-2
regression — identical programs producing different event counts on the
TPU than on CPU, via backend-dependent float transcendentals — was
invisible to it. These tests run the comparison in a SUBPROCESS with the
default (accelerator) platform: skipped cleanly when no live accelerator
is reachable within the probe deadline.

VERDICT r2 #5: ≥1k hosts, ≥50 windows, identical counters (PHOLD).
VERDICT r4 #6: the NET model (TCP + filexfer + Tor) asserted on the chip
too — the full semantic counter set plus per-host summaries.
"""

import json
import os
import re
import subprocess
import sys
import tempfile

import pytest

# Tier-1 wall budget (PR 4): when the axon tunnel is present but dead, the
# no-kill liveness probe eats its full 150s deadline before these tests can
# skip — the single largest line item of a CPU-only tier-1 run, for tests
# that then do nothing. ./ci.sh all (and any accelerator-attached run)
# still exercises them.
pytestmark = pytest.mark.slow

_PHOLD_CHILD = r"""
import json
import shadow1_tpu
import jax
print("BACKEND_UP", jax.default_backend(), flush=True)  # init sentinel
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine

exp = single_vertex_experiment(
    n_hosts=1024, seed=2024, end_time=60 * MS, latency_ns=1 * MS,
    model="phold", model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 4},
)
params = EngineParams(ev_cap=32, outbox_cap=16, max_rounds=64)
eng = Engine(exp, params)
st = eng.run()  # 60 windows on the DEFAULT backend (accelerator when alive)
m = Engine.metrics_dict(st)
cm = CpuEngine(exp, params).run()
print(json.dumps({"backend": jax.default_backend(), "tpu": m, "cpu": cm}))
"""

# The net-model child: lossy TCP file transfers AND a miniature Tor net
# (weighted paths, telescoped circuits, cell streams) on the accelerator,
# vs the CPU oracle. Device work rides 100-window chunks — the tunneled
# TPU faults on long single executions (docs/PERF.md), and this test must
# measure determinism, not fault behavior.
_NET_CHILD = r"""
import json
import numpy as np
import shadow1_tpu
import jax
print("BACKEND_UP", jax.default_backend(), flush=True)  # init sentinel
from shadow1_tpu import ckpt
from shadow1_tpu.consts import SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
import __graft_entry__ as ge
from tests.test_tor_parity import TOR_KEYS

CASES = {
    "filexfer": (
        ge._flagship_exp(64, 2 * SEC), EngineParams(ev_cap=256),
        ("rx_bytes", "flows_done", "done_time"),
    ),
    "tor": (
        ge._tor_exp(24, 10 * SEC),
        EngineParams(ev_cap=128, outbox_cap=32, sockets_per_host=16),
        TOR_KEYS,
    ),
}
out = {"backend": jax.default_backend(), "cases": {}}
for name, (exp, params, sum_keys) in CASES.items():
    eng = Engine(exp, params)
    st = ckpt.run_chunked(eng, chunk=100)
    ts = eng.model_summary(st)
    cpu = CpuEngine(exp, params)
    cm = cpu.run()
    cs = cpu.summary()
    out["cases"][name] = {
        "tpu": Engine.metrics_dict(st),
        "cpu": cm,
        "tpu_sum": {k: np.asarray(ts[k]).tolist() for k in sum_keys},
        "cpu_sum": {k: np.asarray(cs[k]).tolist() for k in sum_keys},
    }
print(json.dumps(out))
"""

# The full cross-engine semantic counter set (tests/test_net_parity.py
# PARITY_KEYS + the NIC/AQM fidelity counters).
SEMANTIC_KEYS = [
    "events", "pkts_sent", "pkts_delivered", "pkts_lost",
    "ev_overflow", "ob_overflow", "tcp_fast_rtx", "tcp_rto", "tcp_ooo_drops",
    "nic_tx_drops", "nic_rx_drops", "nic_aqm_drops",
    "pops_pkt", "pops_deliver", "pops_timer", "pops_txr", "pops_app",
]


def _run_detached_no_kill(src: str, timeout_s: float, env, cwd,
                          skip_msg: str) -> tuple[str, str, int]:
    """Run ``python -c src`` with a deadline that NEVER kills the child:
    SIGKILLing a process inside tunnel device-init or device-execution
    wedges the tunnel for every subsequent client (docs/PERF.md; observed
    round 5 when this file's old timeout-kill probe took the device down).
    On deadline the child is left to finish detached and the test skips.
    Returns (stdout, stderr, returncode) on normal exit."""
    with tempfile.TemporaryDirectory() as td:
        out_p, err_p = os.path.join(td, "out"), os.path.join(td, "err")
        with open(out_p, "w") as fo, open(err_p, "w") as fe:
            proc = subprocess.Popen(
                [sys.executable, "-c", src],
                stdout=fo, stderr=fe, text=True, env=env, cwd=cwd,
                start_new_session=True,
            )
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pytest.skip(skip_msg + " (child left to finish detached)")
        return open(out_p).read(), open(err_p).read(), proc.returncode


# One probe per test FILE, not per test: a hung tunnel eats the full probe
# deadline, and paying it once already answers "is an accelerator alive"
# for every test here (the children re-verify via their BACKEND_UP
# sentinel anyway).
_probe_result: tuple | None = None


def _run_on_accelerator(child_src: str, timeout_s: int) -> dict:
    """Run ``child_src`` on the default (accelerator) platform; skip when no
    live accelerator exists, FAIL when the backend came up and the engine
    then broke on it (the regression these tests exist to catch)."""
    global _probe_result
    # Undo conftest's CPU-forcing env mutations for the child so it boots
    # the default accelerator platform. (Probing via shadow1_tpu.platform
    # would inherit the conftest env and could mis-report cpu on machines
    # configured by JAX_PLATFORMS alone.)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    if "XLA_FLAGS" in env:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", env["XLA_FLAGS"]
        ).strip()
        if flags:
            env["XLA_FLAGS"] = flags
        else:
            del env["XLA_FLAGS"]  # whitespace-only XLA_FLAGS is a hard error
    cwd = str(__import__("pathlib").Path(__file__).resolve().parent.parent)
    # Cheap liveness probe first (hung backend init is a known failure mode
    # — platform.py). NEVER kill the probe child mid-init: SIGKILLing a
    # process inside tunnel device-init is precisely what wedges the tunnel
    # for every subsequent client (docs/PERF.md; observed again round 5 when
    # this probe's own timeout-kill took the device down). On deadline the
    # child is left to finish detached and the test skips.
    probe_src = "import jax; print(jax.default_backend(), len(jax.devices()))"
    if _probe_result is None:
        try:
            _probe_result = _run_detached_no_kill(
                probe_src, 150, env, cwd,
                skip_msg="accelerator backend init exceeded 150s probe deadline",
            )
        except BaseException:  # incl. the deadline Skip — cache it, re-raise
            _probe_result = ("", "probe deadline exceeded (cached)", 1)
            raise
    stdout, stderr, rc = _probe_result
    if rc != 0 or stdout.split()[:1] in ([], ["cpu"]):
        pytest.skip(f"no live accelerator backend: {stdout} {stderr[-300:]}")
    # Same no-kill rule for the real child: on deadline it is left to finish
    # detached (a SIGKILL mid-device-execution wedges the tunnel).
    stdout, stderr, rc = _run_detached_no_kill(
        child_src, timeout_s, env, cwd,
        skip_msg=f"accelerator backend run exceeded {timeout_s}s",
    )
    if rc != 0:
        if "BACKEND_UP" in stdout:
            # The backend initialized and THEN the engine failed: that is a
            # backend-specific regression — fail, don't skip.
            raise AssertionError(
                f"engine failed on live accelerator backend:\n{stderr[-2000:]}"
            )
        pytest.skip(f"accelerator backend failed to initialize: {stderr[-500:]}")
    r = json.loads(stdout.strip().splitlines()[-1])
    if r["backend"] in ("", "cpu"):
        pytest.skip(f"default backend is {r['backend']!r} — nothing to compare")
    return r


def test_accelerator_vs_oracle_counters():
    r = _run_on_accelerator(_PHOLD_CHILD, timeout_s=600)
    for k in ("events", "pkts_sent", "pkts_delivered", "pkts_lost",
              "ev_overflow", "ob_overflow"):
        assert r["tpu"][k] == r["cpu"][k], (k, r["tpu"][k], r["cpu"][k])


def test_accelerator_net_model_vs_oracle():
    """The TCP/Tor path on the real chip under a parity assertion (VERDICT
    r4 #6): full semantic counters + per-host summaries, bit-identical."""
    r = _run_on_accelerator(_NET_CHILD, timeout_s=1500)
    for name, case in r["cases"].items():
        for k in SEMANTIC_KEYS:
            assert case["tpu"][k] == case["cpu"][k], (name, k, case["tpu"][k],
                                                      case["cpu"][k])
        assert case["tpu"]["events"] > 0, name
        for k, tv in case["tpu_sum"].items():
            assert tv == case["cpu_sum"][k], (name, k)
