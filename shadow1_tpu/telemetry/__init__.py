"""Telemetry plane: on-device metrics ring, phase profiler, metrics registry.

Three coordinated observability pieces (see docs/OBSERVABILITY.md):

* ``telemetry.ring`` — per-window counter deltas recorded on device inside
  the jitted window loop, drained at chunk boundaries (the true time series
  the chunk-averaged heartbeat cannot provide);
* ``telemetry.profiler`` — host-side phase spans exported as Chrome
  trace-event JSON (Perfetto-viewable);
* ``telemetry.registry`` — the one named-counter namespace shared by the
  tpu, sharded and cpu engines, with Prometheus text exposition and the
  JSONL record schema.

``registry`` is jax-free and safe for tools; ``ring`` pulls in jax — import
it lazily from host-only paths.
"""

from shadow1_tpu.telemetry.profiler import (  # noqa: F401
    PH_CHECKPOINT,
    PH_COMPILE,
    PH_DEVICE_TRACE,
    PH_DRAIN,
    PH_INIT,
    PH_RUN_CHUNK,
    PhaseProfiler,
    device_trace,
    maybe_span,
)
from shadow1_tpu.telemetry.registry import (  # noqa: F401
    DROP_FIELDS,
    DROP_SPECS,
    METRIC_SPECS,
    RECORD_TYPES,
    RING_COUNTERS,
    RING_DIGESTS,
    RING_FIELDS,
    RING_GAUGES,
    RING_WORK,
    ExpositionServer,
    normalize,
    to_prometheus,
)
