"""Aux subsystems: pipes, DNS registry, pcap capture, logger, tools."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from shadow1_tpu.config.experiment import build_experiment
from shadow1_tpu.consts import MS, SEC
from shadow1_tpu.net.pipe import pipe_init, pipe_read, pipe_readable, pipe_write


def test_pipe_fifo_and_capacity():
    h = 4
    pt = pipe_init(h, n_pipes=2, mq_cap=2)
    allh = jnp.ones(h, bool)
    p0 = jnp.zeros(h, jnp.int32)
    # two writes FIFO
    pt, ok1 = pipe_write(pt, allh, p0, jnp.full(h, 10, jnp.int32),
                         jnp.full(h, 111, jnp.int32), capacity=64)
    pt, ok2 = pipe_write(pt, allh, p0, jnp.full(h, 20, jnp.int32),
                         jnp.full(h, 222, jnp.int32), capacity=64)
    assert bool(ok1.all()) and bool(ok2.all())
    assert bool(pipe_readable(pt, p0).all())
    # mq full (cap 2): third write refused
    pt, ok3 = pipe_write(pt, allh, p0, jnp.full(h, 5, jnp.int32),
                         jnp.full(h, 333, jnp.int32), capacity=64)
    assert not bool(ok3.any())
    # reads come back in write order — including after slot reuse
    pt, got, n, m = pipe_read(pt, allh, p0)
    assert bool(got.all()) and int(n[0]) == 10 and int(m[0]) == 111
    pt, ok4 = pipe_write(pt, allh, p0, jnp.full(h, 30, jnp.int32),
                         jnp.full(h, 444, jnp.int32), capacity=64)
    assert bool(ok4.all())
    pt, got, n, m = pipe_read(pt, allh, p0)
    assert int(n[0]) == 20 and int(m[0]) == 222  # FIFO survives slot reuse
    pt, got, n, m = pipe_read(pt, allh, p0)
    assert int(n[0]) == 30 and int(m[0]) == 444
    pt, got, n, m = pipe_read(pt, allh, p0)
    assert not bool(got.any())
    # byte-capacity refusal
    pt, okbig = pipe_write(pt, allh, p0, jnp.full(h, 100, jnp.int32),
                           jnp.full(h, 1, jnp.int32), capacity=64)
    assert not bool(okbig.any())
    assert int(pt.written[0, 0]) == 60 and int(pt.drained[0, 0]) == 60


def _doc():
    return {
        "general": {"seed": 3, "stop_time": "2 s"},
        "engine": {"scheduler": "cpu"},
        "hosts": [
            {"name": "server", "count": 1},
            {"name": "client", "count": 3},
        ],
        "app": {
            "model": "filexfer",
            "groups": {
                "server": {"role": 0},
                "client": {"role": 1, "server": "@server", "flow_bytes": 2000,
                           "flow_count": 1, "start_time": "1 ms"},
            },
        },
    }


def test_dns_registry():
    exp, _, _ = build_experiment(_doc())
    dns = exp.dns
    assert dns.resolve("server") == 0
    assert dns.resolve("client-0") == 1 and dns.resolve("client-2") == 3
    assert dns.resolve("client") == 1  # bare group name = first host
    assert dns.reverse(0) == "server" and dns.reverse(3) == "client-2"
    assert dns.vertex_of(2) == 0
    assert len(dns) == 4
    with pytest.raises(KeyError):
        dns.resolve("nonexistent")


def test_pcap_capture(tmp_path):
    from shadow1_tpu.cpu_engine import CpuEngine
    from shadow1_tpu.tools.pcap import PcapWriter

    exp, params, _ = build_experiment(_doc())
    out = tmp_path / "cap.pcap"
    with PcapWriter(str(out)) as w:
        CpuEngine(exp, params, capture=w).run()
        n = w.n_packets
    assert n > 10
    data = out.read_bytes()
    import struct

    magic, _vmaj, _vmin, _tz, _sig, snaplen, linktype = struct.unpack(
        "<IHHiIII", data[:24]
    )
    assert magic == 0xA1B2C3D4 and linktype == 101
    # walk every record; verify IPv4 headers and count
    off, count = 24, 0
    while off < len(data):
        _ts, _us, incl, _orig = struct.unpack("<IIII", data[off:off + 16])
        assert incl <= snaplen
        pkt = data[off + 16: off + 16 + incl]
        assert pkt[0] == 0x45  # IPv4, IHL 5
        off += 16 + incl
        count += 1
    assert count == n


def test_sim_logger_levels(capsys):
    import io

    from shadow1_tpu.log import SimLogger

    buf = io.StringIO()
    log = SimLogger(stream=buf, level="message")
    log.debug("hidden")
    log.message("shown", sim_ns=5 * MS, host=3, extra=1)
    log.error("boom")
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert len(lines) == 2 and log.n_dropped == 1
    assert lines[0]["msg"] == "shown" and lines[0]["host"] == 3
    assert lines[0]["sim_s"] == 0.005 and lines[0]["extra"] == 1


def test_tracker_records_and_report(tmp_path, capsys):
    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.log import tracker_records
    from shadow1_tpu.tools.heartbeat_report import load_records, summarize

    exp, params, _ = build_experiment(_doc())
    eng = Engine(exp, params)
    st = eng.run()
    recs = tracker_records(eng, st)
    assert len(recs) == 4
    assert recs[1]["nic_rx_bytes"] > 0 and recs[0]["nic_tx_bytes"] > 0
    assert recs[0]["rx_bytes"] > 0  # app-level bytes at the server
    assert all("flows_done" in r for r in recs)
    # heartbeat_report consumes a mixed log of heartbeats + tracker records
    log = tmp_path / "run.log"
    hb = {"type": "heartbeat", "sim_time_s": 2.0, "wall_s": 1.0,
          "windows": 100, "events_per_sec": 50.0, "sim_per_wall": 2.0,
          "delta": {"events": 50, "windows": 100, "pkts_delivered": 30}}
    with open(log, "w") as f:
        f.write(json.dumps(hb) + "\n")
        for r in recs:
            f.write(json.dumps(r) + "\n")
    got = load_records(str(log))
    assert len(got) == 5
    s = summarize(got)
    assert s["heartbeats"] == 1 and s["tracker_records"] == 4
    assert s["events"] == 50
