"""Block-cached counter RNG draws for the CPU oracle.

The oracle consumes draws one at a time; issuing one eager JAX call per draw
would dominate its runtime. Draws are pure functions of (purpose, host,
counter), so we batch-compute blocks of consecutive counters with the exact
same jnp transforms the TPU engine traces (shadow1_tpu.rng) and cache them —
bit-identical values, amortized dispatch.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow1_tpu import rng

_BLOCK = 256


class DrawCache:
    def __init__(self, seed: int):
        self.key = rng.base_key(seed)
        self._bits: dict[tuple, np.ndarray] = {}

    def bits(self, purpose: int, host: int, ctr: int) -> np.uint32:
        blk = ctr // _BLOCK
        k = (purpose, host, blk)
        got = self._bits.get(k)
        if got is None:
            ctrs = jnp.arange(blk * _BLOCK, (blk + 1) * _BLOCK)
            hosts = jnp.full(_BLOCK, host)
            got = np.asarray(rng.bits_v(self.key, purpose, hosts, ctrs))
            self._bits[k] = got
        return got[ctr % _BLOCK]

    def uniform(self, purpose: int, host: int, ctr: int) -> float:
        return float(rng.uniform01(jnp.uint32(self.bits(purpose, host, ctr))))

    def exponential_ns(self, purpose: int, host: int, ctr: int, mean_ns: float) -> int:
        return int(rng.exponential_ns(jnp.uint32(self.bits(purpose, host, ctr)), mean_ns))

    def randint(self, purpose: int, host: int, ctr: int, n: int) -> int:
        return int(rng.randint(jnp.uint32(self.bits(purpose, host, ctr)), n))
