"""Fleet mode: batched experiment sweeps as one device program.

The contract under test (docs/SEMANTICS.md "Fleet contract"): lane e of a
vmapped fleet run is bit-indistinguishable from running experiment e
alone — per-window digest streams and every parity counter match the solo
tpu engine AND the cpu oracle; an E=1 fleet equals a plain run; a fleet
snapshot resumes bit-identically and any lane slices out as a
solo-resumable state. Plus the config half: sweep expansion, unknown-key
rejection, and the shape-uniformity errors.
"""

import dataclasses
import json
import subprocess
import sys

import numpy as np
import pytest

from shadow1_tpu.ckpt import load_state, run_chunked, save_state
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import EXIT_CONFIG, MS, EngineParams
from shadow1_tpu.core.digest import SUBSYSTEMS
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.fleet.engine import FleetEngine, slice_experiment
from shadow1_tpu.fleet.expand import (
    FleetConfigError,
    check_uniform,
    expand_sweep,
    expand_sweep_docs,
)
from shadow1_tpu.telemetry.ring import drain_ring
from shadow1_tpu.txn import CapacityExceededError

N_WINDOWS = 15
PARAMS = EngineParams(ev_cap=32, outbox_cap=16, metrics_ring=N_WINDOWS,
                      state_digest=1)


def base_doc(count=16, stop_ms=150):
    return {
        "general": {"seed": 7, "stop_time": f"{stop_ms} ms"},
        "engine": {"scheduler": "tpu", "ev_cap": 32, "outbox_cap": 16,
                   "metrics_ring": N_WINDOWS, "state_digest": 1},
        "network": {"single_vertex": {"latency": "10 ms"}},
        "hosts": [{"name": "h", "count": count}],
        "app": {"model": "phold",
                "params": {"mean_delay_ns": 2.0e7, "init_events": 2}},
    }


def sweep_doc():
    """The standard 3-experiment sweep: seed change, loss-rate change, and
    a churn (restart) fault schedule — one lane per fleet-variable axis."""
    doc = base_doc()
    doc["sweep"] = {
        "seeds": [7, 8, 9],
        "vary": [
            {},
            {"network": {"single_vertex": {"loss": 0.05}}},
            {"faults": {"hosts": [
                {"group": "h", "down_at": "40 ms", "up_at": "80 ms"}]}},
        ],
    }
    return doc


def digest_stream(st, window_ns):
    return {
        r["window"]: tuple(r[f"dg_{s}"] for s in SUBSYSTEMS)
        for r in drain_ring(st, window_ns)
        if r["type"] == "ring"
    }


@pytest.fixture(scope="module")
def fleet_run():
    """One shared fleet run of the standard sweep (compile amortized
    across the parity tests below)."""
    plan = expand_sweep(sweep_doc())
    eng = FleetEngine(plan.exps, plan.params, plan.max_rounds)
    st = eng.run(n_windows=N_WINDOWS)
    return plan, eng, st


# ---------------------------------------------------------------------------
# sweep expansion / validation
# ---------------------------------------------------------------------------

def test_sweep_expansion_seeds_and_vary():
    plan = expand_sweep(sweep_doc())
    assert plan.n_exp == 3
    assert [e.seed for e in plan.exps] == [7, 8, 9]
    assert float(plan.exps[1].loss_vv[0, 0]) == pytest.approx(0.05)
    assert plan.exps[2].faults is not None and plan.exps[0].faults is None
    assert plan.labels[1] == {"exp": 1, "seed": 8}


def test_sweep_count_generates_seeds():
    doc = base_doc()
    doc["sweep"] = {"count": 4, "base_seed": 20}
    docs = expand_sweep_docs(doc)
    assert [d["general"]["seed"] for d in docs] == [20, 21, 22, 23]
    assert all("sweep" not in d for d in docs)


def test_sweep_unknown_key_and_length_mismatch_rejected():
    doc = base_doc()
    doc["sweep"] = {"seedz": [1, 2]}
    with pytest.raises(FleetConfigError):
        expand_sweep_docs(doc)
    doc["sweep"] = {"seeds": [1, 2], "vary": [{}, {}, {}]}
    with pytest.raises(FleetConfigError, match="disagree"):
        expand_sweep_docs(doc)
    doc["sweep"] = {}
    with pytest.raises(FleetConfigError, match="at least one"):
        expand_sweep_docs(doc)
    # Malformed value TYPES are structured rejections too, never raw
    # TypeError/ValueError tracebacks (the CLI only maps FleetConfigError
    # to the fleet_config record).
    doc["sweep"] = {"seeds": 5}
    with pytest.raises(FleetConfigError, match="must be a list"):
        expand_sweep_docs(doc)
    doc["sweep"] = {"count": "sixteen"}
    with pytest.raises(FleetConfigError, match="must be an integer"):
        expand_sweep_docs(doc)
    doc["sweep"] = {"seeds": ["a", "b"]}
    with pytest.raises(FleetConfigError, match=r"seeds\[0\]"):
        expand_sweep_docs(doc)
    doc["sweep"] = {"vary": {"not": "a list"}}
    with pytest.raises(FleetConfigError, match="must be a list"):
        expand_sweep_docs(doc)


def test_sweep_vary_none_entry_means_no_override():
    """A YAML `- ~` (or bare `-`) vary entry is 'no override', not a
    TypeError: the natural way to hold a lane at the base config."""
    doc = base_doc()
    doc["sweep"] = {"seeds": [3, 4], "vary": [None, {}]}
    docs = expand_sweep_docs(doc)
    assert [d["general"]["seed"] for d in docs] == [3, 4]
    doc["sweep"] = {"vary": [None, 42]}
    with pytest.raises(FleetConfigError, match="must be a mapping"):
        expand_sweep_docs(doc)


def test_sweep_vary_typo_fails_in_standard_validation():
    """A typo inside a vary entry hits the same _reject_unknown wall every
    solo config does — the merged doc compiles through build_experiment."""
    doc = base_doc()
    doc["sweep"] = {"vary": [{"general": {"stop_tme": "1 s"}}]}
    with pytest.raises(AssertionError, match="stop_tme"):
        expand_sweep(doc)


def test_sweep_shape_change_rejected_with_shape_error():
    """Swept knobs that change plane shapes (host count, caps, latency,
    horizon) raise the structured shape error naming the knob."""
    doc = base_doc()
    doc["sweep"] = {"vary": [{}, {"hosts": [{"name": "h", "count": 8}]}]}
    with pytest.raises(FleetConfigError, match="plane shapes") as ei:
        expand_sweep(doc)
    assert ei.value.kind == "shape" and ei.value.knob == "n_hosts"

    doc["sweep"] = {"vary": [{}, {"engine": {"ev_cap": 64}}]}
    with pytest.raises(FleetConfigError, match="fleet-uniform") as ei:
        expand_sweep(doc)
    assert ei.value.kind == "shape" and ei.value.knob == "engine.ev_cap"

    doc["sweep"] = {"vary": [
        {}, {"network": {"single_vertex": {"latency": "5 ms"}}}]}
    with pytest.raises(FleetConfigError, match="conservative window") as ei:
        expand_sweep(doc)
    assert ei.value.kind == "shape"

    doc["sweep"] = {"vary": [{}, {"general": {"stop_time": "1 s"}}]}
    with pytest.raises(FleetConfigError) as ei:
        expand_sweep(doc)
    assert ei.value.knob == "end_time"


def test_sweep_may_vary_max_rounds_only_engine_knob():
    doc = base_doc()
    doc["sweep"] = {"vary": [{}, {"engine": {"max_rounds": 128}}]}
    plan = expand_sweep(doc)
    assert plan.max_rounds == [256, 128]


def test_check_uniform_model_cfg_guard():
    a = single_vertex_experiment(n_hosts=4, seed=1, end_time=20 * MS,
                                 latency_ns=10 * MS, model="phold",
                                 model_cfg={"mean_delay_ns": 1e6})
    b = single_vertex_experiment(n_hosts=4, seed=2, end_time=20 * MS,
                                 latency_ns=10 * MS, model="phold",
                                 model_cfg={"mean_delay_ns": 2e6})
    with pytest.raises(FleetConfigError) as ei:
        check_uniform([a, b], [EngineParams()] * 2)
    assert ei.value.knob == "model_cfg" and ei.value.kind == "uniform"


# ---------------------------------------------------------------------------
# fleet <-> solo parity (the tentpole contract)
# ---------------------------------------------------------------------------

def test_fleet_digest_and_metric_parity_vs_solo_tpu_and_cpu(fleet_run):
    """Every lane's digest stream and metrics bit-match running that
    experiment alone — on the solo batched engine AND the cpu oracle
    (the 3-experiment acceptance gate; ci.sh runs the same check via
    tools/fleetprobe.py)."""
    plan, eng, st = fleet_run
    for e, exp in enumerate(plan.exps):
        lane = slice_experiment(st, e)
        fleet_digs = digest_stream(lane, eng.window)
        fleet_m = {k: int(v) for k, v in lane.metrics._asdict().items()}

        solo = Engine(exp, plan.params)
        st_solo = solo.run(n_windows=N_WINDOWS)
        assert Engine.metrics_dict(st_solo) == fleet_m, f"exp {e} metrics"
        assert digest_stream(st_solo, solo.window) == fleet_digs, \
            f"exp {e} vs solo tpu"

        cpu = CpuEngine(exp, plan.params)
        cm = cpu.run(n_windows=N_WINDOWS)
        oracle = {r["window"]: tuple(r[f"dg_{s}"] for s in SUBSYSTEMS)
                  for r in cpu.digest_rows}
        assert {w: fleet_digs[w] for w in oracle} == oracle, \
            f"exp {e} vs cpu oracle"
        for k in ("events", "pkts_sent", "pkts_delivered", "pkts_lost",
                  "down_events", "down_pkts", "host_restarts"):
            assert cm[k] == fleet_m[k], (e, k)


def test_fleet_e1_equals_plain_run():
    """An E=1 fleet is exactly a plain run wearing one vmap axis."""
    exp = single_vertex_experiment(
        n_hosts=8, seed=3, end_time=100 * MS, latency_ns=10 * MS,
        loss=0.02, model="phold",
        model_cfg={"mean_delay_ns": float(20 * MS), "init_events": 2})
    fleet = FleetEngine([exp], PARAMS)
    stf = fleet.run(n_windows=10)
    solo = Engine(exp, PARAMS)
    sts = solo.run(n_windows=10)
    lane = slice_experiment(stf, 0)
    assert Engine.metrics_dict(sts) == \
        {k: int(v) for k, v in lane.metrics._asdict().items()}
    assert digest_stream(sts, solo.window) == digest_stream(lane,
                                                            fleet.window)
    # Aggregate view of an E=1 fleet is the solo metrics dict verbatim.
    assert FleetEngine.metrics_dict(stf) == Engine.metrics_dict(sts)


def test_fleet_resume_mid_fleet_bit_identical(fleet_run, tmp_path):
    """Snapshot the whole fleet mid-run, resume into a fresh engine:
    digest stream and final state bit-match the straight run."""
    plan, eng, ref = fleet_run
    path = str(tmp_path / "fleet.npz")
    st_half = eng.run(n_windows=8)
    save_state(st_half, path)

    eng2 = FleetEngine(plan.exps, plan.params, plan.max_rounds)
    st = load_state(eng2.init_state(), path)
    st = eng2.run(st, n_windows=N_WINDOWS - 8)
    for e in range(eng.n_exp):
        a, b = slice_experiment(ref, e), slice_experiment(st, e)
        assert digest_stream(a, eng.window) == digest_stream(b, eng.window)
    for la, lb in zip(np.asarray(ref.win_start), np.asarray(st.win_start)):
        assert la == lb
    for k, v in ref.metrics._asdict().items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(getattr(st.metrics, k)), k)


def test_fleet_slice_resumes_solo(fleet_run, tmp_path):
    """Per-experiment resume slicing: lane e of a mid-run fleet snapshot
    loads into a SOLO engine and continues bit-identically to the solo
    straight run."""
    plan, eng, ref = fleet_run
    e = 1  # the loss-rate lane
    st_half = eng.run(n_windows=8)
    path = str(tmp_path / "lane.npz")
    save_state(slice_experiment(st_half, e), path)

    solo = Engine(plan.exps[e], plan.params)
    st = load_state(solo.init_state(), path)
    st = solo.run(st, n_windows=N_WINDOWS - 8)
    ref_digs = digest_stream(slice_experiment(ref, e), eng.window)
    assert digest_stream(st, solo.window) == ref_digs


# ---------------------------------------------------------------------------
# rejections / boundary policies
# ---------------------------------------------------------------------------

def test_fleet_accepts_auto_caps_and_retry():
    """Rejection-lift regression (PR 13): --auto-caps and --on-overflow
    retry were structured kind="mode" rejections through PR 12 — both now
    CONSTRUCT (the recovery semantics are proven in
    tests/test_fleet_recover.py)."""
    plan = expand_sweep(sweep_doc())
    eng = FleetEngine(plan.exps,
                      dataclasses.replace(plan.params, auto_caps=1))
    assert eng.params.auto_caps == 1
    eng = FleetEngine(plan.exps,
                      dataclasses.replace(plan.params, on_overflow="retry"))
    assert eng.params.on_overflow == "retry"


def test_fleet_halt_names_the_overflowing_experiment():
    """on_overflow=halt under --fleet: the boundary check runs per
    experiment and the structured error names the lane (and seed) whose
    cap overflowed."""
    from shadow1_tpu.fleet.run import run_fleet

    exps = [
        single_vertex_experiment(
            n_hosts=8, seed=5, end_time=20 * MS, latency_ns=1 * MS,
            loss=loss, model="phold",
            model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 6})
        # 50% loss keeps lane 0's event population under ev_cap=8; the
        # lossless lane 1 overflows — halt must blame lane 1, not lane 0.
        for loss in (0.5, 0.0)
    ]
    p = EngineParams(ev_cap=8, on_overflow="halt")
    eng = FleetEngine(exps, p)
    with pytest.raises(CapacityExceededError) as ei:
        run_fleet(eng, n_windows=20, every_windows=5, stream=False,
                  labels=[{"exp": 0, "seed": 5}, {"exp": 1, "seed": 5}])
    assert ei.value.knob == "ev_cap"
    assert "fleet experiment 1" in str(ei.value)


def test_fleet_selfcheck_runs_per_experiment(fleet_run):
    """--selfcheck under fleet verifies the drop-accounting identity per
    lane — a clean sweep passes (violation paths are exercised by the
    solo txn tests; the identity math is shared)."""
    plan, _, _ = fleet_run
    from shadow1_tpu.fleet.run import run_fleet

    p = dataclasses.replace(plan.params, selfcheck=1)
    eng = FleetEngine(plan.exps, p, plan.max_rounds)
    st, hb = run_fleet(eng, n_windows=6, every_windows=3, stream=False,
                       selfcheck=True, labels=plan.labels)
    assert int(np.asarray(st.metrics.windows).max()) == 6
    assert len(hb.records) == 2  # one heartbeat per chunk


# ---------------------------------------------------------------------------
# records / report tooling
# ---------------------------------------------------------------------------

def test_final_records_shapes(fleet_run):
    plan, eng, st = fleet_run
    from shadow1_tpu.fleet.run import final_records

    recs, summary = final_records(eng, st, plan.labels, N_WINDOWS, 1.0)
    assert [r["exp"] for r in recs] == [0, 1, 2]
    assert all(r["type"] == "fleet_exp" for r in recs)
    assert recs[2]["faults"]["host_restarts"] > 0
    assert "faults" not in recs[0]
    assert summary["type"] == "fleet_summary"
    assert summary["experiments"] == 3
    assert summary["events_per_exp"] == \
        [r["metrics"]["events"] for r in recs]
    # Aggregate counters sum; gauges max (never E x the lane value).
    assert summary["metrics"]["events"] == sum(summary["events_per_exp"])
    assert summary["metrics"]["windows"] == N_WINDOWS


def test_ring_records_tagged_per_experiment(fleet_run):
    plan, eng, st = fleet_run
    recs = eng.drain_rings(st)
    assert {r["exp"] for r in recs} == {0, 1, 2}
    by_exp = {}
    for r in recs:
        if r["type"] == "ring":
            by_exp.setdefault(r["exp"], []).append(r)
    assert all(len(v) == N_WINDOWS for v in by_exp.values())
    # Lane 1 (5% loss) must record losses some window; lane 0 none.
    assert sum(r["pkts_lost"] for r in by_exp[1]) > 0
    assert sum(r["pkts_lost"] for r in by_exp[0]) == 0


def test_captune_groups_by_experiment(fleet_run):
    """A sweep's cap verdicts come out one per experiment — the experiment
    id is a grouping key only, never part of the peak math."""
    plan, eng, st = fleet_run
    from shadow1_tpu.fleet.run import final_records
    from shadow1_tpu.tools import captune

    recs, summary = final_records(eng, st, plan.labels, N_WINDOWS, 1.0)
    rows = recs + [summary] + eng.drain_rings(st)
    groups = captune.group_records(rows)
    assert {"(run) [exp 0]", "(run) [exp 1]", "(run) [exp 2]"} <= set(groups)
    advice = {g: captune.advise(*captune.peaks_from_records(rs))
              for g, rs in groups.items()}
    for g in ("(run) [exp 0]", "(run) [exp 1]", "(run) [exp 2]"):
        knobs = {r["knob"] for r in advice[g]}
        assert "ev_cap" in knobs
        ev = next(r for r in advice[g] if r["knob"] == "ev_cap")
        assert ev["cap"] == plan.params.ev_cap
        assert 0 < ev["peak"] <= plan.params.ev_cap


def test_heartbeat_report_groups_rings_by_experiment(fleet_run, tmp_path,
                                                     capsys):
    plan, eng, st = fleet_run
    from shadow1_tpu.fleet.run import final_records
    from shadow1_tpu.tools import heartbeat_report

    recs, summary = final_records(eng, st, plan.labels, N_WINDOWS, 1.0)
    log = tmp_path / "fleet.log"
    with open(log, "w") as f:
        for r in recs + [summary] + eng.drain_rings(st):
            f.write(json.dumps(r) + "\n")
    out = heartbeat_report.summarize(heartbeat_report.load_records(str(log)))
    printed = capsys.readouterr().out
    assert out["fleet_experiments"] == 3
    assert out["ring_experiments"] == 3
    assert set(out["ring_by_exp"]) == {0, 1, 2}
    assert "experiment 2" in printed
    # Per-exp stats stay per-exp: lane 0 (lossless) ranks zero pkts_lost
    # even though lane 2 lost plenty.
    assert out["ring_by_exp"][0]["pkts_lost"]["max"] == 0


# ---------------------------------------------------------------------------
# CLI (subprocess — fast config, compile cache shared via conftest env)
# ---------------------------------------------------------------------------

def _write_sweep_cfg(tmp_path, extra=""):
    cfg = tmp_path / "sweep.yaml"
    cfg.write_text(
        "general: {seed: 7, stop_time: 60 ms}\n"
        "engine: {scheduler: tpu, ev_cap: 32, outbox_cap: 16}\n"
        "network: {single_vertex: {latency: 10 ms}}\n"
        "hosts: [{name: h, count: 8}]\n"
        "app: {model: phold, params: {mean_delay_ns: 2.0e7, "
        "init_events: 2}}\n"
        "sweep: {seeds: [7, 8, 9]}\n" + extra
    )
    return cfg


def test_cli_fleet_records(tmp_path):
    cfg = _write_sweep_cfg(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr[-800:]
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert [r["type"] for r in lines] == \
        ["fleet_exp"] * 3 + ["fleet_summary"]
    assert [r["seed"] for r in lines[:3]] == [7, 8, 9]
    assert lines[3]["experiments"] == 3


def test_cli_fleet_faults_off_strips_schedules(tmp_path):
    """--faults off under --fleet is the same healthy-world A/B as solo:
    every experiment's fault schedule (vary[]-added ones included) is
    stripped, so churn lanes run clean."""
    cfg = tmp_path / "churn_sweep.yaml"
    cfg.write_text(
        "general: {seed: 7, stop_time: 60 ms}\n"
        "engine: {scheduler: tpu, ev_cap: 32, outbox_cap: 16}\n"
        "network: {single_vertex: {latency: 10 ms}}\n"
        "hosts: [{name: h, count: 8}]\n"
        "app: {model: phold, params: {mean_delay_ns: 2.0e7, "
        "init_events: 2}}\n"
        "sweep:\n"
        "  seeds: [7, 8]\n"
        "  vary:\n"
        "    - {}\n"
        "    - {faults: {hosts: [{group: h, down_at: 20 ms, "
        "up_at: 40 ms}]}}\n"
    )
    on = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet"],
        capture_output=True, text=True)
    off = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
         "--faults", "off"],
        capture_output=True, text=True)
    assert on.returncode == 0 and off.returncode == 0, off.stderr[-500:]
    rec_on = json.loads(on.stdout.strip().splitlines()[1])
    rec_off = json.loads(off.stdout.strip().splitlines()[1])
    assert rec_on["faults"]["host_restarts"] > 0
    assert "faults" not in rec_off
    assert rec_off["metrics"]["host_restarts"] == 0


def test_cli_fleet_corrupt_ckpt_falls_back_to_fresh_start(tmp_path):
    """A supervised fleet child whose --ckpt snapshot is corrupt restarts
    from scratch (solo-path policy) instead of crash-looping."""
    cfg = _write_sweep_cfg(tmp_path)
    ck = tmp_path / "fleet.npz"
    ck.write_bytes(b"not a checkpoint at all")
    out = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
         "--ckpt", str(ck), "--supervised-child"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr[-800:]
    assert "discarding corrupt fleet checkpoint" in out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["type"] == "fleet_summary" and not summary["resumed"]


def test_cli_fleet_structured_rejections(tmp_path):
    cfg = _write_sweep_cfg(tmp_path)

    def run(*flags):
        out = subprocess.run(
            [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
             *flags], capture_output=True, text=True)
        return out.returncode, out.stdout.strip().splitlines()

    rc, lines = run("--engine", "sharded")
    assert rc == EXIT_CONFIG
    err = json.loads(lines[-1])
    assert err["error"] == "fleet_config" and err["kind"] == "mode"
    # Rejection-lift regression (PR 13): --auto-caps / --on-overflow retry
    # under --fleet no longer exit with the old kind="mode" records — the
    # sweep runs (recovery semantics proven in tests/test_fleet_recover.py).
    for flags in (("--auto-caps",), ("--on-overflow", "retry")):
        rc, lines = run(*flags, "--windows", "4")
        assert rc == 0, (flags, lines[-1:])
        assert json.loads(lines[-1])["type"] == "fleet_summary", flags
    # No sweep: section -> schema-kind rejection.
    solo = tmp_path / "solo.yaml"
    solo.write_text(cfg.read_text().replace("sweep: {seeds: [7, 8, 9]}\n",
                                            ""))
    out = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(solo), "--fleet"],
        capture_output=True, text=True)
    assert out.returncode == EXIT_CONFIG
    assert json.loads(out.stdout.strip().splitlines()[-1])["kind"] == \
        "schema"


@pytest.mark.slow
def test_cli_fleet_ckpt_resume_bit_identical(tmp_path):
    """A --fleet --ckpt run killed mid-flight resumes from the fleet
    snapshot and finishes with per-experiment metrics identical to a
    straight run (the supervised chunk+resume recipe, fleet-shaped)."""
    import os

    cfg = _write_sweep_cfg(tmp_path)
    straight = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet"],
        capture_output=True, text=True)
    assert straight.returncode == 0, straight.stderr[-800:]
    ck = tmp_path / "fleet_ck.npz"
    env = {**os.environ, "SHADOW1_OBS_CRASH_AT_NS": "40000000",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0"}
    sup = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
         "--ckpt", str(ck), "--ckpt-every-s", "0", "--heartbeat", "2"],
        capture_output=True, text=True, env=env)
    assert sup.returncode == 0, sup.stderr[-800:]
    assert "respawning" in sup.stderr
    a = [json.loads(l) for l in straight.stdout.strip().splitlines()]
    b = [json.loads(l) for l in sup.stdout.strip().splitlines()]
    for ra, rb in zip(a[:3], b[:3]):
        assert ra["metrics"] == rb["metrics"], ra.get("exp")


@pytest.mark.slow
def test_fleet_net_model_parity():
    """The TCP/NIC plane rides the experiment axis too: a filexfer fleet
    (loss-rate ladder) lane bit-matches its solo run."""
    def fx(seed, loss):
        role = np.full(4, 1, np.int64)
        role[0] = 0
        return single_vertex_experiment(
            n_hosts=4, seed=seed, end_time=2_000 * MS, latency_ns=10 * MS,
            loss=loss, bw_bits=10**7, model="net",
            model_cfg={
                "app": "filexfer",
                "role": role,
                "server": np.zeros(4, np.int64),
                "flow_bytes": np.full(4, 30_000, np.int64),
                "start_time": np.full(4, 1 * MS, np.int64),
                "flow_count": np.where(role == 1, 1, 0),
            })

    exps = [fx(11, 0.0), fx(11, 0.02), fx(12, 0.05)]
    n = 40
    p = dataclasses.replace(PARAMS, metrics_ring=n)
    fleet = FleetEngine(exps, p)
    stf = fleet.run(n_windows=n)
    for e, exp in enumerate(exps):
        solo = Engine(exp, p)
        sts = solo.run(n_windows=n)
        lane = slice_experiment(stf, e)
        assert digest_stream(sts, solo.window) == \
            digest_stream(lane, fleet.window), f"exp {e}"
        assert Engine.metrics_dict(sts) == \
            {k: int(v) for k, v in lane.metrics._asdict().items()}
