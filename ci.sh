#!/usr/bin/env bash
# CI driver — the `./setup test` analogue (reference: setup + cmake + ctest).
#
#   ./ci.sh            fast tier: full suite minus the slow mid-scale tier
#   ./ci.sh all        everything, including 512–1024-host parity
#   ./ci.sh smoke      config + events + ckpt/obs/telemetry + tune + digest
#                      fast paths (tgen-based tune tests stay in fast/all),
#                      plus a tiny tpu-vs-cpu paritytrace bisect on the
#                      rung-1 config: inject a window-8 corruption, assert
#                      the flight recorder localizes it to exactly window 8
#
# Tests force the CPU platform with 8 virtual devices (tests/conftest.py),
# so CI needs no accelerator; the TPU-hardware path is covered separately
# by tests/test_backend_parity.py, which skips cleanly when absent.
set -euo pipefail
cd "$(dirname "$0")"

tier="${1:-fast}"
case "$tier" in
  smoke)
    python -m pytest tests/test_config.py tests/test_events.py tests/test_rng.py tests/test_ckpt_obs.py tests/test_telemetry.py tests/test_tune.py tests/test_digest.py -q -m "not slow" -k "not tgen"
    echo "== paritytrace bisect smoke (rung-1, injected corruption) =="
    # CPU platform like the pytest tiers (conftest forces it there; the
    # tool inherits the env) — the smoke must not depend on an accelerator.
    out=$(JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.paritytrace \
          configs/rung1_filexfer.yaml tpu cpu \
          --windows 16 --chunk 8 --inject 8:rng --no-localize 2>/dev/null) && rc=0 || rc=$?
    [ "$rc" -eq 3 ] || { echo "paritytrace: expected divergence exit 3, got $rc" >&2; exit 1; }
    echo "$out" | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])["first_divergence"]
assert d == {"window": 8, "subsystems": ["rng"]}, d
print("paritytrace localized the injected corruption to", d)
'
    ;;
  fast)  exec python -m pytest tests/ -q -m "not slow" ;;
  all)   exec python -m pytest tests/ -q ;;
  *) echo "usage: $0 [smoke|fast|all]" >&2; exit 2 ;;
esac
