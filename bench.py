"""North-star benchmark: batched-engine event throughput vs the CPU oracle.

Runs the PHOLD engine-stress workload (SURVEY §4 — the reference's scheduler
benchmark, src/test/phold/) on the batched TPU engine and on the sequential
CPU reference engine, and prints ONE JSON line:

    {"metric": "phold_events_per_sec", "value": N, "unit": "events/s",
     "vs_baseline": tpu_events_per_sec / cpu_engine_events_per_sec, ...}

Robustness contract (round-1 postmortem): this script ALWAYS prints exactly
one JSON line on stdout. The accelerator backend is probed in a subprocess
with a deadline (shadow1_tpu.platform); if it is down or hangs, the batched
engine runs on the forced-CPU platform and the ``backend`` field labels that
honestly. Any unexpected failure still emits a JSON line with an ``error``
detail instead of a stack trace.

The CPU comparator is this repo's own reference engine (BASELINE.md: no
external numbers exist in-environment).
"""

from __future__ import annotations

import json
import time


def run_bench() -> dict:
    import jax

    from shadow1_tpu.config.compiled import single_vertex_experiment
    from shadow1_tpu.consts import MS, SEC, EngineParams
    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.cpu_engine import CpuEngine

    n_hosts = 4096
    mean_delay = 2 * MS
    window = 1 * MS
    sim_seconds = 2
    exp = single_vertex_experiment(
        n_hosts=n_hosts,
        seed=1234,
        end_time=sim_seconds * SEC,
        latency_ns=window,
        model="phold",
        model_cfg={"mean_delay_ns": float(mean_delay), "init_events": 2},
    )
    params = EngineParams(ev_cap=32, outbox_cap=32, max_rounds=64)

    eng = Engine(exp, params)
    # Warm-up at the FULL window count: n_windows is a jit static arg, so the
    # timed call below must reuse this exact compiled program.
    t0 = time.perf_counter()
    st = eng.run()
    jax.block_until_ready(st)
    compile_wall = time.perf_counter() - t0
    t0 = time.perf_counter()
    st = eng.run()
    jax.block_until_ready(st)
    tpu_wall = time.perf_counter() - t0
    m = Engine.metrics_dict(st)
    tpu_eps = m["events"] / tpu_wall

    # CPU oracle on a slice of the sim (it is >10x slower; extrapolating
    # events/sec from 10% of the windows is fair — PHOLD is stationary).
    cpu = CpuEngine(exp, params)
    cpu_windows = max(1, eng.n_windows // 10)
    t0 = time.perf_counter()
    cm = cpu.run(n_windows=cpu_windows)
    cpu_wall = time.perf_counter() - t0
    cpu_eps = cm["events"] / cpu_wall

    sim_per_wall = (eng.n_windows * exp.window / SEC) / tpu_wall
    return {
        "metric": "phold_events_per_sec",
        "value": round(tpu_eps, 1),
        "unit": "events/s",
        "vs_baseline": round(tpu_eps / cpu_eps, 3),
        "detail": {
            "n_hosts": n_hosts,
            "events": m["events"],
            "tpu_wall_s": round(tpu_wall, 3),
            "compile_plus_first_run_s": round(compile_wall, 3),
            "sim_sec_per_wall_sec": round(sim_per_wall, 3),
            "cpu_engine_events_per_sec": round(cpu_eps, 1),
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "ev_overflow": m["ev_overflow"],
            "ob_overflow": m["ob_overflow"],
        },
    }


def main() -> None:
    result = None
    try:
        import shadow1_tpu  # noqa: F401  (x64 on, before jax arrays exist)
        from shadow1_tpu.platform import ensure_live_platform, probe_default_backend

        ensure_live_platform(min_devices=1)
        probe = probe_default_backend()
        result = run_bench()
        if probe.get("error"):
            result["detail"]["backend_probe_error"] = probe["error"]
    except Exception as e:  # noqa: BLE001 — the JSON line must always print
        import traceback

        result = {
            "metric": "phold_events_per_sec",
            "value": None,
            "unit": "events/s",
            "vs_baseline": None,
            "error": repr(e),
            "detail": {"traceback": traceback.format_exc()[-2000:]},
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
