// Thread-per-core NET-model comparator — the honest CPU baseline for the
// ladder's network rungs (filexfer / tgen / tor / bitcoin over virtual TCP).
//
// The round-3 comparator covered PHOLD only, so the flagship 20x-vs-CPU
// claim had no denominator on any net rung (VERDICT r3 missing #3). This
// program is the same thread-per-core scheduler shape (reference:
// src/main/core/scheduler/scheduler-policy-host-steal.c — hosts partitioned
// across workers, conservative windows, barrier rounds, locked cross-thread
// packet push) carrying a full mirror of the framework's virtual TCP stack
// and model applications.
//
// Exact-parity contract: identical semantics to shadow1_tpu/cpu_engine/
// (the Python oracle) and therefore to the batched TPU engine — same
// splitmix64 counter RNG (Q32 log2 table loaded from the Python dump),
// same (time, tb) event order, same TCP state machine (Go-Back-N, Reno,
// RFC6298 integer RTT), same capacity gates. tests/test_native_comparator.py
// asserts counter equality, which is what makes this wall clock an honest
// baseline. Fidelity knobs NOT implemented (stop/cpu/qlen/aqm): the Python
// wrapper refuses configs that use them rather than diverging silently.
//
// Usage: net_comparator <table_file> <config_blob> <n_threads>
// Prints one JSON line with counters and wall seconds.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- RNG ----
// Mirrors shadow1_tpu/rng.py exactly (integer pipeline).
constexpr uint64_t C1 = 0xBF58476D1CE4E5B9ull;
constexpr uint64_t C2 = 0x94D049BB133111EBull;
constexpr uint64_t P1 = 0x9E3779B97F4A7C15ull;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4Full;
constexpr uint64_t P3 = 0x165667B19E3779F9ull;
constexpr int LOG_BITS = 12;

uint64_t LOG_TBL[(1 << LOG_BITS) + 1];
uint64_t LN2_Q32 = 0;

inline uint64_t mix(uint64_t z) {
  z ^= z >> 30; z *= C1; z ^= z >> 27; z *= C2; z ^= z >> 31; return z;
}
inline uint64_t base_key(uint64_t seed) { return seed * P1 + C2; }
inline uint32_t rng_bits(uint64_t key, uint64_t purpose, uint64_t host,
                         uint64_t ctr) {
  uint64_t z = key + purpose * P1 + host * P2 + ctr * P3;
  return static_cast<uint32_t>(mix(mix(z)) >> 32);
}
inline uint64_t neg_log1m_q32(uint32_t b) {
  uint64_t x = (1ull << 32) - static_cast<uint64_t>(b);
  int k = 63 - __builtin_clzll(x);
  uint64_t m = x << (63 - k);
  uint64_t frac = (m << 1) >> 1;
  uint64_t idx = frac >> (63 - LOG_BITS);
  uint64_t rem = (frac >> (63 - LOG_BITS - 24)) & ((1ull << 24) - 1);
  uint64_t lo = LOG_TBL[idx], hi = LOG_TBL[idx + 1];
  uint64_t log2_frac = lo + (((hi - lo) * rem) >> 24);
  uint64_t log2_x = (static_cast<uint64_t>(k) << 32) + log2_frac;
  uint64_t e2 = (32ull << 32) - log2_x;
  return (e2 * (LN2_Q32 >> 5)) >> 27;
}
// mean_ns must be PRE-ROUNDED by the Python side (np.round is half-even;
// no C++ rounding happens here so no libm/rounding drift can enter).
inline int64_t exponential_ns(uint32_t b, uint64_t mean_ns) {
  uint64_t e = neg_log1m_q32(b);
  if (mean_ns > (1ull << 38)) mean_ns = 1ull << 38;
  uint64_t d = mean_ns * (e >> 32) + ((mean_ns * ((e & 0xFFFFFFFFull) >> 7)) >> 25);
  return d < 1 ? 1 : static_cast<int64_t>(d);
}
inline int32_t randint(uint32_t b, uint64_t n) {
  return static_cast<int32_t>((static_cast<uint64_t>(b) * n) >> 32);
}

// ------------------------------------------------------- shared consts ----
// Mirrors shadow1_tpu/consts.py.
constexpr int K_PKT = 2, K_PKT_DELIVER = 3, K_TCP_TIMER = 4, K_TX_RESUME = 5,
              K_APP = 6;
constexpr int F_SYN = 1, F_ACK = 2, F_FIN = 4, F_DGRAM = 16;
constexpr int N_ESTABLISHED = 1, N_ACCEPTED = 2, N_MSG = 4, N_SPACE = 8,
              N_PEER_FIN = 16, N_CLOSED = 32, N_DGRAM = 64, N_DATA = 128;
constexpr int TCP_FREE = 0, TCP_LISTEN = 1, TCP_SYN_SENT = 2,
              TCP_SYN_RCVD = 3, TCP_ESTABLISHED = 4, TCP_FIN_WAIT_1 = 5,
              TCP_FIN_WAIT_2 = 6, TCP_CLOSE_WAIT = 7, TCP_LAST_ACK = 8,
              TCP_CLOSING = 9;
constexpr int64_t SSTHRESH_INIT = 1ll << 28, CWND_MAX = 1ll << 28;
constexpr int WIRE_OVERHEAD = 40;
constexpr int64_t TB_PACKET_BASE = 1ll << 62;
constexpr uint64_t R_LOSS = 3, R_APP = 4, R_TOR_PATH = 5, R_BTC = 6,
                   R_JITTER = 7;
constexpr int64_t SEC = 1000000000ll;

inline bool sendable(int st) {
  return st == TCP_SYN_SENT || st == TCP_SYN_RCVD || st == TCP_ESTABLISHED ||
         st == TCP_CLOSE_WAIT || st == TCP_FIN_WAIT_1 || st == TCP_LAST_ACK ||
         st == TCP_CLOSING;
}
inline bool conn_state(int st) {
  return st >= TCP_SYN_SENT && st <= TCP_CLOSING;  // SYN_SENT..CLOSING
}
inline bool rcv_state(int st) {
  return st == TCP_ESTABLISHED || st == TCP_FIN_WAIT_1 || st == TCP_FIN_WAIT_2;
}

// u32 wrapping sequence arithmetic (consts.py seq_*).
inline uint32_t seq_add(uint32_t a, int64_t n) {
  return static_cast<uint32_t>(a + static_cast<uint32_t>(n));
}
inline int32_t seq_sub(uint32_t a, uint32_t b) {
  return static_cast<int32_t>(a - b);
}
inline bool seq_lt(uint32_t a, uint32_t b) { return seq_sub(a, b) < 0; }
inline bool seq_le(uint32_t a, uint32_t b) { return seq_sub(a, b) <= 0; }

inline int64_t ser_delay(int64_t wire_bytes, int64_t bw_bits) {
  return (wire_bytes * 8 * SEC + bw_bits - 1) / bw_bits;
}

// ------------------------------------------------------------- config ----
struct Config {
  int64_t n_hosts, seed, window_ns, n_windows;
  int64_t ev_cap, outbox_cap, sockets_per_host, msgq_cap, send_burst;
  int64_t mss, init_cwnd_mss, sndbuf, rcvbuf, rto_min, rto_max, rto_init,
      dupack_thresh;
  int64_t V, has_jitter, app_id;
  std::vector<int64_t> lat_vv, jit_vv;
  std::vector<uint64_t> loss_thr;
  std::vector<int64_t> host_vertex, bw_up, bw_dn;
  // app arrays (meaning depends on app_id; all length n_hosts unless noted)
  std::vector<int64_t> a0, a1, a2, a3, a4;   // generic per-host columns
  std::vector<uint64_t> m0, m1;              // pre-rounded means
  int64_t s0 = 0, s1 = 0, s2 = 0, s3 = 0, s4 = 0;  // scalars
  // tor tables / bitcoin peers
  std::vector<int64_t> t_ids0, t_ids1, t_ids2, t_ids3;  // guard/exit/relay/dir
  std::vector<int64_t> t_cum0, t_cum1, t_cum2;
  std::vector<int64_t> peers;  // bitcoin [H*K] host-major
};

bool read_config(const char* path, Config* c) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  auto rd = [&](void* p, size_t n) { return std::fread(p, 1, n, f) == n; };
  auto rd_i64 = [&](int64_t* p) { return rd(p, 8); };
  auto rd_vec = [&](std::vector<int64_t>* v) {
    int64_t n;
    if (!rd_i64(&n)) return false;
    v->resize(n);
    return n == 0 || rd(v->data(), n * 8);
  };
  auto rd_uvec = [&](std::vector<uint64_t>* v) {
    int64_t n;
    if (!rd_i64(&n)) return false;
    v->resize(n);
    return n == 0 || rd(v->data(), n * 8);
  };
  uint64_t magic;
  bool ok = rd(&magic, 8) && magic == 0x53484457434D5032ull;
  int64_t* hdr[] = {&c->n_hosts, &c->seed, &c->window_ns, &c->n_windows,
                    &c->ev_cap, &c->outbox_cap, &c->sockets_per_host,
                    &c->msgq_cap, &c->send_burst, &c->mss, &c->init_cwnd_mss,
                    &c->sndbuf, &c->rcvbuf, &c->rto_min, &c->rto_max,
                    &c->rto_init, &c->dupack_thresh, &c->V, &c->has_jitter,
                    &c->app_id};
  for (auto* p : hdr) ok = ok && rd_i64(p);
  ok = ok && rd_vec(&c->lat_vv) && rd_vec(&c->jit_vv) &&
       rd_uvec(&c->loss_thr) && rd_vec(&c->host_vertex) &&
       rd_vec(&c->bw_up) && rd_vec(&c->bw_dn);
  ok = ok && rd_vec(&c->a0) && rd_vec(&c->a1) && rd_vec(&c->a2) &&
       rd_vec(&c->a3) && rd_vec(&c->a4) && rd_uvec(&c->m0) && rd_uvec(&c->m1);
  for (auto* p : {&c->s0, &c->s1, &c->s2, &c->s3, &c->s4}) ok = ok && rd_i64(p);
  ok = ok && rd_vec(&c->t_ids0) && rd_vec(&c->t_cum0) && rd_vec(&c->t_ids1) &&
       rd_vec(&c->t_cum1) && rd_vec(&c->t_ids2) && rd_vec(&c->t_cum2) &&
       rd_vec(&c->t_ids3) && rd_vec(&c->peers);
  std::fclose(f);
  return ok;
}

// -------------------------------------------------------------- engine ----
struct Ev {
  int64_t time, tb;
  int32_t host, kind;
  int32_t p[10];
  bool operator>(const Ev& o) const {
    if (time != o.time) return time > o.time;
    if (tb != o.tb) return tb > o.tb;
    return host > o.host;  // cross-host ties are order-independent
  }
};

struct Metrics {
  int64_t events = 0, pkts_sent = 0, pkts_delivered = 0, pkts_lost = 0;
  int64_t ev_overflow = 0, ob_overflow = 0;
  int64_t tcp_fast_rtx = 0, tcp_rto = 0, tcp_ooo_drops = 0;
  int64_t pops_deliver = 0, pops_timer = 0, pops_txr = 0, pops_app = 0;
};

struct Sock {
  int32_t st = TCP_FREE, peer_host = 0, peer_sock = 0;
  uint32_t snd_una = 0, snd_nxt = 0, snd_max = 0, rcv_nxt = 0, app_end = 0;
  int32_t fin_pend = 0;
  int64_t cwnd = 0, ssthresh = 0, peer_wnd = 0;
  int32_t dupacks = 0;
  uint32_t recover = 0, ts_seq = 0;
  int64_t srtt = 0, rttvar = 0, rto = 0, rtx_t = 0, ts_time = 0;
  bool timer_armed = false, ts_act = false;
  int32_t txr = 0;
  std::vector<std::pair<uint32_t, int32_t>> mq;  // (end_seq, meta)
};

struct Shard {
  std::priority_queue<Ev, std::vector<Ev>, std::greater<Ev>> heap;
  std::vector<Ev> mailbox;
  std::mutex mbox_mu;
  Metrics m;
  char pad[64];
};

struct Engine;

// App interface.
struct App {
  virtual ~App() = default;
  virtual void start(Engine& e) = 0;
  virtual void on_wakeup(Engine& e, int h, int64_t now, const int32_t* p) = 0;
  virtual void on_notify(Engine& e, int h, int sock, int flags, int32_t meta,
                         int32_t meta2, int32_t dlen, int64_t now) = 0;
  virtual void summary(char* buf, size_t n) = 0;
};

struct Engine {
  const Config& c;
  uint64_t key;
  int n_threads;
  std::vector<Shard> shards;
  // Per-host state (each host touched by exactly one thread).
  std::vector<int64_t> self_ctr, pkt_ctr, pending, ob_used, ob_win;
  std::vector<int64_t> tx_free, rx_free, tx_bytes, rx_bytes;
  std::vector<Sock> socks;  // [h * S + s]
  App* app = nullptr;

  explicit Engine(const Config& cfg, int nt)
      : c(cfg), key(base_key(cfg.seed)), n_threads(nt), shards(nt),
        self_ctr(cfg.n_hosts, 0), pkt_ctr(cfg.n_hosts, 0),
        pending(cfg.n_hosts, 0), ob_used(cfg.n_hosts, 0),
        ob_win(cfg.n_hosts, -1), tx_free(cfg.n_hosts, 0),
        rx_free(cfg.n_hosts, 0), tx_bytes(cfg.n_hosts, 0),
        rx_bytes(cfg.n_hosts, 0),
        socks(cfg.n_hosts * cfg.sockets_per_host) {}

  int owner(int64_t h) const {
    return static_cast<int>(h * n_threads / c.n_hosts);
  }
  Sock& sk(int h, int s) { return socks[h * c.sockets_per_host + s]; }
  Shard& shard_of(int h) { return shards[owner(h)]; }

  void schedule_local(int h, int64_t time, int kind, const int32_t* p,
                      int np_) {
    Shard& s = shard_of(h);
    if (pending[h] >= c.ev_cap) { s.m.ev_overflow++; return; }
    pending[h]++;
    Ev ev{time, self_ctr[h]++, h, kind, {0}};
    for (int i = 0; i < np_; ++i) ev.p[i] = p[i];
    s.heap.push(ev);
  }
  void schedule_local1(int h, int64_t t, int kind, int32_t p0) {
    int32_t p[1] = {p0};
    schedule_local(h, t, kind, p, 1);
  }

  int64_t outbox_space(int h, int64_t now) {
    int64_t w = now / c.window_ns;
    if (ob_win[h] != w) { ob_win[h] = w; ob_used[h] = 0; }
    return c.outbox_cap - ob_used[h];
  }

  // Route one packet (mirror of CpuEngine.send; no stop/cpu fidelity).
  void send(int src, int dst, int64_t depart, const int32_t* p, int64_t now) {
    Shard& me = shard_of(src);
    if (outbox_space(src, now) <= 0) { me.m.ob_overflow++; return; }
    ob_used[src]++;
    int64_t ctr = pkt_ctr[src]++;
    me.m.pkts_sent++;
    int64_t vs = c.host_vertex[src], vd = c.host_vertex[dst];
    uint64_t thr = c.loss_thr[vs * c.V + vd];
    if (static_cast<uint64_t>(rng_bits(key, R_LOSS, src, ctr)) < thr) {
      me.m.pkts_lost++;
      return;
    }
    int64_t arrival = depart + c.lat_vv[vs * c.V + vd];
    if (c.has_jitter) {
      int64_t jit = c.jit_vv[vs * c.V + vd];
      if (jit)
        arrival += randint(rng_bits(key, R_JITTER, src, ctr), 2 * jit + 1) - jit;
    }
    Ev ev{arrival, TB_PACKET_BASE + (static_cast<int64_t>(src) << 32) +
                       (ctr & 0xFFFFFFFFll),
          dst, K_PKT, {0}};
    for (int i = 0; i < 10; ++i) ev.p[i] = p[i];
    Shard& ds = shard_of(dst);
    if (&ds == &me) {
      if (pending[dst] >= c.ev_cap) { me.m.ev_overflow++; return; }
      pending[dst]++;
      me.m.pkts_delivered++;
      me.heap.push(ev);
    } else {
      std::lock_guard<std::mutex> g(ds.mbox_mu);
      ds.mailbox.push_back(ev);
    }
  }

  // ---- NIC + emission (mirror of CpuNetModel) ----
  void rx_convert(int h, int64_t time, int64_t tb, const int32_t* p) {
    // pop freed a slot; capacity cannot overflow (schedule_packet contract)
    int64_t wire = p[4] + WIRE_OVERHEAD;
    int64_t ready = time > rx_free[h] ? time : rx_free[h];
    rx_free[h] = ready + ser_delay(wire, c.bw_dn[h]);
    rx_bytes[h] += wire;
    pending[h]++;
    Ev ev{ready, tb, h, K_PKT_DELIVER, {0}};
    for (int i = 0; i < 10; ++i) ev.p[i] = p[i];
    shard_of(h).heap.push(ev);
  }

  int64_t tx_reserve(int h, int64_t wire, int64_t now) {
    // No aqm / drop-tail fidelity (wrapper refuses such configs).
    int64_t depart = now > tx_free[h] ? now : tx_free[h];
    tx_free[h] = depart + ser_delay(wire, c.bw_up[h]);
    tx_bytes[h] += wire;
    return depart;
  }

  void emit(int h, int s, int flags, uint32_t seq, int32_t length,
            int32_t mend, int32_t mmeta, int64_t now) {
    Sock& k = sk(h, s);
    int32_t p[10] = {h,
                     s | (k.peer_sock << 8) | (flags << 16),
                     static_cast<int32_t>(seq),
                     static_cast<int32_t>(k.rcv_nxt),
                     length,
                     static_cast<int32_t>(c.rcvbuf),
                     mend,
                     mmeta,
                     0,
                     0};
    int64_t depart = tx_reserve(h, length + WIRE_OVERHEAD, now);
    send(h, k.peer_host, depart, p, now);
  }

  void udp_send(int h, int dst_host, int dst_sock, int32_t length,
                int32_t meta, int32_t meta2, int64_t now) {
    int32_t p[10] = {h, (dst_sock << 8) | (F_DGRAM << 16), 0, 0, length,
                     0, 0, meta, meta2, 0};
    int64_t depart = tx_reserve(h, length + WIRE_OVERHEAD, now);
    send(h, dst_host, depart, p, now);
  }

  // ---- TCP sender (mirror of CpuNetModel.flush / ack_now) ----
  void flush(int h, int s, int64_t now) {
    Sock& k = sk(h, s);
    for (int64_t i = 0; i < c.send_burst; ++i) {
      uint32_t total_end = seq_add(k.app_end, k.fin_pend);
      bool pend = seq_lt(k.snd_nxt, total_end);
      int64_t flight = seq_sub(k.snd_nxt, k.snd_una);
      int64_t limit = k.cwnd < k.peer_wnd ? k.cwnd : k.peer_wnd;
      if (!(sendable(k.st) && pend && flight < limit &&
            outbox_space(h, now) > 0))
        break;
      int flags;
      int32_t length;
      bool seg_syn = false, seg_fin = false;
      if (k.snd_nxt == 0) {
        flags = k.st == TCP_SYN_RCVD ? (F_SYN | F_ACK) : F_SYN;
        length = 0;
        seg_syn = true;
      } else if (k.snd_nxt == k.app_end && k.fin_pend) {
        flags = F_FIN | F_ACK;
        length = 0;
        seg_fin = true;
      } else {
        flags = F_ACK;
        int64_t l = c.mss;
        int64_t rem = seq_sub(k.app_end, k.snd_nxt);
        if (rem < l) l = rem;
        if (limit - flight < l) l = limit - flight;
        length = static_cast<int32_t>(l);
      }
      int32_t mend = 0, mmeta = 0;
      if (!seg_syn && !seg_fin) {
        uint32_t seg_hi = seq_add(k.snd_nxt, length);
        bool have = false;
        int32_t best_d = 0;
        for (const auto& em : k.mq) {
          if (seq_lt(k.snd_nxt, em.first) && seq_le(em.first, seg_hi)) {
            int32_t d = seq_sub(em.first, k.snd_nxt);
            if (!have || d < best_d) {
              have = true;
              best_d = d;
              mend = static_cast<int32_t>(em.first);
              mmeta = em.second;
            }
          }
        }
        if (have) length = best_d;
      }
      emit(h, s, flags, k.snd_nxt, length, mend, mmeta, now);
      k.snd_nxt = seq_add(k.snd_nxt, length + ((seg_syn || seg_fin) ? 1 : 0));
      if (seq_lt(k.snd_max, k.snd_nxt)) k.snd_max = k.snd_nxt;
      if (!k.ts_act) {
        k.ts_act = true;
        k.ts_seq = k.snd_nxt;
        k.ts_time = now;
      }
      if (k.rtx_t == 0) {
        k.rtx_t = now + k.rto;
        if (!k.timer_armed) {
          k.timer_armed = true;
          schedule_local1(h, now + k.rto, K_TCP_TIMER, s);
        }
      }
    }
    uint32_t total_end = seq_add(k.app_end, k.fin_pend);
    bool pend = seq_lt(k.snd_nxt, total_end);
    int64_t limit = k.cwnd < k.peer_wnd ? k.cwnd : k.peer_wnd;
    bool wnd_ok = seq_sub(k.snd_nxt, k.snd_una) < limit;
    bool blocked = outbox_space(h, now) <= 0;
    if (sendable(k.st) && pend && wnd_ok && !k.txr) {
      k.txr = 1;
      int64_t t_resume =
          blocked ? (now / c.window_ns + 1) * c.window_ns : now;
      schedule_local1(h, t_resume, K_TX_RESUME, s);
    }
  }

  void ack_now(int h, int s, int64_t now) {
    if (outbox_space(h, now) > 0) {
      Sock& k = sk(h, s);
      emit(h, s, F_ACK, k.snd_nxt, 0, 0, 0, now);
    }
  }

  // ---- App-facing TCP API ----
  void listen(int h, int s) { sk(h, s).st = TCP_LISTEN; }

  void init_conn(Sock& k, int peer_host, int peer_sock, int state,
                 uint32_t rcv_nxt) {
    k.st = state;
    k.peer_host = peer_host;
    k.peer_sock = peer_sock;
    k.snd_una = k.snd_nxt = k.snd_max = 0;
    k.rcv_nxt = rcv_nxt;
    k.app_end = 1;
    k.fin_pend = 0;
    k.cwnd = c.init_cwnd_mss * c.mss;
    k.ssthresh = SSTHRESH_INIT;
    k.peer_wnd = c.mss;
    k.srtt = k.rttvar = 0;
    k.rto = c.rto_init;
    k.rtx_t = 0;
    k.dupacks = 0;
    k.recover = 0;
    k.ts_act = false;
    k.txr = 0;
    k.mq.clear();
  }

  void connect(int h, int s, int dst_host, int dst_sock, int64_t now) {
    init_conn(sk(h, s), dst_host, dst_sock, TCP_SYN_SENT, 0);
    flush(h, s, now);
  }

  int64_t tcp_send(int h, int s, int64_t nbytes, int32_t meta, int64_t now) {
    Sock& k = sk(h, s);
    int64_t buffered = seq_sub(k.app_end, k.snd_una) - (k.snd_una == 0 ? 1 : 0);
    int64_t space = c.sndbuf - buffered;
    if (space < 0) space = 0;
    int64_t accepted = nbytes < space ? nbytes : space;
    if (accepted < 0) accepted = 0;
    if (accepted > 0) {
      k.app_end = seq_add(k.app_end, accepted);
      if (accepted == nbytes && meta != 0 &&
          static_cast<int64_t>(k.mq.size()) < c.msgq_cap)
        k.mq.emplace_back(k.app_end, meta);
      flush(h, s, now);
    }
    return accepted;
  }

  void close(int h, int s, int64_t now) {
    Sock& k = sk(h, s);
    if (k.st == TCP_ESTABLISHED) k.st = TCP_FIN_WAIT_1;
    else if (k.st == TCP_CLOSE_WAIT) k.st = TCP_LAST_ACK;
    else return;
    k.fin_pend = 1;
    flush(h, s, now);
  }

  // ---- TCP receive (mirror of CpuNetModel.tcp_rx, same sequencing) ----
  void tcp_rx(int h, const int32_t* p, int64_t now) {
    Metrics& m = shard_of(h).m;
    int src = p[0];
    int packed = p[1];
    uint32_t seq = static_cast<uint32_t>(p[2]);
    uint32_t ackno = static_cast<uint32_t>(p[3]);
    int32_t length = p[4];
    int64_t wnd = p[5];
    int32_t mend = p[6], mmeta = p[7];
    int ss = packed & 0xFF, ds = (packed >> 8) & 0xFF;
    int flags = (packed >> 16) & 0xFF;
    bool is_syn = flags & F_SYN, is_ack = flags & F_ACK, is_fin = flags & F_FIN;
    Sock& k = sk(h, ds);
    int notifs = 0;
    int32_t n_meta = 0, n_dlen = 0;

    if (is_syn && !is_ack && k.st == TCP_LISTEN) {
      bool dup = false;
      for (int i = 0; i < c.sockets_per_host; ++i) {
        Sock& ck = sk(h, i);
        if (ck.peer_host == src && ck.peer_sock == ss &&
            ck.st != TCP_FREE && ck.st != TCP_LISTEN) { dup = true; break; }
      }
      int child = -1;
      for (int i = static_cast<int>(c.sockets_per_host) - 1; i >= 0; --i)
        if (sk(h, i).st == TCP_FREE) { child = i; break; }
      if (!dup && child >= 0) {
        Sock& ck = sk(h, child);
        init_conn(ck, src, ss, TCP_SYN_RCVD, 1);
        ck.peer_wnd = wnd;
        flush(h, child, now);
      }
      return;
    }

    bool learn_peer = k.st == TCP_SYN_SENT && is_syn && is_ack;
    bool v = conn_state(k.st) && k.peer_host == src &&
             (k.peer_sock == ss || learn_peer);
    if (!v) return;
    if (learn_peer) k.peer_sock = ss;
    if (is_ack) k.peer_wnd = wnd > 1 ? wnd : 1;

    int state = k.st;
    uint32_t snd_una0 = k.snd_una, snd_nxt0 = k.snd_nxt;
    uint32_t snd_max0 = k.snd_max;
    // ACK acceptance tests against snd_max (highest ever sent), not the
    // possibly-rewound snd_nxt — mirror of tcp.py (outage deadlock fix).
    bool new_ack = is_ack && seq_lt(snd_una0, ackno) && seq_le(ackno, snd_max0);
    bool est_ss = is_ack && is_syn && state == TCP_SYN_SENT && ackno == 1;
    bool frx = false;
    bool closed_by_ack = false;
    if (new_ack) {
      if (k.ts_act && seq_le(k.ts_seq, ackno)) {
        int64_t rtt = now - k.ts_time;
        if (rtt < 1) rtt = 1;
        if (k.srtt == 0) { k.srtt = rtt; k.rttvar = rtt / 2; }
        else {
          int64_t err = rtt - k.srtt;
          k.srtt += err >> 3;
          int64_t ae = err < 0 ? -err : err;
          k.rttvar += (ae - k.rttvar) >> 2;
        }
        int64_t var4 = 4 * k.rttvar;
        if (var4 < 1000000) var4 = 1000000;
        int64_t rto = k.srtt + var4;
        if (rto < c.rto_min) rto = c.rto_min;
        if (rto > c.rto_max) rto = c.rto_max;
        k.rto = rto;
        k.ts_act = false;
      }
      int64_t grow = k.cwnd < k.ssthresh
                         ? c.mss
                         : std::max<int64_t>((c.mss * c.mss) /
                                                 std::max<int64_t>(k.cwnd, 1),
                                             1);
      k.cwnd = std::min<int64_t>(k.cwnd + grow, CWND_MAX);
      k.snd_una = ackno;
      if (seq_lt(k.snd_nxt, ackno)) k.snd_nxt = ackno;
      k.dupacks = 0;
      {
        size_t w = 0;
        for (size_t i = 0; i < k.mq.size(); ++i)
          if (seq_lt(ackno, k.mq[i].first)) k.mq[w++] = k.mq[i];
        k.mq.resize(w);
      }
      bool outstanding = seq_lt(ackno, snd_max0);
      k.rtx_t = outstanding ? now + k.rto : 0;
      if (state == TCP_SYN_RCVD) { k.st = TCP_ESTABLISHED; notifs |= N_ACCEPTED; }
    }
    if (est_ss) { k.st = TCP_ESTABLISHED; k.rcv_nxt = 1; notifs |= N_ESTABLISHED; }
    if (new_ack) {
      uint32_t total_end = seq_add(k.app_end, k.fin_pend);
      bool fin_acked = k.fin_pend == 1 && ackno == total_end;
      if (fin_acked && state == TCP_FIN_WAIT_1) k.st = TCP_FIN_WAIT_2;
      if (fin_acked && (state == TCP_CLOSING || state == TCP_LAST_ACK)) {
        closed_by_ack = true;
        notifs |= N_CLOSED;
      }
      if ((state == TCP_ESTABLISHED || state == TCP_CLOSE_WAIT) &&
          !closed_by_ack)
        notifs |= N_SPACE;
    }
    bool dup_a = is_ack && !new_ack && ackno == snd_una0 &&
                 seq_lt(ackno, snd_max0) && length == 0 && !is_syn && !is_fin;
    if (dup_a) {
      k.dupacks++;
      if (k.dupacks == c.dupack_thresh && seq_le(k.recover, snd_una0)) {
        frx = true;
        int64_t flight = seq_sub(snd_nxt0, snd_una0);
        k.ssthresh = std::max<int64_t>(flight / 2, 2 * c.mss);
        k.cwnd = k.ssthresh;
        k.recover = snd_nxt0;
        k.snd_nxt = snd_una0;
        k.ts_act = false;
        m.tcp_fast_rtx++;
      }
    }
    if (new_ack || frx) flush(h, ds, now);

    int state2 = k.st;
    bool can_rcv = rcv_state(state2);
    bool has_data = can_rcv && length > 0;
    bool in_order = has_data && seq == k.rcv_nxt;
    if (in_order) {
      k.rcv_nxt = seq_add(k.rcv_nxt, length);
      notifs |= N_DATA;
      n_dlen = length;
      if (mend != 0) { notifs |= N_MSG; n_meta = mmeta; }
    } else if (has_data) {
      m.tcp_ooo_drops++;
    }
    bool fin_here = is_fin && seq_add(seq, length) == k.rcv_nxt &&
                    (state2 == TCP_ESTABLISHED || state2 == TCP_FIN_WAIT_1 ||
                     state2 == TCP_FIN_WAIT_2);
    bool closed_by_fin = false;
    if (fin_here) {
      k.rcv_nxt = seq_add(k.rcv_nxt, 1);
      if (state2 == TCP_ESTABLISHED) { k.st = TCP_CLOSE_WAIT; notifs |= N_PEER_FIN; }
      else if (state2 == TCP_FIN_WAIT_1) k.st = TCP_CLOSING;
      else if (state2 == TCP_FIN_WAIT_2) { closed_by_fin = true; notifs |= N_CLOSED; }
    }
    if (closed_by_ack || closed_by_fin) { k.st = TCP_FREE; k.rtx_t = 0; }
    if (has_data || is_fin || est_ss) ack_now(h, ds, now);
    if (notifs) app->on_notify(*this, h, ds, notifs, n_meta, 0, n_dlen, now);
  }

  void tcp_timer(int h, int s, int64_t now) {
    Sock& k = sk(h, s);
    k.timer_armed = false;
    if (k.rtx_t == 0) return;
    if (now < k.rtx_t) {
      k.timer_armed = true;
      schedule_local1(h, k.rtx_t, K_TCP_TIMER, s);
      return;
    }
    bool outstanding = seq_lt(k.snd_una, k.snd_max);
    if (outstanding && sendable(k.st)) {
      int64_t flight = seq_sub(k.snd_nxt, k.snd_una);
      k.ssthresh = std::max<int64_t>(flight / 2, 2 * c.mss);
      k.cwnd = c.mss;
      k.rto = std::min<int64_t>(k.rto * 2, c.rto_max);
      k.snd_nxt = k.snd_una;
      k.ts_act = false;
      k.dupacks = 0;
      k.recover = k.snd_una;
      k.rtx_t = now + k.rto;
      k.timer_armed = true;
      shard_of(h).m.tcp_rto++;
      schedule_local1(h, k.rtx_t, K_TCP_TIMER, s);
      flush(h, s, now);
    } else {
      k.rtx_t = 0;
    }
  }

  void handle(int h, int64_t time, int kind, const int32_t* p) {
    Metrics& m = shard_of(h).m;
    if (kind == K_PKT_DELIVER) {
      m.pops_deliver++;
      int flags = (p[1] >> 16) & 0xFF;
      if (flags & F_DGRAM)
        app->on_notify(*this, h, (p[1] >> 8) & 0xFF, N_DGRAM, p[7], p[8],
                       p[4], time);
      else
        tcp_rx(h, p, time);
    } else if (kind == K_TCP_TIMER) {
      m.pops_timer++;
      tcp_timer(h, p[0], time);
    } else if (kind == K_TX_RESUME) {
      m.pops_txr++;
      sk(h, p[0]).txr = 0;
      flush(h, p[0], time);
    } else if (kind == K_APP) {
      m.pops_app++;
      app->on_wakeup(*this, h, time, p);
    }
  }
};

// ---------------------------------------------------------------- apps ----
// filexfer: a0=role a1=server a2=flow_bytes a3=start_time a4=flow_count
struct Filexfer : App {
  std::vector<int64_t> remaining, flows_left;
  std::vector<char> closed_sent;
  std::vector<int64_t> rx_bytes_, flows_done, done_time;
  static constexpr int FLOW_DONE = 1, OP_START = 1;

  void start(Engine& e) override {
    int64_t n = e.c.n_hosts;
    remaining.assign(n, 0);
    flows_left.assign(e.c.a4.begin(), e.c.a4.end());
    closed_sent.assign(n, 0);
    rx_bytes_.assign(n, 0);
    flows_done.assign(n, 0);
    done_time.assign(n, 0);
    for (int64_t h = 0; h < n; ++h) {
      if (e.c.a0[h] == 0) e.listen(h, 0);
      else if (e.c.a0[h] == 1)
        e.schedule_local1(h, e.c.a3[h], K_APP, OP_START);
    }
  }
  void client_start(Engine& e, int h, int64_t now) {
    remaining[h] = e.c.a2[h];
    closed_sent[h] = 0;
    e.connect(h, 0, static_cast<int>(e.c.a1[h]), 0, now);
  }
  void client_pump(Engine& e, int h, int64_t now) {
    if (remaining[h] > 0)
      remaining[h] -= e.tcp_send(h, 0, remaining[h], FLOW_DONE, now);
    if (remaining[h] == 0 && !closed_sent[h]) {
      closed_sent[h] = 1;
      e.close(h, 0, now);
    }
  }
  void on_wakeup(Engine& e, int h, int64_t now, const int32_t* p) override {
    if (p[0] == OP_START) client_start(e, h, now);
  }
  void on_notify(Engine& e, int h, int sock, int flags, int32_t meta,
                 int32_t, int32_t dlen, int64_t now) override {
    if (e.c.a0[h] == 1 && (flags & (N_ESTABLISHED | N_SPACE)))
      client_pump(e, h, now);
    if (e.c.a0[h] == 0) {
      if (flags & N_DATA) rx_bytes_[h] += dlen;
      if ((flags & N_MSG) && meta == FLOW_DONE) flows_done[h]++;
      if (flags & N_PEER_FIN) e.close(h, sock, now);
    }
    if (e.c.a0[h] == 1 && (flags & N_CLOSED)) {
      if (--flows_left[h] > 0) client_start(e, h, now);
      else done_time[h] = now;
    }
  }
  void summary(char* buf, size_t n) override {
    int64_t fd = 0, rb = 0;
    for (auto v : flows_done) fd += v;
    for (auto v : rx_bytes_) rb += v;
    std::snprintf(buf, n, "\"total_flows_done\": %lld, \"total_rx_bytes\": %lld",
                  (long long)fd, (long long)rb);
  }
};

// tgen: a0=active a1=streams a3=start_time m0=mean_bytes m1=mean_think
//       s0=fixed_size s1=fixed_bytes (trunc(mean), >=1)
struct Tgen : App {
  static constexpr int STREAM_DONE = 1, OP_START = 1;
  static constexpr int64_t SIZE_MAX_ = 1ll << 30;
  std::vector<int64_t> streams_left, remaining, ctr;
  std::vector<char> closed_sent;
  std::vector<int64_t> rx_bytes_, streams_served, streams_done, done_time;

  void start(Engine& e) override {
    int64_t n = e.c.n_hosts;
    streams_left.assign(e.c.a1.begin(), e.c.a1.end());
    remaining.assign(n, 0);
    ctr.assign(n, 0);
    closed_sent.assign(n, 0);
    rx_bytes_.assign(n, 0);
    streams_served.assign(n, 0);
    streams_done.assign(n, 0);
    done_time.assign(n, 0);
    for (int64_t h = 0; h < n; ++h) {
      e.listen(h, 0);
      if (e.c.a0[h] == 1 && streams_left[h] > 0)
        e.schedule_local1(h, e.c.a3[h], K_APP, OP_START);
    }
  }
  void start_stream(Engine& e, int h, int64_t now) {
    int64_t cc = ctr[h];
    int32_t raw = randint(rng_bits(e.key, R_APP, h, 3 * cc + 0),
                          e.c.n_hosts - 1);
    int dst = raw + (raw >= h ? 1 : 0);
    int64_t size;
    if (e.c.s0) {
      size = e.c.a4[h];  // fixed_size: pre-truncated max(int(mean), 1)
    } else {
      size = exponential_ns(rng_bits(e.key, R_APP, h, 3 * cc + 1), e.c.m0[h]);
      if (size < 1) size = 1;
      if (size > SIZE_MAX_) size = SIZE_MAX_;
    }
    remaining[h] = size;
    closed_sent[h] = 0;
    ctr[h]++;
    e.connect(h, 1, dst, 0, now);
  }
  void client_pump(Engine& e, int h, int64_t now) {
    if (remaining[h] > 0)
      remaining[h] -= e.tcp_send(h, 1, remaining[h], STREAM_DONE, now);
    if (remaining[h] == 0 && !closed_sent[h]) {
      closed_sent[h] = 1;
      e.close(h, 1, now);
    }
  }
  void on_wakeup(Engine& e, int h, int64_t now, const int32_t* p) override {
    if (p[0] == OP_START) start_stream(e, h, now);
  }
  void on_notify(Engine& e, int h, int sock, int flags, int32_t meta,
                 int32_t, int32_t dlen, int64_t now) override {
    if (sock == 1) {
      if (flags & (N_ESTABLISHED | N_SPACE)) client_pump(e, h, now);
      if (flags & N_CLOSED) {
        streams_left[h]--;
        streams_done[h]++;
        int64_t cc = ctr[h] - 1;
        if (streams_left[h] > 0) {
          int64_t think =
              exponential_ns(rng_bits(e.key, R_APP, h, 3 * cc + 2), e.c.m1[h]);
          e.schedule_local1(h, now + think, K_APP, OP_START);
        } else {
          done_time[h] = now;
        }
      }
    } else {
      if (flags & N_DATA) rx_bytes_[h] += dlen;
      if ((flags & N_MSG) && meta == STREAM_DONE) streams_served[h]++;
      if (flags & N_PEER_FIN) e.close(h, sock, now);
    }
  }
  void summary(char* buf, size_t n) override {
    int64_t sd = 0, rb = 0, sv = 0;
    for (auto v : streams_done) sd += v;
    for (auto v : rx_bytes_) rb += v;
    for (auto v : streams_served) sv += v;
    std::snprintf(buf, n,
                  "\"total_streams_done\": %lld, \"total_rx_bytes\": %lld, "
                  "\"total_streams_served\": %lld",
                  (long long)sd, (long long)rb, (long long)sv);
  }
};

// tor: a0=role a1=n_circuits a2=n_streams a3=start_time
//      m0=mean_cells m1=mean_think
//      s0=consensus_bytes s1=cells_max s2=ct_cap
//      t_ids0/cum0=guard t_ids1/cum1=exit t_ids2/cum2=relay t_ids3=dir
struct Tor : App {
  static constexpr int CELL = 512;
  static constexpr int C_CREATE = 1, C_CREATED = 2, C_EXTEND = 3,
                       C_EXTENDED = 4, C_BEGIN = 5, C_DATA = 6, C_END = 7,
                       C_DIRREQ = 8, C_DIRRESP = 9;
  static constexpr int OP_START = 1, OP_TX_CELL = 2, OP_CONNECT_RELAY = 3,
                       OP_DRAIN = 4, OP_THINK = 5;
  static constexpr int CL_DIR_CONN = 1, CL_DIR_FETCH = 2, CL_GUARD_CONN = 3,
                       CL_BUILDING = 4, CL_STREAM = 5, CL_DONE = 7;
  int64_t ct_cap = 0;
  std::vector<int32_t> cl_state, cl_guard, cl_circ, cl_hop, cl_mid, cl_exit,
      cl_circs_left, cl_streams_left, cl_cells_want;
  std::vector<int64_t> ctr, streams_done, cells_rx, bootstrap_time, done_time,
      cells_fwd, ct_overflow, cell_retries;
  // relay tables [h * cap + i]
  std::vector<int32_t> rc_peer, rc_next_circ;
  std::vector<char> ct_used, ct_pend;
  std::vector<int32_t> ct_in_sock, ct_in_circ, ct_out_sock, ct_out_circ;

  static int32_t meta_of(int64_t circ, int64_t aux, int cmd) {
    return static_cast<int32_t>((circ << 18) | (aux << 4) | cmd);
  }
  int64_t draw(int h) { return ctr[h]++; }
  int pick_weighted(Engine& e, int h, const std::vector<int64_t>& ids,
                    const std::vector<int64_t>& cum) {
    int32_t u = randint(rng_bits(e.key, R_TOR_PATH, h, draw(h)),
                        static_cast<uint64_t>(cum.back()));
    // searchsorted(cum, u, side="right"): first idx with cum[idx] > u
    size_t lo = 0, hi = cum.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (cum[mid] <= u) lo = mid + 1;
      else hi = mid;
    }
    size_t idx = lo < ids.size() ? lo : ids.size() - 1;
    return static_cast<int>(ids[idx]);
  }
  void push_cell(Engine& e, int h, int sock, int32_t meta, int32_t nbytes,
                 int64_t now) {
    int32_t p[4] = {OP_TX_CELL, sock, meta, nbytes};
    e.schedule_local(h, now, K_APP, p, 4);
  }
  void begin_circuit(Engine& e, int h, int64_t now) {
    cl_mid[h] = pick_weighted(e, h, e.c.t_ids2, e.c.t_cum2);
    cl_exit[h] = pick_weighted(e, h, e.c.t_ids1, e.c.t_cum1);
    cl_circ[h]++;
    cl_hop[h] = 1;
    cl_state[h] = CL_BUILDING;
    cl_streams_left[h] = static_cast<int32_t>(e.c.a2[h]);
    push_cell(e, h, 1, meta_of(cl_circ[h], 0, C_CREATE), CELL, now);
  }
  void begin_stream(Engine& e, int h, int64_t now) {
    int64_t want =
        exponential_ns(rng_bits(e.key, R_TOR_PATH, h, draw(h)), e.c.m0[h]);
    if (want < 1) want = 1;
    if (want > e.c.s1) want = e.c.s1;
    cl_cells_want[h] = static_cast<int32_t>(want);
    cl_state[h] = CL_STREAM;
    push_cell(e, h, 1, meta_of(cl_circ[h], want, C_BEGIN), CELL, now);
  }
  void think(Engine& e, int h, int64_t now) {
    int64_t t =
        exponential_ns(rng_bits(e.key, R_TOR_PATH, h, draw(h)), e.c.m1[h]);
    e.schedule_local1(h, now + t, K_APP, OP_THINK);
  }

  void start(Engine& e) override {
    int64_t n = e.c.n_hosts;
    int64_t s = e.c.sockets_per_host;
    ct_cap = e.c.s2;
    cl_state.assign(n, 0); cl_guard.assign(n, -1); cl_circ.assign(n, 0);
    cl_hop.assign(n, 0); cl_mid.assign(n, 0); cl_exit.assign(n, 0);
    cl_circs_left.assign(n, 0); cl_streams_left.assign(n, 0);
    cl_cells_want.assign(n, 0);
    for (int64_t h = 0; h < n; ++h)
      cl_circs_left[h] = static_cast<int32_t>(e.c.a1[h]);
    ctr.assign(n, 0); streams_done.assign(n, 0); cells_rx.assign(n, 0);
    bootstrap_time.assign(n, 0); done_time.assign(n, 0); cells_fwd.assign(n, 0);
    ct_overflow.assign(n, 0); cell_retries.assign(n, 0);
    rc_peer.assign(n * s, -1); rc_next_circ.assign(n * s, 1);
    ct_used.assign(n * ct_cap, 0); ct_pend.assign(n * ct_cap, 0);
    ct_in_sock.assign(n * ct_cap, 0); ct_in_circ.assign(n * ct_cap, 0);
    ct_out_sock.assign(n * ct_cap, -1); ct_out_circ.assign(n * ct_cap, 0);
    for (int64_t h = 0; h < n; ++h) {
      if (e.c.a0[h] == 0 || e.c.a0[h] == 2) e.listen(h, 0);
      if (e.c.a0[h] == 1 && cl_circs_left[h] > 0)
        e.schedule_local1(h, e.c.a3[h], K_APP, OP_START);
    }
  }
  void on_wakeup(Engine& e, int h, int64_t now, const int32_t* p) override {
    if (p[0] == OP_START) {
      int d_idx = randint(rng_bits(e.key, R_TOR_PATH, h, draw(h)),
                          e.c.t_ids3.size());
      cl_state[h] = CL_DIR_CONN;
      e.connect(h, 2, static_cast<int>(e.c.t_ids3[d_idx]), 0, now);
    } else if (p[0] == OP_TX_CELL) {
      int sock = p[1];
      int32_t meta = p[2], nbytes = p[3];
      Sock& k = e.sk(h, sock);
      int64_t buffered =
          seq_sub(k.app_end, k.snd_una) - (k.snd_una == 0 ? 1 : 0);
      bool fits = (e.c.sndbuf - buffered) >= nbytes;
      bool mq_ok = static_cast<int64_t>(k.mq.size()) < e.c.msgq_cap;
      if (fits && mq_ok) {
        e.tcp_send(h, sock, nbytes, meta, now);
      } else {
        cell_retries[h]++;
        int64_t t_retry = (now / e.c.window_ns + 1) * e.c.window_ns;
        int32_t pp[4] = {OP_TX_CELL, sock, meta, nbytes};
        e.schedule_local(h, t_retry, K_APP, pp, 4);
      }
    } else if (p[0] == OP_CONNECT_RELAY) {
      e.connect(h, p[1], p[2], 0, now);
    } else if (p[0] == OP_DRAIN) {
      int sock = p[1];
      int64_t base = static_cast<int64_t>(h) * ct_cap;
      int first = -1, count = 0;
      for (int64_t i = 0; i < ct_cap; ++i)
        if (ct_used[base + i] && ct_pend[base + i] &&
            ct_out_sock[base + i] == sock) {
          if (first < 0) first = static_cast<int>(i);
          count++;
        }
      if (first >= 0) {
        ct_pend[base + first] = 0;
        push_cell(e, h, sock, meta_of(ct_out_circ[base + first], 0, C_CREATE),
                  CELL, now);
        if (count > 1) {
          int32_t pp[2] = {OP_DRAIN, sock};
          e.schedule_local(h, now, K_APP, pp, 2);
        }
      }
    } else if (p[0] == OP_THINK) {
      if (cl_streams_left[h] > 0) begin_stream(e, h, now);
      else if (cl_circs_left[h] > 0) begin_circuit(e, h, now);
    }
  }
  void on_notify(Engine& e, int h, int sock, int flags, int32_t meta,
                 int32_t, int32_t, int64_t now) override {
    int role = static_cast<int>(e.c.a0[h]);
    bool est = flags & N_ESTABLISHED, msg = flags & N_MSG;
    int64_t circ = meta >> 18, aux = (meta >> 4) & 0x3FFF;
    int cmd = meta & 0xF;
    if (role == 1) {
      if (est && sock == 2 && cl_state[h] == CL_DIR_CONN) {
        cl_state[h] = CL_DIR_FETCH;
        push_cell(e, h, 2, meta_of(0, 0, C_DIRREQ), CELL, now);
      }
      if (msg && sock == 2 && cmd == C_DIRRESP && cl_state[h] == CL_DIR_FETCH) {
        cl_guard[h] = pick_weighted(e, h, e.c.t_ids0, e.c.t_cum0);
        bootstrap_time[h] = now;
        cl_state[h] = CL_GUARD_CONN;
        e.close(h, 2, now);
        e.connect(h, 1, cl_guard[h], 0, now);
      }
      if (est && sock == 1 && cl_state[h] == CL_GUARD_CONN)
        begin_circuit(e, h, now);
      if (msg && sock == 1 && circ == cl_circ[h]) {
        if (cmd == C_CREATED && cl_hop[h] == 1) {
          cl_hop[h] = 2;
          push_cell(e, h, 1, meta_of(circ, cl_mid[h], C_EXTEND), CELL, now);
        } else if (cmd == C_EXTENDED && cl_hop[h] == 2) {
          cl_hop[h] = 3;
          push_cell(e, h, 1, meta_of(circ, cl_exit[h], C_EXTEND), CELL, now);
        } else if (cmd == C_EXTENDED && cl_hop[h] == 3) {
          begin_stream(e, h, now);
        } else if (cmd == C_DATA && cl_state[h] == CL_STREAM) {
          cells_rx[h] += aux;
        } else if (cmd == C_END && cl_state[h] == CL_STREAM) {
          streams_done[h]++;
          if (--cl_streams_left[h] == 0) {
            if (--cl_circs_left[h] == 0) {
              done_time[h] = now;
              cl_state[h] = CL_DONE;
              return;
            }
          }
          think(e, h, now);
        }
      }
      return;
    }
    if (role == 2) {
      if (msg && cmd == C_DIRREQ)
        push_cell(e, h, sock, meta_of(0, 0, C_DIRRESP),
                  static_cast<int32_t>(e.c.s0), now);
      if (flags & N_PEER_FIN) e.close(h, sock, now);
      return;
    }
    if (role != 0) return;
    int64_t sbase = static_cast<int64_t>(h) * e.c.sockets_per_host;
    if (est && rc_peer[sbase + sock] >= 0) {
      int32_t pp[2] = {OP_DRAIN, sock};
      e.schedule_local(h, now, K_APP, pp, 2);
    }
    if (!msg) return;
    relay_on_cell(e, h, sock, meta, now);
  }
  void relay_on_cell(Engine& e, int h, int sock, int32_t meta, int64_t now) {
    int64_t circ = meta >> 18, aux = (meta >> 4) & 0x3FFF;
    int cmd = meta & 0xF;
    int64_t base = static_cast<int64_t>(h) * ct_cap;
    int64_t sbase = static_cast<int64_t>(h) * e.c.sockets_per_host;
    if (cmd == C_CREATE) {
      int slot = -1;
      for (int64_t i = 0; i < ct_cap; ++i)
        if (!ct_used[base + i]) { slot = static_cast<int>(i); break; }
      if (slot < 0) { ct_overflow[h]++; return; }
      ct_used[base + slot] = 1;
      ct_in_sock[base + slot] = sock;
      ct_in_circ[base + slot] = static_cast<int32_t>(circ);
      ct_out_sock[base + slot] = -1;
      ct_pend[base + slot] = 0;
      push_cell(e, h, sock, meta_of(circ, 0, C_CREATED), CELL, now);
      return;
    }
    int idx = -1;
    bool from_in = false, from_out = false;
    for (int64_t i = 0; i < ct_cap; ++i)
      if (ct_used[base + i] && ct_in_sock[base + i] == sock &&
          ct_in_circ[base + i] == circ) { idx = static_cast<int>(i); from_in = true; break; }
    if (idx < 0)
      for (int64_t i = 0; i < ct_cap; ++i)
        if (ct_used[base + i] && ct_out_sock[base + i] == sock &&
            ct_out_circ[base + i] == circ) { idx = static_cast<int>(i); from_out = true; break; }
    if (idx < 0) return;

    if (from_in && cmd == C_EXTEND && ct_out_sock[base + idx] < 0) {
      int target = static_cast<int>(aux);
      int r_sock = -1;
      for (int64_t s = 0; s < e.c.sockets_per_host; ++s)
        if (rc_peer[sbase + s] == target) { r_sock = static_cast<int>(s); break; }
      int osock;
      if (r_sock >= 0) {
        osock = r_sock;
      } else {
        osock = -1;
        for (int64_t s = 1; s < e.c.sockets_per_host; ++s)
          if (e.sk(h, static_cast<int>(s)).st == TCP_FREE) { osock = static_cast<int>(s); break; }
        if (osock < 0) { ct_overflow[h]++; return; }
      }
      int32_t ocirc = rc_next_circ[sbase + osock]++;
      if (r_sock < 0) rc_peer[sbase + osock] = target;
      ct_out_sock[base + idx] = osock;
      ct_out_circ[base + idx] = ocirc;
      bool conn_up = r_sock >= 0 && e.sk(h, osock).st == TCP_ESTABLISHED;
      ct_pend[base + idx] = conn_up ? 0 : 1;
      if (conn_up)
        push_cell(e, h, osock, meta_of(ocirc, 0, C_CREATE), CELL, now);
      if (r_sock < 0) {
        int32_t pp[3] = {OP_CONNECT_RELAY, osock, target};
        e.schedule_local(h, now, K_APP, pp, 3);
      }
      return;
    }
    if (from_out && cmd == C_CREATED) {
      push_cell(e, h, ct_in_sock[base + idx],
                meta_of(ct_in_circ[base + idx], 0, C_EXTENDED), CELL, now);
      return;
    }
    if (from_in && cmd == C_BEGIN && ct_out_sock[base + idx] < 0) {
      push_cell(e, h, sock, meta_of(circ, aux, C_DATA),
                static_cast<int32_t>(aux * CELL), now);
      push_cell(e, h, sock, meta_of(circ, 0, C_END), CELL, now);
      return;
    }
    int32_t nbytes = cmd == C_DATA ? static_cast<int32_t>(aux * CELL) : CELL;
    if (from_in && cmd != C_CREATED && ct_out_sock[base + idx] >= 0) {
      cells_fwd[h]++;
      push_cell(e, h, ct_out_sock[base + idx],
                meta_of(ct_out_circ[base + idx], aux, cmd), nbytes, now);
    } else if (from_out && cmd != C_CREATED) {
      cells_fwd[h]++;
      push_cell(e, h, ct_in_sock[base + idx],
                meta_of(ct_in_circ[base + idx], aux, cmd), nbytes, now);
    }
  }
  void summary(char* buf, size_t n) override {
    int64_t sd = 0, cf = 0, cr = 0, done = 0, over = 0;
    for (auto v : streams_done) sd += v;
    for (auto v : cells_fwd) cf += v;
    for (auto v : cells_rx) cr += v;
    for (auto v : done_time) done += v > 0 ? 1 : 0;
    for (auto v : ct_overflow) over += v;
    std::snprintf(buf, n,
                  "\"total_streams_done\": %lld, \"total_cells_fwd\": %lld, "
                  "\"total_cells_rx\": %lld, \"clients_done\": %lld, "
                  "\"total_ct_overflow\": %lld",
                  (long long)sd, (long long)cf, (long long)cr,
                  (long long)done, (long long)over);
  }
};

// bitcoin: peers=[H*K] a0=tx_origin(n_tx) a1=tx_time(n_tx)
//          s0=tx_size s1=inv_size s2=connect_time s3=K s4=n_tx
struct Bitcoin : App {
  static constexpr int OP_CONNECT_ONE = 1, OP_TX_CREATE = 2, OP_TX_MSG = 3;
  static constexpr int CMD_INV = 1, CMD_GET = 2, CMD_TX = 3;
  static constexpr int TXID_BITS = 20;
  int64_t K = 0, n_tx = 0;
  std::vector<int32_t> nbr_sock;       // [h*K + j]
  std::vector<char> seen, req;         // [h*n_tx + t]
  std::vector<int64_t> tx_rx, msg_retries;

  static int32_t meta_of(int cmd, int64_t txid) {
    return static_cast<int32_t>((static_cast<int64_t>(cmd) << TXID_BITS) | txid);
  }
  void push_msg(Engine& e, int h, int sock, int32_t meta, int32_t nbytes,
                int64_t now) {
    int32_t p[4] = {OP_TX_MSG, sock, meta, nbytes};
    e.schedule_local(h, now, K_APP, p, 4);
  }
  void announce(Engine& e, int h, int64_t txid, int skip_sock, int64_t now) {
    for (int64_t j = 0; j < K; ++j) {
      int ns = nbr_sock[h * K + j];
      if (ns >= 0 && ns != skip_sock)
        push_msg(e, h, ns, meta_of(CMD_INV, txid),
                 static_cast<int32_t>(e.c.s1), now);
    }
  }
  bool mark_seen(int h, int64_t txid) {
    if (seen[h * n_tx + txid]) return false;
    seen[h * n_tx + txid] = 1;
    return true;
  }
  void start(Engine& e) override {
    int64_t n = e.c.n_hosts;
    K = e.c.s3;
    n_tx = e.c.s4;
    nbr_sock.assign(n * K, -1);
    seen.assign(n * n_tx, 0);
    req.assign(n * n_tx, 0);
    tx_rx.assign(n, 0);
    msg_retries.assign(n, 0);
    for (int64_t h = 0; h < n; ++h) e.listen(h, 0);
    for (int64_t j = 0; j < K; ++j)
      for (int64_t h = 0; h < n; ++h)
        if (e.c.peers[h * K + j] > h) {
          int32_t p[2] = {OP_CONNECT_ONE, static_cast<int32_t>(j)};
          e.schedule_local(h, e.c.s2, K_APP, p, 2);
        }
    for (int64_t t = 0; t < n_tx; ++t) {
      int32_t p[2] = {OP_TX_CREATE, static_cast<int32_t>(t)};
      e.schedule_local(static_cast<int>(e.c.a0[t]), e.c.a1[t], K_APP, p, 2);
    }
  }
  void on_wakeup(Engine& e, int h, int64_t now, const int32_t* p) override {
    if (p[0] == OP_CONNECT_ONE) {
      int j = p[1];
      nbr_sock[h * K + j] = 1 + j;
      e.connect(h, 1 + j, static_cast<int>(e.c.peers[h * K + j]), 0, now);
    } else if (p[0] == OP_TX_CREATE) {
      if (mark_seen(h, p[1])) announce(e, h, p[1], -1, now);
    } else if (p[0] == OP_TX_MSG) {
      int sock = p[1];
      int32_t meta = p[2], nbytes = p[3];
      Sock& k = e.sk(h, sock);
      int64_t buffered =
          seq_sub(k.app_end, k.snd_una) - (k.snd_una == 0 ? 1 : 0);
      bool fits = (e.c.sndbuf - buffered) >= nbytes;
      bool mq_ok = static_cast<int64_t>(k.mq.size()) < e.c.msgq_cap;
      if (fits && mq_ok) {
        e.tcp_send(h, sock, nbytes, meta, now);
      } else {
        msg_retries[h]++;
        int64_t t_retry = (now / e.c.window_ns + 1) * e.c.window_ns;
        int32_t pp[4] = {OP_TX_MSG, sock, meta, nbytes};
        e.schedule_local(h, t_retry, K_APP, pp, 4);
      }
    }
  }
  void on_notify(Engine& e, int h, int sock, int flags, int32_t meta,
                 int32_t, int32_t, int64_t now) override {
    if (flags & N_ACCEPTED) {
      int peer = e.sk(h, sock).peer_host;
      for (int64_t j = 0; j < K; ++j)
        if (e.c.peers[h * K + j] == peer && nbr_sock[h * K + j] < 0)
          nbr_sock[h * K + j] = sock;
    }
    if (flags & N_MSG) {
      int cmd = meta >> TXID_BITS;
      int64_t txid = meta & ((1 << TXID_BITS) - 1);
      if (cmd == CMD_INV && !seen[h * n_tx + txid] && !req[h * n_tx + txid]) {
        req[h * n_tx + txid] = 1;
        push_msg(e, h, sock, meta_of(CMD_GET, txid),
                 static_cast<int32_t>(e.c.s1), now);
      } else if (cmd == CMD_GET && seen[h * n_tx + txid]) {
        push_msg(e, h, sock, meta_of(CMD_TX, txid),
                 static_cast<int32_t>(e.c.s0), now);
      } else if (cmd == CMD_TX) {
        tx_rx[h]++;
        if (mark_seen(h, txid)) announce(e, h, txid, sock, now);
      }
    }
  }
  void summary(char* buf, size_t n) override {
    int64_t ts = 0, tr = 0;
    for (auto v : seen) ts += v;
    for (auto v : tx_rx) tr += v;
    std::snprintf(buf, n, "\"total_seen\": %lld, \"total_tx_rx\": %lld",
                  (long long)ts, (long long)tr);
  }
};

// ---------------------------------------------------------------- main ----
int main_run(int argc, char** argv) {
  if (argc != 4) {
    std::fprintf(stderr, "usage: net_comparator <table> <config> <threads>\n");
    return 2;
  }
  {
    std::FILE* f = std::fopen(argv[1], "rb");
    if (!f) { std::fprintf(stderr, "no table\n"); return 2; }
    size_t want = (1 << LOG_BITS) + 1;
    if (std::fread(LOG_TBL, 8, want, f) != want ||
        std::fread(&LN2_Q32, 8, 1, f) != 1) {
      std::fclose(f);
      std::fprintf(stderr, "bad table\n");
      return 2;
    }
    std::fclose(f);
  }
  Config cfg;
  if (!read_config(argv[2], &cfg)) {
    std::fprintf(stderr, "bad config blob\n");
    return 2;
  }
  int n_threads = std::atoi(argv[3]);
  if (n_threads < 1) n_threads = 1;

  Engine eng(cfg, n_threads);
  Filexfer fx;
  Tgen tg;
  Tor tor;
  Bitcoin btc;
  switch (cfg.app_id) {
    case 1: eng.app = &fx; break;
    case 2: eng.app = &tg; break;
    case 3: eng.app = &tor; break;
    case 4: eng.app = &btc; break;
    default: std::fprintf(stderr, "bad app id\n"); return 2;
  }
  eng.app->start(eng);

  std::atomic<int> barrier_count{0};
  std::atomic<int64_t> barrier_gen{0};
  auto barrier = [&]() {
    int64_t gen = barrier_gen.load();
    if (barrier_count.fetch_add(1) == n_threads - 1) {
      barrier_count.store(0);
      barrier_gen.fetch_add(1);
    } else {
      while (barrier_gen.load() == gen) std::this_thread::yield();
    }
  };

  auto worker = [&](int t) {
    Shard& me = eng.shards[t];
    for (int64_t w = 0; w < cfg.n_windows; ++w) {
      const int64_t win_end = (w + 1) * cfg.window_ns;
      while (!me.heap.empty() && me.heap.top().time < win_end) {
        Ev ev = me.heap.top();
        me.heap.pop();
        eng.pending[ev.host]--;
        if (ev.kind == K_PKT) {
          // rx fast path: plumbing, not an event (rx_batch contract)
          eng.rx_convert(ev.host, ev.time, ev.tb, ev.p);
          continue;
        }
        me.m.events++;
        eng.handle(ev.host, ev.time, ev.kind, ev.p);
      }
      barrier();
      {
        std::lock_guard<std::mutex> g(me.mbox_mu);
        for (const Ev& ev : me.mailbox) {
          if (eng.pending[ev.host] >= cfg.ev_cap) { me.m.ev_overflow++; continue; }
          eng.pending[ev.host]++;
          me.m.pkts_delivered++;
          me.heap.push(ev);
        }
        me.mailbox.clear();
      }
      barrier();
    }
  };

  auto t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();
  const double wall =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();

  Metrics tot;
  for (const Shard& s : eng.shards) {
    tot.events += s.m.events;
    tot.pkts_sent += s.m.pkts_sent;
    tot.pkts_delivered += s.m.pkts_delivered;
    tot.pkts_lost += s.m.pkts_lost;
    tot.ev_overflow += s.m.ev_overflow;
    tot.ob_overflow += s.m.ob_overflow;
    tot.tcp_fast_rtx += s.m.tcp_fast_rtx;
    tot.tcp_rto += s.m.tcp_rto;
    tot.tcp_ooo_drops += s.m.tcp_ooo_drops;
    tot.pops_deliver += s.m.pops_deliver;
    tot.pops_timer += s.m.pops_timer;
    tot.pops_txr += s.m.pops_txr;
    tot.pops_app += s.m.pops_app;
  }
  char sum[512];
  eng.app->summary(sum, sizeof sum);
  std::printf(
      "{\"events\": %lld, \"pkts_sent\": %lld, \"pkts_delivered\": %lld, "
      "\"pkts_lost\": %lld, \"ev_overflow\": %lld, \"ob_overflow\": %lld, "
      "\"tcp_fast_rtx\": %lld, \"tcp_rto\": %lld, \"tcp_ooo_drops\": %lld, "
      "\"pops_deliver\": %lld, \"pops_timer\": %lld, \"pops_txr\": %lld, "
      "\"pops_app\": %lld, %s, \"wall_s\": %.6f, \"events_per_sec\": %.1f, "
      "\"n_threads\": %d}\n",
      (long long)tot.events, (long long)tot.pkts_sent,
      (long long)tot.pkts_delivered, (long long)tot.pkts_lost,
      (long long)tot.ev_overflow, (long long)tot.ob_overflow,
      (long long)tot.tcp_fast_rtx, (long long)tot.tcp_rto,
      (long long)tot.tcp_ooo_drops, (long long)tot.pops_deliver,
      (long long)tot.pops_timer, (long long)tot.pops_txr,
      (long long)tot.pops_app, sum, wall, tot.events / (wall > 0 ? wall : 1),
      n_threads);
  return 0;
}

}  // namespace

int main(int argc, char** argv) { return main_run(argc, argv); }
