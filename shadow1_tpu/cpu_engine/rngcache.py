"""Block-cached counter RNG draws for the CPU oracle.

The oracle consumes draws one at a time; issuing one eager JAX call per draw
would dominate its runtime. Draws are pure functions of (purpose, host,
counter), so we batch-compute blocks of consecutive counters with the exact
same jnp transforms the TPU engine traces (shadow1_tpu.rng) and cache them —
bit-identical values, amortized dispatch.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow1_tpu import rng

_BLOCK = 256


class DrawCache:
    def __init__(self, seed: int):
        self.key = rng.base_key(seed)
        self._bits: dict[tuple, np.ndarray] = {}
        self._xf: dict[tuple, np.ndarray] = {}  # transformed-value blocks

    def _bits_block(self, purpose: int, host: int, blk: int) -> np.ndarray:
        k = (purpose, host, blk)
        got = self._bits.get(k)
        if got is None:
            ctrs = jnp.arange(blk * _BLOCK, (blk + 1) * _BLOCK)
            hosts = jnp.full(_BLOCK, host)
            got = np.asarray(rng.bits_v(self.key, purpose, hosts, ctrs))
            self._bits[k] = got
        return got

    def bits(self, purpose: int, host: int, ctr: int) -> np.uint32:
        return self._bits_block(purpose, host, ctr // _BLOCK)[ctr % _BLOCK]

    def _xf_block(self, tag, purpose, host, ctr, fn) -> np.ndarray:
        """Whole-block transform via the shared jnp code path (one eager call
        per block instead of one per draw)."""
        blk = ctr // _BLOCK
        k = (tag, purpose, host, blk)
        got = self._xf.get(k)
        if got is None:
            b = jnp.asarray(self._bits_block(purpose, host, blk))
            got = np.asarray(fn(b))
            self._xf[k] = got
        return got

    def exponential_ns(self, purpose: int, host: int, ctr: int, mean_ns: float) -> int:
        blk = self._xf_block(
            ("e", mean_ns), purpose, host, ctr, lambda b: rng.exponential_ns(b, mean_ns)
        )
        return int(blk[ctr % _BLOCK])

    def randint(self, purpose: int, host: int, ctr: int, n: int) -> int:
        blk = self._xf_block(("r", n), purpose, host, ctr, lambda b: rng.randint(b, n))
        return int(blk[ctr % _BLOCK])
