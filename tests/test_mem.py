"""Memory-safe execution: pre-flight HBM budget, structured OOM taxonomy,
bit-exact downshift (shadow1_tpu/mem.py).

The contract under test (docs/SEMANTICS.md "Memory contract"):

* the pre-flight estimator's resident bytes track ``jax.live_arrays()``
  within 10% — solo, fleet E=3, and after an ``--auto-caps``-style
  resize (the estimator re-runs at the grown caps);
* an oversubscribed config exits EXIT_MEMORY with per-plane attribution
  and advice BEFORE compiling, and the supervisor classifies that exit
  (and a raw RESOURCE_EXHAUSTED crash) as deterministic — no respawn;
* ``--on-oom downshift`` degrades in bit-exactness-preserving order
  (rollback drop → ring shrink → fleet sub-batch), and a sub-batched
  fleet's per-lane digest streams are bit-identical to the full-E run.
"""

import dataclasses
import gc
import json
import os
import subprocess
import sys

import pytest

from shadow1_tpu import mem
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import EXIT_MEMORY, MS, EngineParams
from shadow1_tpu.core.engine import Engine


def phold_exp(n_hosts=16, seed=5, windows=40):
    return single_vertex_experiment(
        n_hosts=n_hosts, seed=seed, end_time=windows * MS, latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 4},
    )


def _measured_resident(build):
    """live-bytes delta of whatever ``build()`` returns (held until
    measured) — the actual side of the estimator audit."""
    import jax

    gc.collect()
    base = mem.live_bytes()
    obj = build()
    jax.block_until_ready(obj)
    measured = mem.live_bytes() - base
    del obj
    gc.collect()
    return measured


# ---------------------------------------------------------------------------
# Estimator-vs-actual byte audits (the 10% acceptance bound)
# ---------------------------------------------------------------------------

def test_estimate_matches_live_bytes_solo():
    exp = phold_exp()
    params = EngineParams(ev_cap=32, outbox_cap=16, metrics_ring=10)
    est = mem.estimate(exp, params)

    def build():
        eng = Engine(exp, params)
        return (eng, eng.init_state())

    measured = _measured_resident(build)
    assert measured > 0
    ratio = est.resident_bytes / measured
    assert abs(ratio - 1.0) <= mem.AUDIT_TOLERANCE, (
        est.resident_bytes, measured)


def test_estimate_matches_live_bytes_net_model():
    import numpy as np

    n = 8
    exp = single_vertex_experiment(
        n_hosts=n, seed=3, end_time=20 * MS, latency_ns=1 * MS,
        model="net", model_cfg={
            "app": "tgen",
            "active": np.ones(n, np.int64),
            "streams": np.full(n, 2, np.int64),
            "mean_bytes": np.full(n, 20000, np.float64),
            "mean_think_ns": np.full(n, 50.0 * MS, np.float64),
            "start_time": np.full(n, 1 * MS, np.int64),
        },
    )
    params = EngineParams(ev_cap=32, outbox_cap=16)
    est = mem.estimate(exp, params)

    def build():
        eng = Engine(exp, params)
        return (eng, eng.init_state())

    measured = _measured_resident(build)
    ratio = est.resident_bytes / measured
    assert abs(ratio - 1.0) <= mem.AUDIT_TOLERANCE, (
        est.resident_bytes, measured)


def test_estimate_matches_live_bytes_fleet_e3():
    from shadow1_tpu.fleet.engine import FleetEngine

    exps = [phold_exp(seed=s) for s in (5, 6, 7)]
    params = EngineParams(ev_cap=32, outbox_cap=16, metrics_ring=10)
    est = mem.estimate(exps[0], params, n_exp=3)
    assert est.planes["evbuf"] == 3 * mem.estimate(exps[0],
                                                   params).planes["evbuf"]

    def build():
        eng = FleetEngine(exps, params)
        return (eng, eng.init_state())

    measured = _measured_resident(build)
    ratio = est.resident_bytes / measured
    assert abs(ratio - 1.0) <= mem.AUDIT_TOLERANCE, (
        est.resident_bytes, measured)


def test_estimate_matches_live_bytes_after_cap_resize():
    """The post---auto-caps-resize audit: a state migrated to grown caps
    (tune/resize.py — exactly what the controller and retry guard do)
    matches the estimate at the NEW params."""
    import jax
    import numpy as np

    from shadow1_tpu.tune.resize import resize_state

    exp = phold_exp()
    small = EngineParams(ev_cap=16, outbox_cap=16)
    grown = dataclasses.replace(small, ev_cap=48)
    eng = Engine(exp, small)
    st = eng.run(n_windows=4)
    host_st = jax.tree.map(np.asarray, st)
    big = resize_state(host_st, ev_cap=48, outbox_cap=16)
    measured_state = mem.tree_bytes(jax.tree_util.tree_leaves(big))
    est = mem.estimate(exp, grown)
    ratio = est.state_bytes / measured_state
    assert abs(ratio - 1.0) <= mem.AUDIT_TOLERANCE, (
        est.state_bytes, measured_state)


def test_estimate_allocates_nothing_state_sized():
    """The whole point of pre-flight: estimating a 1M-host config must not
    allocate its planes (the abstract trace stages instead of executing)."""
    exp = phold_exp(n_hosts=1 << 20)
    params = EngineParams(ev_cap=256, outbox_cap=32)
    gc.collect()
    base = mem.live_bytes()
    est = mem.estimate(exp, params)
    assert est.state_bytes > (16 << 30)  # a >16 GiB config...
    gc.collect()
    grew = mem.live_bytes() - base
    assert grew < (64 << 20), grew  # ...costs under 64 MiB to estimate


# ---------------------------------------------------------------------------
# Budget check + downshift planner
# ---------------------------------------------------------------------------

def test_check_budget_raises_structured_error():
    exp = phold_exp()
    params = EngineParams(ev_cap=32, outbox_cap=16, on_overflow="retry")
    est = mem.estimate(exp, params)
    with pytest.raises(mem.MemoryBudgetError) as ei:
        mem.check_budget(est, est.peak_bytes // 2, "env")
    e = ei.value
    assert e.estimated == est.peak_bytes
    assert e.planes["evbuf"] > 0 and e.peaks["rollback"] > 0
    assert "--on-overflow halt" in e.advice  # rollback remedy named
    assert "downshift" in e.advice
    # over-budget is not OOM (handled by type, not string match)
    assert not mem.is_oom(e)


def test_downshift_order_rollback_then_ring_then_lanes():
    exp = phold_exp()
    params = EngineParams(ev_cap=32, outbox_cap=16, on_overflow="retry",
                          metrics_ring=64)
    est = mem.estimate(exp, params, n_exp=4)
    # Budget that needs all three stages: below the rollback-dropped,
    # ring-floored 4-lane peak but enough for 2 lanes.
    no_roll = dataclasses.replace(params, on_overflow="halt",
                                  metrics_ring=0)
    floor4 = mem.estimate(exp, no_roll, n_exp=4)
    floor2 = mem.estimate(exp, no_roll, n_exp=2)
    budget = (floor2.peak_bytes + floor4.peak_bytes) // 2
    p2, sub, actions = mem.downshift(exp, params, 4, budget)
    kinds = [a["action"] for a in actions]
    assert kinds == ["drop_rollback", "shrink_ring", "sub_batch"]
    assert p2.on_overflow == "halt"
    assert p2.metrics_ring < 64
    assert 1 <= sub < 4
    assert mem.estimate(exp, p2, n_exp=sub).peak_bytes <= budget


def test_downshift_keeps_ring_when_digest_on():
    exp = phold_exp()
    params = EngineParams(ev_cap=32, outbox_cap=16, metrics_ring=64,
                          state_digest=1)
    tiny = mem.estimate(exp, dataclasses.replace(params, metrics_ring=1))
    with pytest.raises(mem.MemoryBudgetError):
        # even W=1 doesn't fit → exhausted, but never W=0 under digest
        mem.downshift(exp, params, 1, tiny.peak_bytes // 4)
    p2, _, actions = mem.downshift(exp, params, 1, tiny.peak_bytes + 64)
    assert p2.metrics_ring == 1 and p2.state_digest == 1
    assert actions[0]["action"] == "shrink_ring"


def test_downshift_subbatch_resume_gating():
    """Sub-batching refuses an explicit --resume/--save-state snapshot
    path (no batch cursor), but composes with --ckpt — the CLI sets
    ``subbatch_resumable`` for plain --ckpt runs and each batch then
    checkpoints its own state (the PR 13 lifted refusal;
    tests/test_fleet_recover.py proves the round trip end to end)."""
    exp = phold_exp()
    params = EngineParams(ev_cap=32, outbox_cap=16)
    e1 = mem.estimate(exp, params, n_exp=1)
    e4 = mem.estimate(exp, params, n_exp=4)
    budget = (e1.peak_bytes + e4.peak_bytes) // 2
    with pytest.raises(mem.MemoryBudgetError) as ei:
        mem.downshift(exp, params, 4, budget, resumable=True)
    assert "--ckpt" in str(ei.value)
    p2, sub, actions = mem.downshift(exp, params, 4, budget,
                                     resumable=True,
                                     subbatch_resumable=True)
    assert sub is not None and 1 <= sub < 4
    assert actions[-1]["action"] == "sub_batch"


def test_downshift_skips_ring_shrink_when_resumable():
    """The ring is a state leaf: a resumable run must not shrink it (a
    budget change against an existing lineage would hit a snapshot shape
    mismatch) — only the shape-neutral rollback drop applies."""
    exp = phold_exp()
    params = EngineParams(ev_cap=32, outbox_cap=16, on_overflow="retry",
                          metrics_ring=64)
    no_roll = dataclasses.replace(params, on_overflow="halt")
    floor = mem.estimate(exp, no_roll)
    ringless = mem.estimate(
        exp, dataclasses.replace(no_roll, metrics_ring=0))
    budget = (ringless.peak_bytes + floor.peak_bytes) // 2
    # non-resumable: rollback drop + ring shrink reach the budget
    p2, _, actions = mem.downshift(exp, params, 1, budget)
    assert [a["action"] for a in actions] == ["drop_rollback",
                                              "shrink_ring"]
    # resumable: the ring stage is skipped → downshift exhausts instead
    with pytest.raises(mem.MemoryBudgetError) as ei:
        mem.downshift(exp, params, 1, budget, resumable=True)
    assert "snapshot shape" in str(ei.value)


def test_is_oom_taxonomy():
    assert mem.is_oom(RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                                   "allocating 123 bytes"))
    assert mem.is_oom(MemoryError())
    assert not mem.is_oom(RuntimeError("INVALID_ARGUMENT: shape"))
    from shadow1_tpu.txn import CapacityExceededError

    assert not mem.is_oom(CapacityExceededError(
        "ev_cap", "ev_overflow", 8, 1, (0, 10)))


def test_device_budget_env_override(monkeypatch):
    monkeypatch.setenv(mem.MEM_BYTES_ENV, str(123 << 20))
    b, src = mem.device_budget()
    assert b == 123 << 20 and src == "env"


# ---------------------------------------------------------------------------
# Sub-batched fleet ≡ full fleet (the downshift bit-exactness contract)
# ---------------------------------------------------------------------------

def test_subbatched_fleet_digest_parity():
    from shadow1_tpu.tools.memprobe import subbatch_parity

    cfg = os.path.join(os.path.dirname(__file__), "..", "configs",
                       "sweep_phold.yaml")
    v = subbatch_parity(cfg, sub=3, windows=12, say=lambda m: None)
    assert v["ok"], v
    assert v["experiments"] == 4 and v["streams_compared"] == 4


# ---------------------------------------------------------------------------
# CLI + supervisor (subprocess): EXIT_MEMORY taxonomy end to end
# ---------------------------------------------------------------------------

def _write_cfg(tmp_path, extra_engine="") -> str:
    cfg = tmp_path / "mem_phold.yaml"
    cfg.write_text(
        "general: {seed: 5, stop_time: 20 ms}\n"
        f"engine: {{scheduler: tpu, ev_cap: 32{extra_engine}}}\n"
        "network: {single_vertex: {latency: 1 ms}}\n"
        "hosts:\n"
        "  - {name: h, count: 16}\n"
        "app:\n"
        "  model: phold\n"
        "  params: {mean_delay_ns: 2000000.0, init_events: 4}\n"
    )
    return str(cfg)


def test_cli_preflight_exit_memory(tmp_path):
    """An over-budget config exits EXIT_MEMORY before compile with the
    parseable record and per-plane advice (the capacity-halt shape)."""
    cfg = _write_cfg(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           mem.MEM_BYTES_ENV: "30000"}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", cfg],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_MEMORY, (r.returncode, r.stderr[-600:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["error"] == "memory_budget"
    assert rec["budget"] == 30000 and rec["estimated"] > 30000
    assert rec["planes"]["evbuf"] > 0
    assert "Remedies" in rec["advice"]
    assert "MemoryBudgetError" in r.stderr


def test_cli_emits_mem_record_and_runs_when_fits(tmp_path):
    cfg = _write_cfg(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           mem.MEM_BYTES_ENV: str(1 << 30)}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", cfg,
                        "--windows", "5"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-800:]
    mems = [json.loads(x) for x in r.stderr.splitlines()
            if x.startswith("{") and '"type": "mem"' in x]
    assert mems and mems[0]["event"] == "estimate"
    assert mems[0]["budget"] == 1 << 30
    assert mems[0]["headroom"] > 0
    assert mems[0]["planes"]["evbuf"] > 0


def test_cli_downshift_demotes_retry_and_runs(tmp_path):
    """--on-oom downshift under a budget that fits only without the
    rollback copy: retry demotes to halt, the run completes, and the
    downshift record documents the action."""
    cfg = _write_cfg(tmp_path)
    from shadow1_tpu.config.experiment import load_experiment

    exp, params, _ = load_experiment(cfg)
    p = dataclasses.replace(params, on_overflow="retry", metrics_ring=10)
    est = mem.estimate(exp, p)
    budget = est.peak_bytes - est.peaks["rollback"] + 512
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           mem.MEM_BYTES_ENV: str(budget)}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", cfg,
                        "--windows", "10", "--on-overflow", "retry",
                        "--metrics-ring", "10", "--on-oom", "downshift"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-800:]
    ds = [json.loads(x) for x in r.stderr.splitlines()
          if x.startswith("{") and '"event": "downshift"' in x]
    assert ds and ds[0]["actions"][0]["action"] == "drop_rollback"
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["retries"]["policy"] == "halt"  # demoted, loud not lossy


def test_cli_runtime_oom_maps_to_exit_memory(tmp_path):
    """The runtime taxonomy: a RESOURCE_EXHAUSTED mid-run (injected) exits
    EXIT_MEMORY with a phase-tagged parseable record."""
    cfg = _write_cfg(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_MEM_INJECT_OOM": "run"}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", cfg,
                        "--windows", "5"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_MEMORY, (r.returncode, r.stderr[-600:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["error"] == "memory_exhausted"
    assert rec["phase"] == "run"
    assert "RESOURCE_EXHAUSTED" in rec["message"]


def test_supervisor_classifies_exit_memory_no_respawn(tmp_path):
    """--ckpt supervision over an over-budget child: EXIT_MEMORY is
    deterministic — classify and stop, never respawn."""
    cfg = _write_cfg(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0",
           mem.MEM_BYTES_ENV: "30000"}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", cfg,
                        "--ckpt", str(tmp_path / "ck.npz"),
                        "--heartbeat", "5"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_MEMORY, (r.returncode, r.stderr[-600:])
    assert "exhausted device memory (rc=EXIT_MEMORY)" in r.stderr
    assert "respawning (" not in r.stderr  # zero respawn attempts


def test_supervisor_classifies_raw_oom_crash(tmp_path):
    """Belt and braces: a child that dies with a RAW RESOURCE_EXHAUSTED on
    stderr (taxonomy bypassed — generic rc) is still classified via the
    stderr scan; no crash-loop through the backoff ladder."""
    cfg = _write_cfg(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0",
           "SHADOW1_MEM_INJECT_OOM": "raw"}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", cfg,
                        "--ckpt", str(tmp_path / "ck.npz"),
                        "--heartbeat", "5"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_MEMORY, (r.returncode, r.stderr[-600:])
    assert "raw RESOURCE_EXHAUSTED on stderr" in r.stderr
    assert "respawning (" not in r.stderr
    # the raw marker itself was teed through to the parent's stderr
    assert "injected raw OOM" in r.stderr


def test_cli_rejects_downshift_on_cpu_engine(tmp_path, capsys):
    from shadow1_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    with pytest.raises(SystemExit) as ei:
        main([cfg, "--engine", "cpu", "--on-oom", "downshift"])
    assert ei.value.code == 2
    assert "batched engine" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Fleet CLI: sub-batched downshift end to end
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_cli_fleet_subbatch_downshift(tmp_path):
    cfg = tmp_path / "sweep.yaml"
    cfg.write_text(
        "general: {seed: 7, stop_time: 40 ms}\n"
        "engine: {scheduler: tpu, ev_cap: 32, outbox_cap: 16}\n"
        "network: {single_vertex: {latency: 10 ms}}\n"
        "hosts:\n"
        "  - {name: h, count: 8}\n"
        "app:\n"
        "  model: phold\n"
        "  params: {mean_delay_ns: 2.0e7, init_events: 2}\n"
        "sweep:\n"
        "  seeds: [7, 8, 9, 10]\n"
    )
    from shadow1_tpu.fleet.expand import load_sweep

    plan = load_sweep(str(cfg))
    e2 = mem.estimate(plan.exps[0], plan.params, n_exp=2)
    e4 = mem.estimate(plan.exps[0], plan.params, n_exp=4)
    budget = (e2.peak_bytes + e4.peak_bytes) // 2
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           mem.MEM_BYTES_ENV: str(budget)}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", str(cfg),
                        "--fleet", "--on-oom", "downshift"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-800:]
    recs = [json.loads(x) for x in r.stdout.strip().splitlines()]
    exps = [x for x in recs if x.get("type") == "fleet_exp"]
    summary = [x for x in recs if x.get("type") == "fleet_summary"][-1]
    assert len(exps) == 4
    assert sorted(x["exp"] for x in exps) == [0, 1, 2, 3]
    assert summary["experiments"] == 4
    assert summary["sub_batches"] >= 2
    assert len(summary["events_per_exp"]) == 4
    # sub-batched lanes must bit-match a full-fleet run of the same sweep
    r2 = subprocess.run([sys.executable, "-m", "shadow1_tpu", str(cfg),
                        "--fleet"],
                        env={**os.environ, "JAX_PLATFORMS": "cpu"},
                        capture_output=True, text=True, timeout=600)
    assert r2.returncode == 0, r2.stderr[-800:]
    full = {x["exp"]: x["metrics"]["events"]
            for x in map(json.loads, r2.stdout.strip().splitlines())
            if x.get("type") == "fleet_exp"}
    assert {x["exp"]: x["metrics"]["events"] for x in exps} == full


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def test_heartbeat_report_memory_section(tmp_path, capsys):
    from shadow1_tpu.tools.heartbeat_report import summarize

    recs = [
        {"type": "mem", "event": "estimate", "estimated_state": 1 << 20,
         "estimated_resident": 1100000, "estimated_peak": 3 << 20,
         "budget": 8 << 20, "budget_source": "env",
         "headroom": (8 << 20) - (3 << 20),
         "planes": {"evbuf": 600000, "model": 400000},
         "peaks": {"output": 1 << 20, "rollback": 0, "transient": 100000}},
        {"type": "mem", "event": "downshift", "budget": 8 << 20,
         "estimated_peak": 2 << 20,
         "actions": [{"action": "drop_rollback"}]},
        {"type": "mem", "event": "final", "peak_in_use": 2500000,
         "estimated_peak": 3 << 20},
    ]
    summary = summarize(recs)
    out = capsys.readouterr().out
    assert "== memory (estimate vs device) ==" in out
    assert "reported peak in use" in out
    assert "downshift applied: drop_rollback" in out
    assert summary["memory"]["estimated_peak"] == 3 << 20
    assert summary["memory"]["peak_in_use"] == 2500000
    assert summary["memory"]["budget"] == 8 << 20
    # mem fields never leak into ring percentile stats (their own type)
    assert "ring" not in summary


def test_memprobe_audit_exit_codes(tmp_path):
    from shadow1_tpu.tools import memprobe

    cfg = _write_cfg(tmp_path)
    row = memprobe.audit_config(cfg)
    assert row["ok"], row
    assert abs(row["ratio"] - 1.0) <= mem.AUDIT_TOLERANCE
