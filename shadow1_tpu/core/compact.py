"""Active-host compaction — sparse windows run on a narrow static bucket.

The batched engine pays every inner round as a full [C, H] tensor pass no
matter how few hosts execute events; on the sparse ladder rungs that is the
dominant waste (rung-3 Tor: mean 47 of 1000 hosts active per window,
p99 = 284 — tools/activeprobe.py). The reference's eager scheduler gets
sparsity for free by only visiting queued events
(src/main/core/scheduler/scheduler-policy-host-steal.c steals only
non-empty host queues); this module is the batched equivalent.

Exactness argument: a window's active-host set is CLOSED under round
execution — handlers only self-push (timers, app wakeups, TX resume all
target the executing host) and cross-host packets defer to the window-end
exchange by the conservative-window construction — so hosts with no
eligible event at window start stay event-free all window. Gathering the
active columns, running the identical round program at bucket width, and
scattering back is therefore the identity on every inactive host and the
identical computation on every active one: pops, handler order, RNG draws
(keyed by GLOBAL host id), and metric sums are bit-equal to the full-width
path. Windows whose active count exceeds the bucket run the full-width
branch (a ``lax.cond``), so the knob is purely a performance choice.

Padding lanes (bucket wider than the active count) clone the last host's
columns but are forced event-free, so they never pop, and masked handlers
never write them; duplicate-clone lanes are excluded from the scatter-back
(``pos`` maps each host to its FIRST lane). All gathers ride
``take``/``searchsorted``; the scatter-back is a lane-axis gather by
inverse permutation + ``where`` — no dynamic scatter (core/dense.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from shadow1_tpu.consts import K_NONE
from shadow1_tpu.core.events import I32_FREE

# Ctx fields indexed by LOCAL host lane (everything else — vertex tables,
# host_vertex (global-id-indexed), scalars, static flags — stays as is).
_CTX_HOST_FIELDS = (
    "hosts", "bw_up", "bw_dn", "fault_down", "fault_up", "cpu_cost",
    "tx_qlen_ns", "rx_qlen_ns", "aqm_min_ns", "aqm_span_ns", "aqm_pmax_thr",
)


def active_mask(evbuf, win_end) -> jnp.ndarray:
    """bool [H]: host has ≥1 eligible event this window (= will pop).

    Runs after the window-start rebase (core/engine.py window_step), so the
    maintained per-host eligible counters are current — an [H]-vector read,
    no [C, H] plane scan (core/events.py n_elig)."""
    del win_end  # pinned at rebase time (evbuf.u32)
    return evbuf.n_elig > 0


def compact_perm(active: jnp.ndarray, cap: int):
    """Bucket permutation for the active set.

    Returns (idx [cap], pos [H], lane_pad [cap]):
    * ``idx``  — host id occupying each bucket lane (clipped into range;
      padding lanes clone host H−1),
    * ``pos``  — bucket lane of each host (valid where ``active``; for a
      cloned host it is the FIRST — real — lane),
    * ``lane_pad`` — True on padding lanes (no real host).
    """
    h = active.shape[0]
    iota = jnp.arange(h, dtype=jnp.int32)
    (key_s,) = jax.lax.sort((jnp.where(active, iota, h),))
    pos = jnp.searchsorted(key_s, iota).astype(jnp.int32)   # first occurrence
    idx = key_s[:cap]
    lane_pad = idx >= h
    return jnp.minimum(idx, h - 1), pos, lane_pad


def _gather_tree(tree, idx, h: int):
    """Gather the host (last) axis of every [*, H] leaf down to the bucket."""
    def g(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[-1] == h:
            return jnp.take(x, idx, axis=-1)
        return x
    return jax.tree.map(g, tree)


def _scatter_tree(full, comp, pos, active, h: int):
    """Inverse of ``_gather_tree``: active hosts read their bucket lane."""
    def s(xf, xc):
        if hasattr(xf, "ndim") and xf.ndim >= 1 and xf.shape[-1] == h:
            back = jnp.take(xc, pos, axis=-1)
            am = active.reshape((1,) * (xf.ndim - 1) + (h,))
            return jnp.where(am, back, xf)
        return xc  # scalars/metrics: the round loop's value wins
    return jax.tree.map(s, full, comp)


def compact_ctx(ctx, idx, cap: int):
    """The bucket-width view of a Ctx: per-host tables gathered, n_hosts=cap."""
    repl = {"n_hosts": cap}
    for f in _CTX_HOST_FIELDS:
        v = getattr(ctx, f)
        if v is not None:
            repl[f] = jnp.take(v, idx, axis=-1)
    return dataclasses.replace(ctx, **repl)


def compact_window_rounds(st, ctx, handlers, make_handlers, run_rounds,
                          win_end, cap: int):
    """Run one window's inner rounds, compacted when the active set fits.

    ``run_rounds(st, ctx, handlers, win_end) -> (st, cap_hit)`` is the
    engine's full-width round loop; it is reused verbatim at bucket width.
    ``handlers`` is the engine's existing full-width handler dict (the
    fallback branch); ``make_handlers(ctx)`` rebuilds the handler closures
    over the gathered ctx tensors (model handler builders are pure
    trace-time functions)."""
    h = ctx.n_hosts
    active = active_mask(st.evbuf, win_end)
    n_active = active.sum(dtype=jnp.int32)
    # (The demanded-fill gauge ``compact_max_fill`` is recorded by
    # window_step for every window, compaction on or off — keeping the
    # compacted and plain engines' states bit-identical.)

    def full_branch(st):
        return run_rounds(st, ctx, handlers, win_end)

    def compact_branch(st):
        idx, pos, lane_pad = compact_perm(active, cap)
        ctx_c = compact_ctx(ctx, idx, cap)
        handlers_c = make_handlers(ctx_c)
        host_state = (st.evbuf, st.outbox, st.model, st.cpu_busy)
        evbuf_c, outbox_c, model_c, busy_c = _gather_tree(host_state, idx, h)
        # Padding/clone lanes must never pop: force them event-free.
        evbuf_c = evbuf_c._replace(
            kind=jnp.where(lane_pad[None, :], K_NONE, evbuf_c.kind),
            t32=jnp.where(lane_pad[None, :], I32_FREE, evbuf_c.t32),
            # A clone lane with a live n_elig copy would spin the round
            # loop (it can never pop, its count never drains).
            n_elig=jnp.where(lane_pad, 0, evbuf_c.n_elig),
        )
        st_c = st._replace(evbuf=evbuf_c, outbox=outbox_c, model=model_c,
                           cpu_busy=busy_c)
        st_c, cap_hit = run_rounds(st_c, ctx_c, handlers_c, win_end)
        comp = (st_c.evbuf, st_c.outbox, st_c.model, st_c.cpu_busy)
        evbuf_f, outbox_f, model_f, busy_f = _scatter_tree(
            host_state, comp, pos, active, h
        )
        st = st_c._replace(evbuf=evbuf_f, outbox=outbox_f, model=model_f,
                           cpu_busy=busy_f)
        return st, cap_hit

    return jax.lax.cond(n_active <= cap, compact_branch, full_branch, st)
