"""Batched event buffers — the tensorized per-host priority queues.

The reference gives every host a binary-heap event queue and a locked async
queue for cross-thread pushes (src/main/core/scheduler/*,
src/main/utility/priority-queue.c). Here all H queues live in one set of
fixed-capacity SoA tensors ``[C, H]`` (slot-major, host-minor — see
core/dense.py for why); pop-min is a chain of masked min-reductions, local
push writes the first free slot, and cross-host delivery is a sorted batch
merge performed once per conservative window (SURVEY §7.1).

Total event order matches the reference's (time, host, seq) comparator
(src/main/core/work/event.c): within a host, events pop by (time, tb) where
``tb`` is a deterministic tie-break assigned at creation — local pushes use
the host's own monotone counter, delivered packets use
``consts.packet_tb(src_host, src_pkt_counter)``. Both engines compute the
same keys, so event order is engine-independent.

int32 round path (round-5 rewrite): the chip has no native int64 — every
i64 op is a 3-6x-cost emulation (docs/PERF.md) — and the inner round loop
used to run ~15 full-plane i64 passes per pop/push. The buffer therefore
carries the pop keys twice:

* ``time``  i64 [C, H] — the authoritative absolute event time, written on
  push/delivery, READ ONLY at window granularity (rebase, pre_window);
* ``t32``   i32 [C, H] — ``clamp(time - epoch, 0, I32_HORIZON)`` where
  ``epoch`` advances to the window start each window (``rebase``). Pop
  eligibility/ordering runs entirely on t32: exact for every eligible
  event because eligible means ``time < win_end = epoch + W`` and the
  engine validates ``W < 2**31`` ns, so eligible rebased times never
  clamp; far-future events saturate at I32_HORIZON ≥ W and stay
  ineligible until the epoch catches up;
* ``tb_hi``/``tb_lo`` i32 [C, H] — the i64 tie-break split into an
  order-preserving (hi, lo) pair (``lo`` is sign-flipped so SIGNED i32
  comparison matches the unsigned low-word order). Pop's tie-break is a
  2-step lexicographic min over these planes: no i64 anywhere per round.

Pop-min exploits that the (time, tb) key pair is UNIQUE per host — tb
values never repeat within a host (local pushes consume a monotone counter;
packet tbs embed the unique (src, src_ctr); the two ranges are disjoint via
TB_PACKET_BASE) — so "the" minimum slot is an equality one-hot against the
reduced min keys, and payload extraction is a masked sum. No dynamic
scatters, no per-slot argmin/cumsum in the round path (core/dense.py).
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple

import jax
import jax.numpy as jnp

from shadow1_tpu.consts import K_NONE, NP
from shadow1_tpu.core.dense import extract_col, first_true

# Trace-time push-implementation selector (EngineParams.push_impl). Handlers
# throughout the model layers call push_local/push_back directly, so the
# engine scopes this around its window-step tracing instead of threading an
# argument through every handler signature. Tracing is single-threaded
# Python, so a plain module global scoped by the context manager is exact.
_PUSH_IMPL = "xla"


@contextlib.contextmanager
def push_impl_ctx(impl: str):
    global _PUSH_IMPL
    prev, _PUSH_IMPL = _PUSH_IMPL, impl
    try:
        yield
    finally:
        _PUSH_IMPL = prev

I64_MAX = jnp.iinfo(jnp.int64).max
I32_MAX = jnp.iinfo(jnp.int32).max
# Free/ineligible sentinel for the t32 plane; live far-future events clamp
# to I32_HORIZON. Both are ≥ any valid until32 (window < 2**31 — validated
# by the engine), so neither can pop.
I32_FREE = I32_MAX
I32_HORIZON = I32_MAX - 1
# Lower clamp: PAST-DUE events (left eligible by a max_rounds cap-hit
# window) rebase to NEGATIVE t32 so their (time, tb) order and exact
# reconstructed times survive into the next window — they sort before
# every in-window event, as the i64 semantics require. Only a backlog
# older than ~2.1 s would hit this clamp (and lose exactness); a cap-hit
# run that deep is already flagged by the round_cap_hits metric.
I32_PASTDUE = -I32_HORIZON
_SIGN = jnp.int32(-0x80000000)  # == 1 << 31 as a signed bit pattern


def tb_split(tb) -> tuple[jnp.ndarray, jnp.ndarray]:
    """i64 tie-break → (hi, lo) i32 planes, SIGNED-order-preserving.

    tb is always ≥ 0 and < 2**62 (consts.packet_tb / self_ctr), so
    hi = tb >> 32 fits positive i32 and orders first; lo is the low 32 bits
    with the sign bit flipped so signed i32 comparison equals unsigned
    low-word comparison."""
    hi = (tb >> 32).astype(jnp.int32)
    lo = (tb & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32) ^ _SIGN
    return hi, lo


def tb_join(hi, lo) -> jnp.ndarray:
    """Inverse of tb_split."""
    lo_u = (lo ^ _SIGN).astype(jnp.uint32).astype(jnp.int64)
    return (hi.astype(jnp.int64) << 32) | lo_u


def _t32_of(time, epoch) -> jnp.ndarray:
    """Rebased saturating pop key; exact (and order-exact) for times within
    (epoch - 2**31 + 2, epoch + 2**31 - 1)."""
    return jnp.clip(time - epoch, I32_PASTDUE, I32_HORIZON).astype(jnp.int32)


class EventBuf(NamedTuple):
    """Every [C, H] plane is i32 — the chip has no native i64, and this is
    also the precondition for the Pallas fused-pop kernel (core/popk.py).
    Absolute event times live as a tb_split-encoded (hi, lo) pair,
    reassembled only at window granularity (rebase, pre_window)."""

    time_hi: jnp.ndarray   # i32 [C, H] absolute time, high word
    time_lo: jnp.ndarray   # i32 [C, H] absolute time, low word (sign-flip)
    t32: jnp.ndarray       # i32 [C, H] rebased pop key (I32_FREE = empty)
    tb_hi: jnp.ndarray     # i32 [C, H] tie-break high word
    tb_lo: jnp.ndarray     # i32 [C, H] tie-break low word (sign-flipped)
    kind: jnp.ndarray      # i32 [C, H] (K_NONE = free slot)
    p: jnp.ndarray         # i32 [NP, C, H] payload columns
    self_ctr: jnp.ndarray  # i64 [H] counter for locally-pushed tb keys
    epoch: jnp.ndarray     # i64 scalar — t32 = clamp(time - epoch)
    # Running per-host count of events eligible before ``u32`` — maintained
    # incrementally by push/pop (cheap [H]-vector arithmetic) so the round
    # loop's continue-condition and the compaction active mask read a
    # vector instead of re-scanning the [C, H] planes every round. Only
    # valid between a ``rebase`` (which recomputes it and pins ``u32``)
    # and the next window-granularity mutation (deliver_batch/pre_window
    # rewrites leave it stale, exactly like t32).
    n_elig: jnp.ndarray    # i32 [H]
    u32: jnp.ndarray       # i32 scalar eligibility bound of n_elig

    def abs_time(self) -> jnp.ndarray:
        """i64 [C, H] absolute times (window-granularity readers only)."""
        return tb_join(self.time_hi, self.time_lo)


class Popped(NamedTuple):
    mask: jnp.ndarray   # bool [H] — host had an eligible event this round
    time: jnp.ndarray   # i64 [H] absolute
    kind: jnp.ndarray   # i32 [H] (K_NONE where ~mask)
    p: jnp.ndarray      # i32 [NP, H]
    tb: jnp.ndarray     # i64 [H] original tie-break (for cpu-model requeue)


def evbuf_init(n_hosts: int, cap: int) -> EventBuf:
    thi, tlo = tb_split(jnp.asarray(I64_MAX, jnp.int64))
    return EventBuf(
        time_hi=jnp.full((cap, n_hosts), thi, jnp.int32),
        time_lo=jnp.full((cap, n_hosts), tlo, jnp.int32),
        t32=jnp.full((cap, n_hosts), I32_FREE, jnp.int32),
        tb_hi=jnp.zeros((cap, n_hosts), jnp.int32),
        tb_lo=jnp.zeros((cap, n_hosts), jnp.int32),
        kind=jnp.full((cap, n_hosts), K_NONE, jnp.int32),
        p=jnp.zeros((NP, cap, n_hosts), jnp.int32),
        self_ctr=jnp.zeros(n_hosts, jnp.int64),
        epoch=jnp.zeros((), jnp.int64),
        n_elig=jnp.zeros(n_hosts, jnp.int32),
        u32=jnp.asarray(I32_HORIZON, jnp.int32),
    )


def rebase(buf: EventBuf, epoch, until=None) -> EventBuf:
    """Advance the t32 plane's epoch (once per window, off the round path).

    Recomputes t32 from the authoritative absolute times — this is also
    what makes window-end ``deliver_batch`` and pre-window event rewrites
    free to skip t32 maintenance: any staleness is repaired here before the
    next round loop reads it. ``until`` (default: the saturation horizon)
    pins the eligibility bound the ``n_elig`` counters are maintained
    against — the engine passes win_end."""
    epoch = jnp.asarray(epoch, jnp.int64)
    t32 = jnp.where(
        buf.kind != K_NONE, _t32_of(buf.abs_time(), epoch), I32_FREE
    )
    u32 = (jnp.asarray(I32_HORIZON, jnp.int32) if until is None
           else jnp.clip(jnp.asarray(until, jnp.int64) - epoch, 0,
                         I32_HORIZON).astype(jnp.int32))
    n_elig = (t32 < u32).sum(axis=0, dtype=jnp.int32)
    return buf._replace(t32=t32, epoch=epoch, n_elig=n_elig, u32=u32)


def push_local(buf: EventBuf, mask, time, kind, p) -> tuple[EventBuf, jnp.ndarray]:
    """Push one event per host where ``mask``; tb from the host's own counter.

    Returns (buf, overflow_mask). Overflowing events are dropped and must be
    surfaced as a metric — capacity is an experiment knob (SURVEY §7.3.2).
    """
    if _PUSH_IMPL == "pallas":
        from shadow1_tpu.core.popk import push_local_fused

        return push_local_fused(buf, mask, time, kind, p)
    has_free, first = first_true(buf.kind == K_NONE)
    ok = mask & has_free
    w = first & ok[None, :]
    time = jnp.asarray(time, jnp.int64)
    thi, tlo = tb_split(time)
    t32v = _t32_of(time, buf.epoch)
    hi, lo = tb_split(buf.self_ctr)
    buf = buf._replace(
        time_hi=jnp.where(w, thi[None, :], buf.time_hi),
        time_lo=jnp.where(w, tlo[None, :], buf.time_lo),
        t32=jnp.where(w, t32v[None, :], buf.t32),
        tb_hi=jnp.where(w, hi[None, :], buf.tb_hi),
        tb_lo=jnp.where(w, lo[None, :], buf.tb_lo),
        kind=jnp.where(w, jnp.asarray(kind, jnp.int32)[None, :], buf.kind),
        p=jnp.where(w[None], jnp.asarray(p, jnp.int32)[:, None, :], buf.p),
        self_ctr=buf.self_ctr + ok.astype(jnp.int64),
        n_elig=buf.n_elig + (ok & (t32v < buf.u32)).astype(jnp.int32),
    )
    return buf, mask & ~has_free


def push_back(buf: EventBuf, mask, time, tb, kind, p) -> tuple[EventBuf, jnp.ndarray]:
    """Re-insert a popped event with its ORIGINAL tie-break key.

    Used by the virtual-CPU model when a busy host's event execution slips
    past the window boundary (docs/SEMANTICS.md §cpu): the event re-enters
    at (eff_time, original tb), so its order among same-time events is
    preserved. Does not advance self_ctr."""
    if _PUSH_IMPL == "pallas":
        from shadow1_tpu.core.popk import push_back_fused

        return push_back_fused(buf, mask, time, tb, kind, p)
    has_free, first = first_true(buf.kind == K_NONE)
    ok = mask & has_free
    w = first & ok[None, :]
    time = jnp.asarray(time, jnp.int64)
    thi, tlo = tb_split(time)
    t32v = _t32_of(time, buf.epoch)
    hi, lo = tb_split(jnp.asarray(tb, jnp.int64))
    buf = buf._replace(
        time_hi=jnp.where(w, thi[None, :], buf.time_hi),
        time_lo=jnp.where(w, tlo[None, :], buf.time_lo),
        t32=jnp.where(w, t32v[None, :], buf.t32),
        tb_hi=jnp.where(w, hi[None, :], buf.tb_hi),
        tb_lo=jnp.where(w, lo[None, :], buf.tb_lo),
        kind=jnp.where(w, jnp.asarray(kind, jnp.int32)[None, :], buf.kind),
        p=jnp.where(w[None], jnp.asarray(p, jnp.int32)[:, None, :], buf.p),
        n_elig=buf.n_elig + (ok & (t32v < buf.u32)).astype(jnp.int32),
    )
    return buf, mask & ~has_free


def until32(buf: EventBuf, until) -> jnp.ndarray:
    """Rebased eligibility bound. Exact when until - epoch <= I32_HORIZON
    = 2**31 - 2 (the engine's window-size validation guarantees it for
    win_end bounds: window < 2**31 - 1, config/compiled.py)."""
    return jnp.clip(until - buf.epoch, 0, I32_HORIZON).astype(jnp.int32)


def pop_until(buf: EventBuf, until, extract: str = "sum") -> tuple[EventBuf, Popped]:
    """Per-host pop of the minimum-(time, tb) event with time < until.

    A 3-step lexicographic masked min over the slot (sublane) axis — t32,
    then tb_hi among time-ties, then tb_lo — ending in an equality one-hot;
    exact because (time, tb) is unique per host (module docstring). All
    i32: the only i64 work is the [H]-vector reconstruction of the popped
    absolute time/tb.

    ``extract`` selects how kind/payload leave the buffer — "sum" (masked
    sum over the one-hot) or "gather" (one-hot → index → take_along_axis).
    Both are exact; which is faster is a backend/layout question
    (EngineParams.pop_extract, docs/PERF.md round-5)."""
    assert extract in ("sum", "gather"), f"bad pop_extract {extract!r}"
    u32 = until32(buf, until)
    elig = (buf.kind != K_NONE) & (buf.t32 < u32)
    t_masked = jnp.where(elig, buf.t32, I32_FREE)
    min_t = t_masked.min(axis=0)
    mask = min_t < u32
    tie = elig & (t_masked == min_t[None, :])
    hi_masked = jnp.where(tie, buf.tb_hi, I32_MAX)
    min_hi = hi_masked.min(axis=0)
    tie2 = tie & (hi_masked == min_hi[None, :])
    lo_masked = jnp.where(tie2, buf.tb_lo, I32_MAX)
    min_lo = lo_masked.min(axis=0)
    sel = tie2 & (lo_masked == min_lo[None, :])    # one-hot per active host
    if extract == "gather":
        from shadow1_tpu.core.dense import first_true_idx, get_col

        _, slot = first_true_idx(sel)
        kind = jnp.where(mask, get_col(buf.kind, slot), K_NONE)
        pay = jnp.where(mask[None, :], get_col(buf.p, slot), 0)
    else:
        kind = extract_col(sel, buf.kind)
        pay = extract_col(sel, buf.p)
    ev = Popped(
        mask=mask,
        time=jnp.where(mask, buf.epoch + min_t.astype(jnp.int64), 0),
        kind=kind,
        p=pay,
        tb=jnp.where(mask, tb_join(min_hi, min_lo), 0),
    )
    buf = buf._replace(
        kind=jnp.where(sel, K_NONE, buf.kind),
        t32=jnp.where(sel, I32_FREE, buf.t32),
        n_elig=buf.n_elig - mask.astype(jnp.int32),
    )
    return buf, ev


def any_eligible(buf: EventBuf, until) -> jnp.ndarray:
    """True if any host still has an eligible event. Reads the maintained
    [H] counters, NOT the [C, H] planes — exact whenever ``until`` matches
    the bound pinned by the last ``rebase`` (the engine always passes
    win_end to both; arbitrary other ``until`` values are not supported
    here and must scan the planes directly)."""
    del until  # pinned at rebase time (buf.u32)
    return (buf.n_elig > 0).any()


def evbuf_fill(buf: EventBuf) -> jnp.ndarray:
    """Occupancy gauge: pending events on the busiest host, i64 scalar.

    One [C, H] plane pass — read at WINDOW granularity only (the engine's
    window-end gauge update and the telemetry ring share one evaluation),
    never in the round loop. Slot-layout-independent: it counts occupied
    slots, so a cap migration (tune/resize.py) cannot change it."""
    return (buf.kind != K_NONE).sum(axis=0, dtype=jnp.int32).max().astype(jnp.int64)


def deliver_batch(buf: EventBuf, dst, time, tb, kind, p, mask) -> tuple[EventBuf, jnp.ndarray]:
    """Merge N externally-created events into their hosts' buffers.

    The tensor analogue of the reference's locked cross-thread event push
    (src/main/utility/async-priority-queue.c), restructured gather-style for
    TPU: sort packets by destination (masked ones to the end), then each
    host's r-th free slot *gathers* the r-th packet of its segment
    (seg_start[h] + r). All reads are sorted gathers; the only writes are
    dense ``where``s. Packet r per host is the r-th in flat source order,
    and free slots fill in ascending slot index. Slot ASSIGNMENT is an
    engine-internal layout choice; pop order is decided purely by the
    (time, tb) keys, so it is engine- and layout-independent.
    Returns (buf, n_overflow). ``p`` is [NP, N].

    Runs at window granularity only, so it writes the authoritative i64
    time plane and leaves t32 stale — the window-start ``rebase`` repairs
    it before any round reads it.

    Overflow-victim selection is layout-defined: when a destination's free
    slots run out, which packets drop depends on flat source order (since
    the [C, H] rewrite: slot-major), so it differs across engines and
    layout revisions. Cross-engine parity is guaranteed only for runs with
    ``ev_overflow == 0`` — the oracle harness asserts this
    (docs/SEMANTICS.md "Bounds and overflow").

    TPU tuning: the sort key packs (dst, flat index) into one integer so an
    *unstable* single-key sort is deterministic (keys are distinct and the
    packing preserves source order within a destination); segment bounds
    come from one H+1-point searchsorted; the 15 payload rows (time split
    into i32 halves, the pre-split tb planes, kind, p) ride one stacked
    gather instead of four. This runs once per window, so its cumsum over
    the slot axis is off the round path.
    """
    cap, n_hosts = buf.kind.shape
    n = dst.shape[0]
    nb = max((n - 1).bit_length(), 1)
    wide = (n_hosts + 1) << nb > 2**31 - 1
    kdt = jnp.int64 if wide else jnp.int32
    key = (jnp.where(mask, dst, n_hosts).astype(kdt) << nb) | jnp.arange(n, dtype=kdt)
    (key_s,) = jax.lax.sort((key,), is_stable=False)
    dst_s = (key_s >> nb).astype(jnp.int32)
    hs = jnp.arange(n_hosts + 1, dtype=jnp.int32)
    seg = jnp.searchsorted(dst_s, hs, side="left")
    n_in = (seg[1:] - seg[:-1]).astype(jnp.int32)            # [H]
    free = buf.kind == K_NONE                                # [C, H]
    free_rank = (jnp.cumsum(free, axis=0) - free).astype(jnp.int32)
    take = free & (free_rank < n_in[None, :])                # slot receives one
    src = jnp.minimum(seg[:-1][None, :] + free_rank, n - 1)
    oidx = (key_s & ((1 << nb) - 1)).astype(jnp.int32)[src]  # [C, H] flat idx
    thi, tlo = tb_split(jnp.asarray(time, jnp.int64))
    bhi, blo = tb_split(jnp.asarray(tb, jnp.int64))
    stacked = jnp.concatenate(
        [
            jnp.stack([thi, tlo, bhi, blo, kind]),
            p,
        ]
    )                                                        # [5+NP, N] i32
    g = stacked[:, oidx]                                     # [5+NP, C, H]
    buf = buf._replace(
        time_hi=jnp.where(take, g[0], buf.time_hi),
        time_lo=jnp.where(take, g[1], buf.time_lo),
        tb_hi=jnp.where(take, g[2], buf.tb_hi),
        tb_lo=jnp.where(take, g[3], buf.tb_lo),
        kind=jnp.where(take, g[4], buf.kind),
        p=jnp.where(take[None], g[5:], buf.p),
    )
    free_cnt = free.sum(axis=0, dtype=jnp.int32)
    n_over = mask.sum() - jnp.minimum(n_in, free_cnt).sum()
    return buf, n_over


def _lo(x):
    return (x & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)


def _hi(x):
    return ((x >> 32) & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)


def _join(lo, hi):
    return (
        lo.astype(jnp.uint32).astype(jnp.uint64)
        | (hi.astype(jnp.uint32).astype(jnp.uint64) << jnp.uint64(32))
    ).astype(jnp.int64)
