"""Observability — the heartbeat metrics stream.

The reference's Tracker logs per-host statistics at a configured interval
and its log records carry sim-time + wall-time so the sim/wall ratio is
derivable (src/main/host/tracker.c, SURVEY §5). The batched analogue: run
the window loop in chunks and emit one structured heartbeat per chunk with
the metric deltas — events/sec, packets, retransmits, overflow counters —
without ever synchronizing device→host inside a window.

Layered on top (round 6, docs/OBSERVABILITY.md): when the engine state
carries an on-device telemetry ring (EngineParams.metrics_ring), the
heartbeat also drains the ring's per-window rows at each chunk boundary —
the true per-window time series underneath the chunk averages — and a
telemetry.PhaseProfiler can be attached to time the compile / run-chunk /
drain / checkpoint phases into a Chrome trace.
"""

from __future__ import annotations

import json
import os
import sys
import time

from shadow1_tpu.ckpt import run_chunked
from shadow1_tpu.consts import SEC
from shadow1_tpu.telemetry import (
    PH_CHECKPOINT,
    PH_COMPILE,
    PH_DRAIN,
    maybe_span,
    normalize,
)


def _metrics_mapping(metrics) -> dict:
    """Engine metrics → plain int dict (Metrics NamedTuple or already a dict
    — alternate engines need not mimic the NamedTuple)."""
    d = metrics if isinstance(metrics, dict) else metrics._asdict()
    return {k: int(v) for k, v in d.items()}


class Heartbeat:
    """Collects per-chunk metric deltas; writes JSON lines to ``stream``.

    Metric dicts are normalized through the telemetry registry, so engines
    whose metrics lack canonical fields (cpu_engine, future models) reuse
    the heartbeat unchanged — missing counters read as 0, never KeyError.
    """

    def __init__(self, engine, stream=None, label: str = "heartbeat",
                 initial_state=None, profiler=None,
                 emit_heartbeat: bool = True, emit_ring: bool = True,
                 guard=None):
        self.engine = engine
        self.stream = stream if stream is not None else sys.stderr
        self.label = label
        self.profiler = profiler
        self.guard = guard  # txn.OverflowGuard — source of the retries block
        self.emit_heartbeat = emit_heartbeat
        self.emit_ring = emit_ring
        self.t_start = time.perf_counter()
        self.t_last = self.t_start
        # Seed the baseline from a resumed state so the first delta covers
        # only this invocation, not the checkpointed history.
        self.last: dict[str, int] = (
            normalize(_metrics_mapping(initial_state.metrics))
            if initial_state is not None else {}
        )
        # First ring window still undrained (resume-aware like ``last``).
        self._ring_next: int = self.last.get("windows", 0)
        # Same cursor for the flow-probe ring (telemetry/probes.py).
        self._probe_next: int = self.last.get("windows", 0)
        # And for the link accumulator (telemetry/links.py) — link records
        # are cumulative snapshots, so the cursor only suppresses re-drains
        # of already-emitted boundaries on resume.
        self._link_next: int = self.last.get("windows", 0)
        self.records: list[dict] = []
        self.ring_records: list[dict] = []
        self.flow_records: list[dict] = []
        self.link_records: list[dict] = []

    def _emit(self, rec: dict) -> None:
        if self.stream:
            print(json.dumps(rec), file=self.stream, flush=True)

    def __call__(self, st, done_windows: int) -> None:
        now = time.perf_counter()
        # The ONE device→host fetch of the chunk (never inside a window).
        with maybe_span(self.profiler, PH_DRAIN):
            m = normalize(_metrics_mapping(st.metrics))
            ring_recs = self._drain_ring(st)
            flow_recs = self._drain_probes(st)
            link_recs = self._drain_links(st)
        delta = {k: v - self.last.get(k, 0) for k, v in m.items()}
        dt = now - self.t_last
        sim_ns = int(st.win_start)  # the true sim clock (resume-aware)
        d_windows = delta.get("windows", 0)
        rec = {
            "type": self.label,
            "sim_time_s": round(sim_ns / SEC, 6),
            "wall_s": round(now - self.t_start, 3),
            "windows": done_windows,
            "events_per_sec": round(delta.get("events", 0) / dt, 1)
            if dt > 0 else None,
            "sim_per_wall": round(
                (getattr(self.engine, "window", 0) * d_windows / SEC) / dt, 4)
            if dt > 0 else None,
            # Occupancy: how many handler rounds the busiest host forced per
            # window this chunk (the per-window fixed-cost multiplier).
            "rounds_per_window": round(delta.get("rounds", 0) / d_windows, 2)
            if d_windows else None,
            "delta": delta,
        }
        # Drop accounting: the nine ways an event/packet can be discarded,
        # grouped under one structured block (with chunk deltas) instead of
        # scattered through ``delta`` — the shape heartbeat_report's
        # drop-reason table and alerting consume. Always present: an
        # all-zero block is the explicit "nothing dropped" signal.
        from shadow1_tpu.telemetry.registry import DROP_FIELDS

        drops = {f: delta.pop(f, 0) for f in DROP_FIELDS}
        rec["drops"] = {"total": sum(drops.values()), **drops}
        # Overflow-retry plane (txn.OverflowGuard): host-side counters, so
        # they never appear in engine deltas (normalize injects zeros —
        # dropped here); when the guard has retried, a ``retries`` block
        # carries the cumulative counters plus the live (grown) caps.
        from shadow1_tpu.telemetry.registry import HOST_FIELDS

        for f in HOST_FIELDS:
            delta.pop(f, None)
        if self.guard is not None and self.guard.chunk_retries:
            rec["retries"] = self.guard.report()
        # Fault plane: when churn/outage activity happened this chunk, a
        # ``faults`` block surfaces it directly (restart resets plus the
        # fault-induced rows of the drops table) — docs/OBSERVABILITY.md.
        restarts = delta.pop("host_restarts", 0)
        fault_drops = {k: drops[k] for k in
                       ("down_events", "down_pkts", "link_down_pkts")
                       if k in drops}
        if restarts or any(fault_drops.values()):
            rec["faults"] = {"host_restarts": restarts, **fault_drops}
        # Wasted-work accounting (performance attribution plane): the three
        # per-window boundary samples summed over this chunk, with the
        # denominators a consumer needs to turn them into utilization
        # fractions (n_hosts, the chunk's window count). Running sums, not
        # rates — they leave ``delta`` like the fill gauges and ride a
        # ``work`` block; tools/heartbeat_report.py's work-efficiency
        # section consumes it (and reads n_hosts from here for the
        # per-window ring fractions).
        work = {f: delta.pop(f, 0) for f in
                ("active_hosts", "elig_events", "outbox_hosts")}
        n_hosts = getattr(getattr(self.engine, "exp", None), "n_hosts", None)
        if any(work.values()):
            rec["work"] = dict(work)
            if n_hosts:
                rec["work"]["n_hosts"] = n_hosts
                if d_windows:
                    rec["work"]["active_frac"] = round(
                        work["active_hosts"] / (d_windows * n_hosts), 6)
        # Capacity occupancy: run-max fill gauges against their caps — the
        # data the cap controller and tools/captune.py size caps from.
        # High-water marks, not rates: they leave ``delta`` and ride a
        # ``fill`` block with the caps they are measured against.
        params = getattr(self.engine, "params", None)
        fill = {}
        for gauge, cap_field in (("ev_max_fill", "ev_cap"),
                                 ("ob_max_fill", "outbox_cap"),
                                 ("compact_max_fill", "compact_cap")):
            if delta.pop(gauge, 0) or m.get(gauge):
                fill[gauge] = m.get(gauge)
                if params is not None:
                    fill[cap_field] = getattr(params, cap_field)
        if fill:
            rec["fill"] = fill
        # Exchange occupancy (sharded engine): how close the busiest
        # all_to_all bucket has come to its cap — the datum that pins
        # x2x_cap rationally (a high-water near cap predicts overflow).
        cap = getattr(self.engine, "_x2x_cap", None)
        if cap:
            rec["x2x"] = {
                "max_fill": m.get("x2x_max_fill"),
                "cap": cap,
                "full_cap": getattr(self.engine, "_full_cap", None),
            }
            delta.pop("x2x_max_fill", None)  # a high-water mark, not a rate
        self.records.append(rec)
        if self.emit_heartbeat:
            self._emit(rec)
        for r in ring_recs:
            self.ring_records.append(r)
            if self.emit_ring:
                self._emit(r)
        for r in flow_recs:
            self.flow_records.append(r)
            if self.emit_ring:
                self._emit(r)
        for r in link_recs:
            self.link_records.append(r)
            if self.emit_ring:
                self._emit(r)
        self.t_last = now
        self.last = m

    def _drain_ring(self, st) -> list[dict]:
        """Per-window ring rows accumulated since the last chunk boundary."""
        if getattr(st, "telem", None) is None:
            return []
        from shadow1_tpu.telemetry.ring import drain_ring

        recs = drain_ring(st, self.engine.window, start=self._ring_next)
        self._ring_next = int(st.metrics.windows)
        return recs

    def _drain_probes(self, st) -> list[dict]:
        """Per-window flow-probe rows since the last chunk boundary (solo
        engines; the fleet engine's drain_rings handles its [E,...] ring)."""
        if getattr(st, "probes", None) is None:
            return []
        from shadow1_tpu.telemetry.probes import drain_probes

        probes = getattr(getattr(self.engine, "params", None), "probes", ())
        recs = drain_probes(st, self.engine.window, probes,
                            start=self._probe_next)
        self._probe_next = int(st.metrics.windows)
        return recs

    def _drain_links(self, st) -> list[dict]:
        """Cumulative per-edge link snapshot at this chunk boundary (solo
        engines; the fleet engine's drain_rings handles its [E,...]
        accumulator)."""
        if getattr(st, "links", None) is None:
            return []
        from shadow1_tpu.telemetry.links import drain_links

        recs = drain_links(st, self.engine.window, start=self._link_next)
        self._link_next = int(st.metrics.windows)
        return recs


def run_with_heartbeat(engine, st=None, n_windows=None, every_windows=None,
                       stream=None, ckpt_path=None, ckpt_every_s=120.0,
                       profiler=None, emit_heartbeat=True, emit_ring=True,
                       controller=None, guard=None, selfcheck=False,
                       ckpt_keep=3, drain=None):
    """Run the engine emitting a heartbeat every ``every_windows`` windows.

    With ``ckpt_path``, engine state is snapshotted there at heartbeat
    boundaries (throttled to ~``ckpt_every_s`` of wall) plus a ``.progress``
    sidecar with the completed window count — so a device fault mid-run
    (the tunneled TPU wedges whole processes: round-4 postmortem, hb5.log)
    loses at most the windows since the last save, and a supervisor can
    respawn a fresh process that resumes from the snapshot (cli.py --ckpt).
    Determinism makes the resumed run bit-identical to an uninterrupted one.
    Snapshots rotate through a ``ckpt_keep``-deep generation set
    (lineage.Lineage, CLI --ckpt-keep) so a corrupt newest snapshot costs
    one generation of progress, not the run; the ``.progress`` sidecar is
    refreshed at EVERY chunk boundary (write-then-rename atomic) — it is
    the liveness signal the supervisor's watchdog reads, so it must tick
    even between throttled snapshot saves.

    ``drain`` (preempt.DrainHandler — the signal plane): a pending
    SIGTERM/SIGINT drain request forces the snapshot at the next chunk
    boundary regardless of the wall throttle, then the chunk runner raises
    preempt.PreemptedExit (docs/SEMANTICS.md "Preemption contract").

    With ``profiler`` (telemetry.PhaseProfiler), the compile warmup, every
    run-chunk, every chunk-boundary drain and every checkpoint save are
    recorded as Chrome-trace spans (CLI --trace).

    With ``controller`` (tune.CapController — CLI --auto-caps), buffer caps
    adapt between chunks: the controller may swap in an engine re-jitted at
    new static capacities with the state migrated bit-exactly; subsequent
    heartbeats report the live engine's caps.

    With ``guard`` (txn.OverflowGuard — CLI --on-overflow retry|halt),
    chunks are transactional: overflowing chunks are discarded and replayed
    at grown caps (or the run halts with a structured error), heartbeats
    and checkpoints only ever see committed overflow-free states, and
    heartbeat records carry a ``retries`` block once a retry happened.
    ``selfcheck`` verifies the drop-accounting identity at every committed
    boundary (txn.check_boundary_identity).

    Returns (final_state, heartbeat) — heartbeat.records holds the stream,
    heartbeat.ring_records the drained per-window telemetry rows.
    """
    import jax

    from shadow1_tpu.telemetry import PH_INIT

    total = n_windows if n_windows is not None else engine.n_windows
    if every_windows is None:
        every_windows = max(total // 10, 1)
    if st is None:
        with maybe_span(profiler, PH_INIT):
            st = engine.init_state()
    # Compile before the clock starts: n_windows is a traced argument, so a
    # zero-window call builds the exact program every chunk reuses — the
    # first heartbeat's events/sec no longer folds compile time in.
    with maybe_span(profiler, PH_COMPILE):
        try:
            jax.block_until_ready(engine.run(st, n_windows=0))
        except Exception as e:
            from shadow1_tpu import mem

            # OOM taxonomy: an exhaustion here is a COMPILE/allocation
            # failure, not a mid-run one — tag it so the CLI's memory
            # record reports the phase truthfully (mem.py).
            if mem.is_oom(e):
                e.shadow1_oom_phase = "compile"
            raise
    hb = Heartbeat(engine, stream=stream, initial_state=st, profiler=profiler,
                   emit_heartbeat=emit_heartbeat, emit_ring=emit_ring,
                   guard=guard)
    retune = None
    if controller is not None:
        def retune(eng_cur, s):
            eng_new, s = controller(eng_cur, s)
            hb.engine = eng_new  # heartbeat caps track the live engine
            return eng_new, s
    if guard is not None:
        # Retry-driven cap grows swap engines too — heartbeat fill blocks
        # must report the caps of the engine that actually ran the chunk.
        guard.on_engine_swap = lambda eng_new: setattr(hb, "engine", eng_new)
    if ckpt_path is None:
        st = run_chunked(engine, st, n_windows=total, chunk=every_windows,
                         on_chunk=hb, profiler=profiler, retune=retune,
                         guard=guard, selfcheck=selfcheck, drain=drain)
        return st, hb

    from shadow1_tpu.lineage import Lineage, write_json_atomic
    from shadow1_tpu.preempt import run_injection_hooks

    lineage = Lineage(ckpt_path, keep=ckpt_keep)
    last_save = time.perf_counter()
    last_seq = [None]

    def on_chunk(s, done):
        nonlocal last_save
        hb(s, done)
        sim_ns = int(s.win_start)
        # Fault/preemption/hang injection (tests, ci.sh, chaosprobe) —
        # the shared chunk-boundary contract; inert without the env vars.
        run_injection_hooks(sim_ns)
        now = time.perf_counter()
        draining = drain is not None and drain.requested
        saved = False
        if done >= total or now - last_save > ckpt_every_s or draining:
            with maybe_span(profiler, PH_CHECKPOINT):
                last_seq[0] = lineage.save(
                    s, {"win_start": sim_ns, "done_windows": done})
            last_save = now
            saved = True
        # The progress sidecar is written at EVERY chunk boundary — it is
        # the watchdog's liveness signal, so it must tick even between
        # throttled saves. win_start is the absolute sim clock — monotonic
        # across respawned processes, unlike the invocation-relative
        # ``done``. Atomic like save_state: a wedge mid-write must not
        # leave a truncated sidecar that makes the supervisor abandon a
        # perfectly resumable snapshot.
        write_json_atomic(ckpt_path + ".progress",
                          {"done_windows": done, "total": total,
                           "win_start": sim_ns, "seq": last_seq[0]})
        # Fault injection (SURVEY §5 failure-detection analogue): die
        # like a wedged device process at an exact sim time, once — a
        # respawned resume starts past it. Exercised by the supervisor
        # test; inert without the env var.
        crash_at = os.environ.get("SHADOW1_OBS_CRASH_AT_NS")
        if saved and crash_at is not None and sim_ns == int(crash_at):
            os._exit(41)

    st = run_chunked(engine, st, n_windows=total, chunk=every_windows,
                     on_chunk=on_chunk, profiler=profiler, retune=retune,
                     guard=guard, selfcheck=selfcheck, drain=drain)
    return st, hb
