"""Determinism flight recorder — per-window order-independent state digests.

The determinism contract (docs/SEMANTICS.md) says oracle, single-chip,
sharded, and resumed runs are bit-identical — but the parity tests only
observe it at end of run, as whole-run counter equality. This module makes
the contract *continuously* observable: one integer digest word per engine
subsystem per conservative window, computed INSIDE the jitted window loop
(window granularity, never the round path) and recorded as telemetry-ring
columns. Any two runs of the same config — tpu↔cpu, sharded↔single,
pallas↔xla, resume↔straight-through — must carry identical digest streams;
the first differing (window, subsystem) pinpoints a violation that an
end-of-run assert could only report as "some key mismatched after millions
of windows" (``tools/paritytrace.py`` automates the bisection).

Digest construction (the properties everything below hangs on):

* each semantic element (an occupied event slot, a buffered packet, a live
  socket, a host's NIC/counter row) hashes to one u32 word via a
  splitmix64-style polynomial fold of its *semantic* fields — keyed by
  global host id and value keys like ``(time, tb)``, NEVER by slot index
  or memory layout, so cap migrations (tune/resize.py) and slot
  permutation cannot change it;
* a subsystem's window digest is the plain i64 SUM of its element words —
  order-independent and associative, so the sharded engine psums per-shard
  partial sums into the exact single-device value, and the eager CPU
  oracle can maintain the same sum incrementally (add on push, subtract
  on pop) instead of rescanning its heap;
* i32-semantics fields are masked to their low 32 bits before folding, so
  the TPU's i32 planes (natural wraparound) and the oracle's u32 Python
  ints hash identically.

Three bit-identical implementations live here, mirroring rng.py's twins:
jnp (traced, for the batched engines), numpy-vector (the oracle's [H]
planes), and plain-Python-int (the oracle's per-event / per-socket paths).

What is mixed per subsystem — and what is deliberately excluded — is
documented in docs/SEMANTICS.md §"State digest"; keep the two in sync.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow1_tpu.consts import NP, TCP_FREE
from shadow1_tpu.rng import _mix, _mix_np

# The five digested subsystems, in canonical (ring-column) order.
SUBSYSTEMS = ("evbuf", "outbox", "tcp", "nic", "rng")
DIGEST_FIELDS = tuple(f"dg_{s}" for s in SUBSYSTEMS)

_M64 = (1 << 64) - 1
_M32 = 0xFFFFFFFF
# Odd fold multiplier (xorshift128+/splitmix family constant). The fold is
# a polynomial hash z = z*K + v; the double splitmix finalizer on top makes
# the output word avalanche.
_K = 0x2545F4914F6CDD1D
_K_NP = np.uint64(_K)

# Distinct per-subsystem seed constants so an element can never alias an
# element of another subsystem (or the mq sub-stream of the tcp plane).
SEED_EVBUF = 0xA0761D6478BD642F
SEED_OUTBOX = 0xE7037ED1A0B428DB
SEED_TCP = 0x8EBC6AF09C88C6E3
SEED_MQ = 0x589965CC75374CC3
SEED_NIC = 0x1D8E4E27C47D124F
SEED_RNG = 0xEB44ACCAB455D165

# TCP plane field order is THE canonical order both engines fold in — it is
# imported from the tcp module so the schema cannot drift from the state.
from shadow1_tpu.tcp.tcp import _FIELDS_BOOL as TCP_FIELDS_BOOL  # noqa: E402
from shadow1_tpu.tcp.tcp import _FIELDS_I32 as TCP_FIELDS_I32  # noqa: E402
from shadow1_tpu.tcp.tcp import _FIELDS_I64 as TCP_FIELDS_I64  # noqa: E402


# ---------------------------------------------------------------------------
# jnp implementation (traced; used by core/engine.window_step)
# ---------------------------------------------------------------------------

def _u(v):
    """Field → u64 fold input. i32/bool widen via u32 (masking to the low 32
    bits — the i32-semantics rule); i64 reinterprets mod 2^64."""
    v = jnp.asarray(v)
    if v.dtype == jnp.int32:
        return v.astype(jnp.uint32).astype(jnp.uint64)
    if v.dtype == jnp.bool_:
        return v.astype(jnp.uint64)
    return v.astype(jnp.uint64)


def _fold(z, v):
    return z * _K_NP + _u(v)


def _words(seed: int, fields) -> jnp.ndarray:
    """Element hash words: fold ``fields`` (broadcastable arrays) in order
    onto the subsystem seed, finalize, return u32 words."""
    z = jnp.asarray(np.uint64(seed))
    for v in fields:
        z = _fold(z, v)
    return (_mix(_mix(z)) >> np.uint64(32)).astype(jnp.uint32)


def _masked_sum(words, mask) -> jnp.ndarray:
    """i64 sum of the selected u32 words (exact: < 2^32 per element)."""
    return jnp.where(mask, words.astype(jnp.int64), 0).sum()


def digest_evbuf(buf, hosts) -> jnp.ndarray:
    """Occupied event slots keyed by (host, time, tb, kind, payload)."""
    mask = buf.kind != 0  # K_NONE
    from shadow1_tpu.core.events import tb_join

    fields = [
        jnp.broadcast_to(hosts[None, :], buf.kind.shape),
        buf.abs_time(),
        tb_join(buf.tb_hi, buf.tb_lo),
        buf.kind,
    ] + [buf.p[i] for i in range(NP)]
    return _masked_sum(_words(SEED_EVBUF, fields), mask)


def digest_outbox(ob, hosts) -> jnp.ndarray:
    """This window's buffered sends keyed by (src, dst, depart, ctr, kind,
    payload) — computed BEFORE outbox_clear (window_step does this)."""
    cap, h = ob.dst.shape
    mask = jnp.arange(cap)[:, None] < ob.cnt[None, :]
    fields = [
        jnp.broadcast_to(hosts[None, :], (cap, h)),
        ob.dst,
        ob.abs_depart(),
        ob.ctr,
        ob.kind,
    ] + [ob.p[i] for i in range(NP)]
    return _masked_sum(_words(SEED_OUTBOX, fields), mask)


def digest_tcp(tcp: dict, hosts) -> jnp.ndarray:
    """Live sockets (st != TCP_FREE): every semantic field in canonical
    order, plus the socket's valid message-boundary FIFO entries (summed
    positionlessly — retirement order is ack-driven on both engines)."""
    from shadow1_tpu.core.events import tb_join

    s, h = tcp["st"].shape
    live = tcp["st"] != TCP_FREE
    socks = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[:, None], (s, h))
    fields = [jnp.broadcast_to(hosts[None, :], (s, h)), socks]
    fields += [tcp[f] for f in TCP_FIELDS_I32]
    fields += [tb_join(tcp[f + "_hi"], tcp[f + "_lo"]) for f in TCP_FIELDS_I64]
    fields += [tcp[f] for f in TCP_FIELDS_BOOL]
    total = _masked_sum(_words(SEED_TCP, fields), live)
    mq_mask = tcp["mq_valid"] & live[None, :, :]
    mq_fields = [
        jnp.broadcast_to(hosts[None, None, :], tcp["mq_valid"].shape),
        jnp.broadcast_to(socks[None, :, :], tcp["mq_valid"].shape),
        tcp["mq_end"],
        tcp["mq_meta"],
    ]
    return total + _masked_sum(_words(SEED_MQ, mq_fields), mq_mask)


def digest_nic(nic, hosts) -> jnp.ndarray:
    """Per-host NIC clocks/counters (tx/rx free-at, byte counters, AQM coin
    counter)."""
    fields = [hosts, nic.tx_free, nic.rx_free, nic.tx_bytes, nic.rx_bytes,
              nic.aqm_ctr]
    return _masked_sum(_words(SEED_NIC, fields),
                       jnp.ones(hosts.shape, bool))


def digest_rng(hosts, vectors) -> jnp.ndarray:
    """Per-host deterministic counters: evbuf self_ctr, outbox pkt_ctr, the
    virtual-CPU busy clocks, plus model-level draw counters (``vectors`` is
    the canonical per-model list — see model_host_vectors)."""
    fields = [hosts] + list(vectors)
    return _masked_sum(_words(SEED_RNG, fields),
                       jnp.ones(hosts.shape, bool))


def model_host_vectors(model) -> list:
    """The model-level [H] counter vectors folded into the rng digest, in a
    canonical per-model order. PHOLD contributes (hops, ctr); the net model
    contributes nothing here (its NIC/TCP planes carry their own words; app
    state is deliberately outside the digest contract — docs/SEMANTICS.md).
    Keep ``model_vector_names`` below in lockstep: it labels these vectors
    in paritytrace's plane-diff dumps."""
    f = getattr(model, "_fields", ())
    if "hops" in f and "ctr" in f:
        return [model.hops, model.ctr]
    return []


def model_vector_names(model) -> list[str]:
    """Labels for model_host_vectors' vectors, same order, same dispatch."""
    f = getattr(model, "_fields", ())
    if "hops" in f and "ctr" in f:
        return ["hops", "ctr"]
    return []


def state_digests(st, ctx, dg_outbox) -> jnp.ndarray:
    """The per-window digest vector (i64 [len(SUBSYSTEMS)], SUBSYSTEMS
    order). ``dg_outbox`` is computed by the caller BEFORE the window-end
    delivery clears the outbox; everything else digests the post-delivery
    window-boundary state."""
    hosts = ctx.hosts
    dg_ev = digest_evbuf(st.evbuf, hosts)
    model = st.model
    mf = getattr(model, "_fields", ())
    if "nic" in mf and "tcp" in mf:
        dg_tcp = digest_tcp(model.tcp, hosts)
        dg_nic = digest_nic(model.nic, hosts)
    else:
        dg_tcp = jnp.zeros((), jnp.int64)
        dg_nic = jnp.zeros((), jnp.int64)
    vectors = [st.evbuf.self_ctr, st.outbox.pkt_ctr, st.cpu_busy]
    vectors += model_host_vectors(model)
    dg_rng = digest_rng(hosts, vectors)
    return jnp.stack([dg_ev, dg_outbox, dg_tcp, dg_nic, dg_rng])


# ---------------------------------------------------------------------------
# Plain-Python-int twins (the oracle's per-event / per-socket paths)
# ---------------------------------------------------------------------------

def _mix_int(z: int) -> int:
    z ^= z >> 30
    z = (z * 0xBF58476D1CE4E5B9) & _M64
    z ^= z >> 27
    z = (z * 0x94D049BB133111EB) & _M64
    z ^= z >> 31
    return z


def word_int(seed: int, fields) -> int:
    """Python-int twin of _words for one element. i32-semantics fields must
    be pre-masked with & 0xFFFFFFFF by the caller; i64 fields may be any
    Python int (folded mod 2^64, matching the u64 reinterpret)."""
    z = seed
    for v in fields:
        z = (z * _K + (int(v) & _M64)) & _M64
    return _mix_int(_mix_int(z)) >> 32


def event_word(host: int, time: int, tb: int, kind: int, p: tuple) -> int:
    """Oracle event hash — identical to digest_evbuf's element word. ``p``
    is the (possibly short) payload tuple; missing columns are zero."""
    fields = [host, time, tb, kind]
    fields += [int(p[i]) & _M32 if i < len(p) else 0 for i in range(NP)]
    return word_int(SEED_EVBUF, fields)


def packet_word(src: int, dst: int, depart: int, ctr: int, kind: int,
                p: tuple) -> int:
    """Oracle outbox-send hash — identical to digest_outbox's element word
    (``ctr`` is the per-src lifetime packet counter; only its low 32 bits
    ride the outbox plane)."""
    fields = [src, dst, depart, ctr & _M32, kind]
    fields += [int(p[i]) & _M32 if i < len(p) else 0 for i in range(NP)]
    return word_int(SEED_OUTBOX, fields)


def sock_word(host: int, sock: int, k) -> int:
    """Oracle live-socket hash — identical to digest_tcp's element word.
    ``k`` is a CpuSock; field order is the canonical tcp-plane order."""
    fields = [host, sock]
    fields += [getattr(k, f) & _M32 for f in TCP_FIELDS_I32]
    fields += [getattr(k, f) for f in TCP_FIELDS_I64]
    fields += [1 if getattr(k, f) else 0 for f in TCP_FIELDS_BOOL]
    total = word_int(SEED_TCP, fields)
    for end, meta in k.mq:
        total += word_int(SEED_MQ, [host, sock, end & _M32, meta & _M32])
    return total


# ---------------------------------------------------------------------------
# numpy-vector twins (the oracle's [H] planes — one call per boundary)
# ---------------------------------------------------------------------------

def _words_np(seed: int, fields) -> np.ndarray:
    with np.errstate(over="ignore"):
        z = np.uint64(seed)
        for v in fields:
            v = np.asarray(v)
            if v.dtype == np.int32 or v.dtype == np.bool_:
                v = v.astype(np.uint32)
            z = z * _K_NP + v.astype(np.uint64)
        return (_mix_np(_mix_np(z)) >> np.uint64(32)).astype(np.uint32)


def digest_nic_np(tx_free, rx_free, tx_bytes, rx_bytes, aqm_ctr) -> int:
    h = np.arange(len(tx_free), dtype=np.int64)
    w = _words_np(SEED_NIC, [h, tx_free, rx_free, tx_bytes, rx_bytes,
                             aqm_ctr])
    return int(w.astype(np.int64).sum())


def digest_rng_np(vectors) -> int:
    h = np.arange(len(vectors[0]), dtype=np.int64)
    w = _words_np(SEED_RNG, [h] + list(vectors))
    return int(w.astype(np.int64).sum())
