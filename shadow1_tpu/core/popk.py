"""Pallas fused pop-min kernel — the event-buffer pop in ONE memory pass.

The XLA pop (core/events.py pop_until) lowers to ~12 full-plane HBM passes
(eligibility, three masked mins with their broadcasts/compares, the one-hot
extraction, the clears); on-chip each [C, H] pass costs ~50-95 us at rung-3
shape and the composite measured ~1.35 ms/round (tools/roundprobe.py,
docs/PERF.md round-5). The whole computation is a per-lane (per-host)
reduction chain over the sublane (slot) axis with NO cross-lane traffic —
exactly the shape a fused VMEM kernel wants: read each plane once, keep
every intermediate in registers/VMEM, write the two updated planes and the
[H]-vector results once.

Semantics are IDENTICAL to events.pop_until(extract="sum") — same
lexicographic (t32, tb_hi, tb_lo) masked-min chain, same equality one-hot
(exact: the key triple is unique per host, events.py module docstring),
same masked-sum extraction — asserted bit-equal in tests/test_events.py
and selectable per-run via EngineParams.pop_impl = "pallas".

Grid: 1-D over lane (host) tiles; each program instance sees every slot of
its host tile ([C, BH] blocks), so the reduction never crosses program
instances. The lane tile shrinks as ev_cap grows to hold the block set
(keys + NP payload planes) under the ~16 MB VMEM budget. The updated
t32/kind planes alias their inputs (in-place update, no spare HBM copy).

Reference anchor: this kernel is the batched analogue of the per-host
binary-heap pop in the reference's worker loop
(src/main/core/scheduler/scheduler.c runNextEvent path,
src/main/utility/priority-queue.c).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from shadow1_tpu.consts import K_NONE, NP
from shadow1_tpu.core import events as ev


def _lane_tile(cap: int, planes: int) -> int:
    """Lane-tile width holding ``planes`` i32 [cap, BH] blocks in ~8 MB of
    VMEM. The minimum useful tile is one lane group (128); a cap so large
    that even 128 lanes blow the budget is rejected loudly instead of
    silently compiling an over-VMEM kernel."""
    budget = 8 * 2**20 // (4 * planes * cap)
    if budget < 128:
        raise ValueError(
            f"ev_cap={cap} needs {4 * planes * cap * 128 / 2**20:.1f} MB "
            "per 128-lane tile — beyond the fused-kernel VMEM budget; use "
            "pop_impl/push_impl='xla' for caps this deep"
        )
    return min(1 << (budget.bit_length() - 1), 2048)


def _pop_kernel(until_ref, t32_ref, hi_ref, lo_ref, kind_ref, p_ref,
                t32o_ref, kindo_ref, mt_ref, mhi_ref, mlo_ref, ko_ref,
                po_ref):
    u = until_ref[0]
    t = t32_ref[:, :]                                   # [C, BH] i32
    k = kind_ref[:, :]
    elig = (k != K_NONE) & (t < u)
    tm = jnp.where(elig, t, ev.I32_FREE)
    mint = tm.min(axis=0, keepdims=True)                # [1, BH]
    tie = elig & (tm == mint)
    him = jnp.where(tie, hi_ref[:, :], ev.I32_MAX)
    minhi = him.min(axis=0, keepdims=True)
    tie2 = tie & (him == minhi)
    lom = jnp.where(tie2, lo_ref[:, :], ev.I32_MAX)
    minlo = lom.min(axis=0, keepdims=True)
    sel = tie2 & (lom == minlo)                         # one-hot per host
    t32o_ref[:, :] = jnp.where(sel, ev.I32_FREE, t)
    kindo_ref[:, :] = jnp.where(sel, K_NONE, k)
    mt_ref[:, :] = mint
    mhi_ref[:, :] = minhi
    mlo_ref[:, :] = minlo
    ko_ref[:, :] = jnp.where(sel, k, 0).sum(axis=0, keepdims=True)
    po_ref[:, :, :] = jnp.where(sel[None], p_ref[:, :, :], 0).sum(
        axis=1, keepdims=True
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pop_call(t32, tb_hi, tb_lo, kind, p, u32, *, interpret=False):
    cap, h = kind.shape
    bh = _lane_tile(cap, planes=6 + NP)
    grid = (pl.cdiv(h, bh),)
    blk2 = pl.BlockSpec((cap, bh), lambda i: (0, i))
    vec = pl.BlockSpec((1, bh), lambda i: (0, i))
    out_shapes = (
        jax.ShapeDtypeStruct((cap, h), jnp.int32),   # t32'
        jax.ShapeDtypeStruct((cap, h), jnp.int32),   # kind'
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # min_t
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # min_hi
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # min_lo
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # kind_out
        jax.ShapeDtypeStruct((NP, 1, h), jnp.int32),  # p_out
    )
    return pl.pallas_call(
        _pop_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # until32 (1,)
            blk2, blk2, blk2, blk2,
            pl.BlockSpec((NP, cap, bh), lambda i: (0, 0, i)),
        ],
        out_specs=(
            blk2, blk2, vec, vec, vec, vec,
            pl.BlockSpec((NP, 1, bh), lambda i: (0, 0, i)),
        ),
        out_shape=out_shapes,
        input_output_aliases={1: 0, 4: 1},           # t32, kind in-place
        interpret=interpret,
    )(jnp.asarray(u32).reshape(1), t32, tb_hi, tb_lo, kind, p)


def _resolve_interpret(interpret):
    """Mosaic compiles only for TPU; every other backend (the CPU test
    platform, virtual device meshes) runs the kernels in interpret mode.
    Resolved here so call sites cannot forget the incantation."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def pop_until_fused(buf: ev.EventBuf, until, *,
                    interpret: bool | None = None) -> tuple[ev.EventBuf, ev.Popped]:
    """Drop-in fused replacement for events.pop_until (extract="sum")."""
    interpret = _resolve_interpret(interpret)
    u32 = ev.until32(buf, until)
    t32o, kindo, mt, mhi, mlo, ko, po = _pop_call(
        buf.t32, buf.tb_hi, buf.tb_lo, buf.kind, buf.p, u32,
        interpret=interpret,
    )
    mt, mhi, mlo, ko = mt[0], mhi[0], mlo[0], ko[0]
    mask = mt < u32
    popped = ev.Popped(
        mask=mask,
        time=jnp.where(mask, buf.epoch + mt.astype(jnp.int64), 0),
        kind=ko,
        p=po[:, 0, :],
        tb=jnp.where(mask, ev.tb_join(mhi, mlo), 0),
    )
    buf = buf._replace(
        t32=t32o, kind=kindo,
        n_elig=buf.n_elig - mask.astype(jnp.int32),
    )
    return buf, popped


def _push_kernel(maskv_ref, thi_v, tlo_v, t32_v, bhi_v, blo_v, kind_v, p_v,
                 thi_ref, tlo_ref, t32_ref, bhi_ref, blo_ref, kind_ref, p_ref,
                 thi_o, tlo_o, t32_o, bhi_o, blo_o, kind_o, p_o, over_o):
    k = kind_ref[:, :]                                  # [C, BH]
    free = k == K_NONE
    idx = jax.lax.broadcasted_iota(jnp.int32, k.shape, 0)
    cap = k.shape[0]
    fidx = jnp.where(free, idx, cap).min(axis=0, keepdims=True)  # [1, BH]
    has = fidx < cap
    mv = maskv_ref[:, :] != 0
    ok = mv & has
    w = free & (idx == fidx) & ok
    thi_o[:, :] = jnp.where(w, thi_v[:, :], thi_ref[:, :])
    tlo_o[:, :] = jnp.where(w, tlo_v[:, :], tlo_ref[:, :])
    t32_o[:, :] = jnp.where(w, t32_v[:, :], t32_ref[:, :])
    bhi_o[:, :] = jnp.where(w, bhi_v[:, :], bhi_ref[:, :])
    blo_o[:, :] = jnp.where(w, blo_v[:, :], blo_ref[:, :])
    kind_o[:, :] = jnp.where(w, kind_v[:, :], k)
    p_o[:, :, :] = jnp.where(w[None], p_v[:, :, :], p_ref[:, :, :])
    over_o[:, :] = (mv & ~has).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _push_call(maskv, thi_v, tlo_v, t32_v, bhi_v, blo_v, kind_v, p_v,
               thi, tlo, t32, bhi, blo, kind, p, *, interpret=False):
    cap, h = kind.shape
    bh = _lane_tile(cap, planes=7 + NP)
    grid = (pl.cdiv(h, bh),)
    blk2 = pl.BlockSpec((cap, bh), lambda i: (0, i))
    vec = pl.BlockSpec((1, bh), lambda i: (0, i))
    pvec = pl.BlockSpec((NP, 1, bh), lambda i: (0, 0, i))
    pblk = pl.BlockSpec((NP, cap, bh), lambda i: (0, 0, i))
    plane = jax.ShapeDtypeStruct((cap, h), jnp.int32)
    out_shapes = (
        plane, plane, plane, plane, plane, plane,
        jax.ShapeDtypeStruct((NP, cap, h), jnp.int32),
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # overflow
    )
    return pl.pallas_call(
        _push_kernel,
        grid=grid,
        in_specs=[vec, vec, vec, vec, vec, vec, vec, pvec,
                  blk2, blk2, blk2, blk2, blk2, blk2, pblk],
        out_specs=(blk2, blk2, blk2, blk2, blk2, blk2, pblk, vec),
        out_shape=out_shapes,
        # The seven buffer planes update in place.
        input_output_aliases={8: 0, 9: 1, 10: 2, 11: 3, 12: 4, 13: 5, 14: 6},
        interpret=interpret,
    )(maskv, thi_v, tlo_v, t32_v, bhi_v, blo_v, kind_v, p_v,
      thi, tlo, t32, bhi, blo, kind, p)


def _push_fused(buf: ev.EventBuf, mask, time, tb, kind, p, *,
                advance_ctr: bool, interpret: bool | None = None):
    """Shared body of the fused push_local/push_back (tb = self_ctr or the
    original tie-break, per events.py semantics)."""
    interpret = _resolve_interpret(interpret)
    time = jnp.asarray(time, jnp.int64)
    thi_v, tlo_v = ev.tb_split(time)
    bhi_v, blo_v = ev.tb_split(jnp.asarray(tb, jnp.int64))
    t32_v = ev._t32_of(time, buf.epoch)
    row = lambda x: jnp.asarray(x, jnp.int32).reshape(1, -1)
    thi, tlo, t32, bhi, blo, kindo, po, over = _push_call(
        row(mask), row(thi_v), row(tlo_v), row(t32_v), row(bhi_v),
        row(blo_v), row(jnp.broadcast_to(jnp.asarray(kind, jnp.int32),
                                         time.shape)),
        jnp.asarray(p, jnp.int32)[:, None, :],
        buf.time_hi, buf.time_lo, buf.t32, buf.tb_hi, buf.tb_lo, buf.kind,
        buf.p, interpret=interpret,
    )
    over = (over[0] != 0) & mask
    ok = mask & ~over
    buf = buf._replace(
        time_hi=thi, time_lo=tlo, t32=t32, tb_hi=bhi, tb_lo=blo,
        kind=kindo, p=po,
        n_elig=buf.n_elig + (ok & (t32_v < buf.u32)).astype(jnp.int32),
    )
    if advance_ctr:
        buf = buf._replace(self_ctr=buf.self_ctr + ok.astype(jnp.int64))
    return buf, over


def push_local_fused(buf: ev.EventBuf, mask, time, kind, p, *,
                     interpret: bool | None = None):
    """Drop-in fused replacement for events.push_local."""
    return _push_fused(buf, mask, time, buf.self_ctr, kind, p,
                       advance_ctr=True, interpret=interpret)


def push_back_fused(buf: ev.EventBuf, mask, time, tb, kind, p, *,
                    interpret: bool | None = None):
    """Drop-in fused replacement for events.push_back."""
    return _push_fused(buf, mask, time, tb, kind, p,
                       advance_ctr=False, interpret=interpret)


def _obox_kernel(cnt_ref, okv_ref, dst_v, kind_v, dhi_v, dlo_v, ctr_v, p_v,
                 dst_ref, kind_ref, dhi_ref, dlo_ref, ctr_ref, p_ref,
                 dst_o, kind_o, dhi_o, dlo_o, ctr_o, p_o):
    cap = dst_ref.shape[0]
    idx = jax.lax.broadcasted_iota(jnp.int32, (cap,) + cnt_ref.shape[1:], 0)
    w = (idx == cnt_ref[:, :]) & (okv_ref[:, :] != 0)
    dst_o[:, :] = jnp.where(w, dst_v[:, :], dst_ref[:, :])
    kind_o[:, :] = jnp.where(w, kind_v[:, :], kind_ref[:, :])
    dhi_o[:, :] = jnp.where(w, dhi_v[:, :], dhi_ref[:, :])
    dlo_o[:, :] = jnp.where(w, dlo_v[:, :], dlo_ref[:, :])
    ctr_o[:, :] = jnp.where(w, ctr_v[:, :], ctr_ref[:, :])
    p_o[:, :, :] = jnp.where(w[None], p_v[:, :, :], p_ref[:, :, :])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _obox_call(cnt, okv, dst_v, kind_v, dhi_v, dlo_v, ctr_v, p_v,
               dst, kind, dhi, dlo, ctr, p, *, interpret=False):
    cap, h = dst.shape
    bh = _lane_tile(cap, planes=5 + NP)
    grid = (pl.cdiv(h, bh),)
    blk2 = pl.BlockSpec((cap, bh), lambda i: (0, i))
    vec = pl.BlockSpec((1, bh), lambda i: (0, i))
    pvec = pl.BlockSpec((NP, 1, bh), lambda i: (0, 0, i))
    pblk = pl.BlockSpec((NP, cap, bh), lambda i: (0, 0, i))
    plane = jax.ShapeDtypeStruct((cap, h), jnp.int32)
    return pl.pallas_call(
        _obox_kernel,
        grid=grid,
        in_specs=[vec, vec, vec, vec, vec, vec, vec, pvec,
                  blk2, blk2, blk2, blk2, blk2, pblk],
        out_specs=(blk2, blk2, blk2, blk2, blk2, pblk),
        out_shape=(plane, plane, plane, plane, plane,
                   jax.ShapeDtypeStruct((NP, cap, h), jnp.int32)),
        input_output_aliases={8: 0, 9: 1, 10: 2, 11: 3, 12: 4, 13: 5},
        interpret=interpret,
    )(cnt, okv, dst_v, kind_v, dhi_v, dlo_v, ctr_v, p_v,
      dst, kind, dhi, dlo, ctr, p)


def outbox_append_fused(ob, mask, dst, kind, depart, p, *,
                        interpret: bool | None = None):
    """Drop-in fused replacement for outbox.outbox_append: the write slot is
    ``cnt[h]`` (not a first-free search), so the kernel is a pure one-hot
    write pass over the [P, H] planes."""
    interpret = _resolve_interpret(interpret)
    cap = ob.dst.shape[0]
    ok = mask & (ob.cnt < cap)
    dhi_v, dlo_v = ev.tb_split(jnp.asarray(depart, jnp.int64))
    row = lambda x: jnp.asarray(x, jnp.int32).reshape(1, -1)
    h = ob.cnt.shape[0]
    dsto, kindo, dhio, dloo, ctro, po = _obox_call(
        row(ob.cnt), row(ok), row(jnp.broadcast_to(jnp.asarray(dst, jnp.int32), (h,))),
        row(jnp.broadcast_to(jnp.asarray(kind, jnp.int32), (h,))),
        row(dhi_v), row(dlo_v), row(ob.pkt_ctr.astype(jnp.int32)),
        jnp.asarray(p, jnp.int32)[:, None, :],
        ob.dst, ob.kind, ob.depart_hi, ob.depart_lo, ob.ctr, ob.p,
        interpret=interpret,
    )
    ob = ob._replace(
        dst=dsto, kind=kindo, depart_hi=dhio, depart_lo=dloo, ctr=ctro, p=po,
        cnt=ob.cnt + ok.astype(jnp.int32),
        pkt_ctr=ob.pkt_ctr + ok.astype(jnp.int64),
    )
    return ob, ok
