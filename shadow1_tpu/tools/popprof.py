"""Profile the pop/push primitives — name the op the 1.4 ms/round hides in.

    python -m shadow1_tpu.tools.popprof [--iters N] [--hosts H] [--cap C]
        [--trace DIR]

Round-5 roundprobe finding: EVERY event-buffer primitive (pop, pop_nop,
push, cycle) costs ~1.35-1.4 ms/iter at [C=256, H=1000] on the chip —
~1000x above the HBM roofline for the ~40 MB the ops touch, and
near-identical across probes whose op mix differs. That shape of number
means a fixed pathology (layout transposes, i64 emulation blowup, or a
serialized reduction), not bandwidth. This tool (a) dumps the compiled HLO
for the pop loop so the guilty op is visible by name, and (b) times shape/
dtype ablations of the same pop program: i32 keys vs i64, cap 64 vs 256,
payload vs none — attributing the cost to an axis we can engineer away.
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    # 5000 for the same reason as roundprobe: one XLA execution per timing
    # pays ~70 ms of tunnel RTT, which swamps any 50-iter loop
    # (docs/PERF.md round-5 correction).
    ap.add_argument("--iters", type=int, default=5000)
    ap.add_argument("--hosts", type=int, default=1000)
    ap.add_argument("--cap", type=int, default=256)
    ap.add_argument("--hlo", action="store_true",
                    help="dump optimized HLO of the i64 pop loop to stdout")
    ap.add_argument("--allow-cpu", action="store_true")
    args = ap.parse_args()

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax
    import jax.numpy as jnp
    import numpy as np

    print(json.dumps({"backend": jax.default_backend(), "hosts": args.hosts,
                      "cap": args.cap, "iters": args.iters}), flush=True)
    if jax.default_backend() == "cpu" and not args.allow_cpu:
        print(json.dumps({"error": "cpu backend"}))
        return 1

    rng = np.random.default_rng(7)
    iters = args.iters

    def pop_loop(tdt):
        """The pop_nop reduction skeleton at dtype ``tdt`` for time/tb."""
        MAX = jnp.iinfo(tdt).max

        def step(carry):
            t, tb, kind, acc = carry
            elig = (kind != 0) & (t < MAX // 2)
            t_masked = jnp.where(elig, t, MAX)
            min_t = t_masked.min(axis=0)
            tie = elig & (t_masked == min_t[None, :])
            tb_masked = jnp.where(tie, tb, MAX)
            min_tb = tb_masked.min(axis=0)
            sel = tie & (tb_masked == min_tb[None, :])
            kind = jnp.where(sel, 0, kind)
            t = jnp.where(sel, MAX, t)
            return t, tb, kind, acc + min_t

        def loop(carry, n):
            return jax.lax.fori_loop(0, n, lambda _, c: step(c), carry)

        return jax.jit(loop, static_argnums=1)

    def seeded(tdt, cap, hosts):
        t = jnp.asarray(rng.integers(0, 1 << 30, (cap, hosts)), tdt)
        tb = jnp.asarray(rng.integers(0, 1 << 30, (cap, hosts)), tdt)
        kind = jnp.ones((cap, hosts), jnp.int32)
        acc = jnp.zeros(hosts, tdt)
        return t, tb, kind, acc

    def timeit(name, f, carry):
        jax.block_until_ready(f(carry, iters))
        t0 = time.perf_counter()
        jax.block_until_ready(f(carry, iters))
        wall = time.perf_counter() - t0
        print(json.dumps({"probe": name,
                          "us_per_iter": round(1e6 * wall / iters, 1)}),
              flush=True)

    H, C = args.hosts, args.cap
    if args.hlo:
        f = pop_loop(jnp.int64)
        lowered = f.lower(seeded(jnp.int64, C, H), iters)
        print(lowered.compile().as_text()[:20000])
        return 0

    # Ablation grid: dtype x cap.
    for tdt, label in ((jnp.int64, "i64"), (jnp.int32, "i32")):
        for cap in (C, C // 4):
            f = pop_loop(tdt)
            timeit(f"pop_nop_{label}_c{cap}", f, seeded(tdt, cap, H))

    # Host-major control: the SAME i64 reduction skeleton with axes swapped
    # ([H, C], reduce over the minor/lane axis) — the round-3 layout.
    def pop_loop_hm():
        MAX = jnp.iinfo(jnp.int64).max

        def step(carry):
            t, tb, kind, acc = carry
            elig = (kind != 0) & (t < MAX // 2)
            t_masked = jnp.where(elig, t, MAX)
            min_t = t_masked.min(axis=1)
            tie = elig & (t_masked == min_t[:, None])
            tb_masked = jnp.where(tie, tb, MAX)
            min_tb = tb_masked.min(axis=1)
            sel = tie & (tb_masked == min_tb[:, None])
            kind = jnp.where(sel, 0, kind)
            t = jnp.where(sel, MAX, t)
            return t, tb, kind, acc + min_t

        def loop(carry, n):
            return jax.lax.fori_loop(0, n, lambda _, c: step(c), carry)

        return jax.jit(loop, static_argnums=1)

    t = jnp.asarray(rng.integers(0, 1 << 30, (H, C)), jnp.int64)
    tb = jnp.asarray(rng.integers(0, 1 << 30, (H, C)), jnp.int64)
    kind = jnp.ones((H, C), jnp.int32)
    acc = jnp.zeros(H, jnp.int64)
    timeit("pop_nop_hostmajor_i64", pop_loop_hm(), (t, tb, kind, acc))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
