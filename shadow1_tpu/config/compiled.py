"""Compiled experiment artifact — the common input to both engines.

The reference parses an XML experiment file plus a GraphML topology at
startup (src/main/core/support/configuration.c, src/main/routing/topology.c)
and builds igraph structures queried lazily. We instead *compile* the
experiment on the host into dense numpy tensors once; both the CPU oracle
engine and the TPU engine consume this identical artifact, which is the
cross-validation seam mandated by BASELINE.json ("CPU and TPU engines are
selected from the same config file").

Topology representation: Tor/Bitcoin experiment graphs have few *network*
vertices (points of presence) with many attached hosts, so we precompute
all-pairs shortest-path latency/loss over vertices (SURVEY §7.1) and keep a
host→vertex attachment vector. lat_vv must be strictly positive everywhere:
its minimum IS the conservative window (the reference computes the same
runahead bound from minimum link latency in src/main/core/master.c).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np


NO_STOP = (1 << 62)  # "host never stops" sentinel (i64-safe)


@dataclasses.dataclass
class CompiledExperiment:
    n_hosts: int
    seed: int
    end_time: int                 # ns
    lat_vv: np.ndarray            # i64 [V,V] path latency ns, all > 0
    loss_vv: np.ndarray           # f32 [V,V] end-to-end path loss prob
    host_vertex: np.ndarray       # i32 [H] vertex each host attaches to
    bw_up: np.ndarray             # i64 [H] uplink bits/s
    bw_dn: np.ndarray             # i64 [H] downlink bits/s
    model: str = "phold"          # workload model name
    model_cfg: dict[str, Any] = dataclasses.field(default_factory=dict)
    # --- fidelity knobs (reference: router.c queues, config churn, edge
    # jitter, host/cpu.c), all defaulted off ---
    jitter_vv: np.ndarray | None = None   # i64 [V,V] max ± jitter ns per pkt
    stop_time: np.ndarray | None = None   # i64 [H] host halts at this time
    cpu_ns_per_event: np.ndarray | None = None  # i64 [H] virtual CPU cost
    tx_qlen_bytes: np.ndarray | None = None     # i64 [H] NIC up-queue, 0=inf
    rx_qlen_bytes: np.ndarray | None = None     # i64 [H] NIC down-queue, 0=inf
    # RED AQM on the uplink queue (router.c's upstream active queue
    # management, behind a per-group flag): early-drop probability ramps
    # linearly 0→pmax as the instantaneous backlog crosses [min, max) bytes,
    # certain drop at ≥ max. aqm_max_bytes == 0 disables (the default).
    aqm_min_bytes: np.ndarray | None = None     # i64 [H]
    aqm_max_bytes: np.ndarray | None = None     # i64 [H], 0 = AQM off
    aqm_pmax: np.ndarray | None = None          # f64 [H] drop prob at max
    # Deterministic fault plane (fault/schedule.FaultSchedule or None):
    # host down/up cycles, link outage windows, timed loss ramps — compiled
    # to dense tables both engines share (docs/SEMANTICS.md §"Fault
    # plane"). The legacy per-group stop_time above is the degenerate
    # one-interval case and merges into the same tables.
    faults: Any = None
    # Host-side name registry (config/dns.py); None for programmatic
    # experiments (ids only). Never enters device state.
    dns: Any = None
    # Topology vertex names in id order (GraphML node ids, or ["v0"] for
    # single_vertex); None for programmatic experiments. Host-side only —
    # link records and the pcapdump --edge filter resolve through it.
    vertex_names: Any = None

    def __post_init__(self):
        h, z = self.n_hosts, np.int64
        if self.jitter_vv is None:
            self.jitter_vv = np.zeros_like(self.lat_vv, z)
        if self.stop_time is None:
            self.stop_time = np.full(h, NO_STOP, z)
        if self.cpu_ns_per_event is None:
            self.cpu_ns_per_event = np.zeros(h, z)
        if self.tx_qlen_bytes is None:
            self.tx_qlen_bytes = np.zeros(h, z)
        if self.rx_qlen_bytes is None:
            self.rx_qlen_bytes = np.zeros(h, z)
        if self.aqm_min_bytes is None:
            self.aqm_min_bytes = np.zeros(h, z)
        if self.aqm_max_bytes is None:
            self.aqm_max_bytes = np.zeros(h, z)
        if self.aqm_pmax is None:
            self.aqm_pmax = np.zeros(h, np.float64)

    @property
    def window(self) -> int:
        """Conservative lookahead = min worst-case path latency (runahead).

        With jitter the bound is min(lat − jitter): the earliest any packet
        can arrive (the reference computes runahead from minimum link
        latency in src/main/core/master.c)."""
        return int((self.lat_vv - self.jitter_vv).min())

    def validate(self) -> None:
        assert self.lat_vv.min() > 0, "zero-latency paths break the conservative window"
        assert self.lat_vv.shape == self.loss_vv.shape == self.jitter_vv.shape
        assert (self.jitter_vv >= 0).all()
        assert (self.lat_vv - self.jitter_vv).min() > 0, (
            "jitter ≥ latency would allow arrivals inside the current window"
        )
        assert self.host_vertex.max() < self.lat_vv.shape[0]
        assert (self.bw_up > 0).all() and (self.bw_dn > 0).all()
        assert (self.stop_time > 0).all()
        assert (self.cpu_ns_per_event >= 0).all()
        assert (self.tx_qlen_bytes >= 0).all() and (self.rx_qlen_bytes >= 0).all()
        on = self.aqm_max_bytes > 0
        assert (self.aqm_min_bytes >= 0).all()
        assert (self.aqm_min_bytes[on] < self.aqm_max_bytes[on]).all(), (
            "RED needs aqm_min_bytes < aqm_max_bytes where enabled"
        )
        assert ((self.aqm_pmax[on] > 0) & (self.aqm_pmax[on] <= 1)).all(), (
            "RED needs 0 < aqm_pmax <= 1 where enabled"
        )
        if self.faults is not None:
            self.faults.validate(self.n_hosts, self.lat_vv.shape[0])
        assert self.end_time > 0
        assert int(self.window) < 2**31 - 1, (
            "conservative window must fit the i32 rebased pop keys "
            "(core/events.py t32): window < 2**31 - 1 ns (~2.1 s; the last "
            "value is the clamp sentinel I32_HORIZON, so an event exactly "
            "window-1 ahead must still rebase exactly). Topologies with "
            "multi-second minimum latency are out of this engine's design "
            "envelope."
        )


def single_vertex_experiment(
    n_hosts: int,
    seed: int,
    end_time: int,
    latency_ns: int,
    loss: float = 0.0,
    bw_bits: int = 10**9,
    model: str = "phold",
    model_cfg: dict | None = None,
    jitter_ns: int = 0,
    **fidelity,
) -> CompiledExperiment:
    """Minimal topology: every host on one vertex, uniform latency/loss.

    Mirrors the reference's minimal example configs (resource/examples/).
    ``fidelity`` passes through stop_time / cpu_ns_per_event / *_qlen_bytes.
    """
    return CompiledExperiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end_time,
        lat_vv=np.full((1, 1), latency_ns, np.int64),
        loss_vv=np.full((1, 1), loss, np.float32),
        jitter_vv=np.full((1, 1), jitter_ns, np.int64),
        host_vertex=np.zeros(n_hosts, np.int32),
        bw_up=np.full(n_hosts, bw_bits, np.int64),
        bw_dn=np.full(n_hosts, bw_bits, np.int64),
        model=model,
        model_cfg=model_cfg or {},
        **fidelity,
    )
