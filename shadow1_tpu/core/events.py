"""Batched event buffers — the tensorized per-host priority queues.

The reference gives every host a binary-heap event queue and a locked async
queue for cross-thread pushes (src/main/core/scheduler/*,
src/main/utility/priority-queue.c). Here all H queues live in one set of
fixed-capacity SoA tensors ``[C, H]`` (slot-major, host-minor — see
core/dense.py for why); pop-min is a pair of masked min-reductions, local
push writes the first free slot, and cross-host delivery is a sorted batch
merge performed once per conservative window (SURVEY §7.1).

Total event order matches the reference's (time, host, seq) comparator
(src/main/core/work/event.c): within a host, events pop by (time, tb) where
``tb`` is a deterministic tie-break assigned at creation — local pushes use
the host's own monotone counter, delivered packets use
``consts.packet_tb(src_host, src_pkt_counter)``. Both engines compute the
same keys, so event order is engine-independent.

TPU notes: every update is dense (one-hot + where, or a sort + segment
gather) — no dynamic-index scatters, no per-slot ``argmin``/``cumsum`` in
the round path (all measured slow on the chip; core/dense.py). Pop-min
exploits that the (time, tb) key pair is UNIQUE per host — tb values never
repeat within a host (local pushes consume a monotone counter; packet tbs
embed the unique (src, src_ctr); the two ranges are disjoint via
TB_PACKET_BASE) — so "the" minimum slot is an equality one-hot against the
reduced (min-time, min-tb) pair, and payload extraction is a masked sum.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from shadow1_tpu.consts import K_NONE, NP
from shadow1_tpu.core.dense import extract_col, first_true

I64_MAX = jnp.iinfo(jnp.int64).max


class EventBuf(NamedTuple):
    time: jnp.ndarray      # i64 [C, H]
    tb: jnp.ndarray        # i64 [C, H] tie-break key
    kind: jnp.ndarray      # i32 [C, H] (K_NONE = free slot)
    p: jnp.ndarray         # i32 [NP, C, H] payload columns
    self_ctr: jnp.ndarray  # i64 [H] counter for locally-pushed tb keys


class Popped(NamedTuple):
    mask: jnp.ndarray   # bool [H] — host had an eligible event this round
    time: jnp.ndarray   # i64 [H]
    kind: jnp.ndarray   # i32 [H] (K_NONE where ~mask)
    p: jnp.ndarray      # i32 [NP, H]
    tb: jnp.ndarray     # i64 [H] original tie-break (for cpu-model requeue)


def evbuf_init(n_hosts: int, cap: int) -> EventBuf:
    return EventBuf(
        time=jnp.full((cap, n_hosts), I64_MAX, jnp.int64),
        tb=jnp.zeros((cap, n_hosts), jnp.int64),
        kind=jnp.full((cap, n_hosts), K_NONE, jnp.int32),
        p=jnp.zeros((NP, cap, n_hosts), jnp.int32),
        self_ctr=jnp.zeros(n_hosts, jnp.int64),
    )


def push_local(buf: EventBuf, mask, time, kind, p) -> tuple[EventBuf, jnp.ndarray]:
    """Push one event per host where ``mask``; tb from the host's own counter.

    Returns (buf, overflow_mask). Overflowing events are dropped and must be
    surfaced as a metric — capacity is an experiment knob (SURVEY §7.3.2).
    """
    has_free, first = first_true(buf.kind == K_NONE)
    ok = mask & has_free
    w = first & ok[None, :]
    buf = buf._replace(
        time=jnp.where(w, jnp.asarray(time, jnp.int64)[None, :], buf.time),
        tb=jnp.where(w, buf.self_ctr[None, :], buf.tb),
        kind=jnp.where(w, jnp.asarray(kind, jnp.int32)[None, :], buf.kind),
        p=jnp.where(w[None], jnp.asarray(p, jnp.int32)[:, None, :], buf.p),
        self_ctr=buf.self_ctr + ok.astype(jnp.int64),
    )
    return buf, mask & ~has_free


def push_back(buf: EventBuf, mask, time, tb, kind, p) -> tuple[EventBuf, jnp.ndarray]:
    """Re-insert a popped event with its ORIGINAL tie-break key.

    Used by the virtual-CPU model when a busy host's event execution slips
    past the window boundary (docs/SEMANTICS.md §cpu): the event re-enters
    at (eff_time, original tb), so its order among same-time events is
    preserved. Does not advance self_ctr."""
    has_free, first = first_true(buf.kind == K_NONE)
    ok = mask & has_free
    w = first & ok[None, :]
    buf = buf._replace(
        time=jnp.where(w, jnp.asarray(time, jnp.int64)[None, :], buf.time),
        tb=jnp.where(w, jnp.asarray(tb, jnp.int64)[None, :], buf.tb),
        kind=jnp.where(w, jnp.asarray(kind, jnp.int32)[None, :], buf.kind),
        p=jnp.where(w[None], jnp.asarray(p, jnp.int32)[:, None, :], buf.p),
    )
    return buf, mask & ~has_free


def pop_until(buf: EventBuf, until, extract: str = "sum") -> tuple[EventBuf, Popped]:
    """Per-host pop of the minimum-(time, tb) event with time < until.

    Two min-reductions over the slot (sublane) axis + an equality one-hot;
    exact because (time, tb) is unique per host (module docstring).

    ``extract`` selects how kind/payload leave the buffer — "sum" (masked
    sum over the one-hot) or "gather" (one-hot → index → take_along_axis).
    Both are exact; which is faster is a backend/layout question
    (EngineParams.pop_extract, docs/PERF.md round-5)."""
    assert extract in ("sum", "gather"), f"bad pop_extract {extract!r}"
    elig = (buf.kind != K_NONE) & (buf.time < until)
    t_masked = jnp.where(elig, buf.time, I64_MAX)
    min_t = t_masked.min(axis=0)
    mask = elig.any(axis=0)
    tie = elig & (t_masked == min_t[None, :])
    tb_masked = jnp.where(tie, buf.tb, I64_MAX)
    min_tb = tb_masked.min(axis=0)
    sel = tie & (tb_masked == min_tb[None, :])      # one-hot per active host
    if extract == "gather":
        from shadow1_tpu.core.dense import first_true_idx, get_col

        _, slot = first_true_idx(sel)
        kind = jnp.where(mask, get_col(buf.kind, slot), K_NONE)
        pay = jnp.where(mask[None, :], get_col(buf.p, slot), 0)
    else:
        kind = extract_col(sel, buf.kind)
        pay = extract_col(sel, buf.p)
    ev = Popped(
        mask=mask,
        time=jnp.where(mask, min_t, 0),
        kind=kind,
        p=pay,
        tb=jnp.where(mask, min_tb, 0),
    )
    buf = buf._replace(
        kind=jnp.where(sel, K_NONE, buf.kind),
        time=jnp.where(sel, I64_MAX, buf.time),
    )
    return buf, ev


def any_eligible(buf: EventBuf, until) -> jnp.ndarray:
    return ((buf.kind != K_NONE) & (buf.time < until)).any()


def deliver_batch(buf: EventBuf, dst, time, tb, kind, p, mask) -> tuple[EventBuf, jnp.ndarray]:
    """Merge N externally-created events into their hosts' buffers.

    The tensor analogue of the reference's locked cross-thread event push
    (src/main/utility/async-priority-queue.c), restructured gather-style for
    TPU: sort packets by destination (masked ones to the end), then each
    host's r-th free slot *gathers* the r-th packet of its segment
    (seg_start[h] + r). All reads are sorted gathers; the only writes are
    dense ``where``s. Packet r per host is the r-th in flat source order,
    and free slots fill in ascending slot index. Slot ASSIGNMENT is an
    engine-internal layout choice; pop order is decided purely by the
    (time, tb) keys, so it is engine- and layout-independent.
    Returns (buf, n_overflow). ``p`` is [NP, N].

    Overflow-victim selection is layout-defined: when a destination's free
    slots run out, which packets drop depends on flat source order (since
    the [C, H] rewrite: slot-major), so it differs across engines and
    layout revisions. Cross-engine parity is guaranteed only for runs with
    ``ev_overflow == 0`` — the oracle harness asserts this
    (docs/SEMANTICS.md "Bounds and overflow").

    TPU tuning: the sort key packs (dst, flat index) into one integer so an
    *unstable* single-key sort is deterministic (keys are distinct and the
    packing preserves source order within a destination); segment bounds
    come from one H+1-point searchsorted; the 15 payload rows (time/tb
    split into i32 halves, kind, p) ride one stacked gather instead of
    four. This runs once per window, so its cumsum over the slot axis is
    off the round path.
    """
    cap, n_hosts = buf.time.shape
    n = dst.shape[0]
    nb = max((n - 1).bit_length(), 1)
    wide = (n_hosts + 1) << nb > 2**31 - 1
    kdt = jnp.int64 if wide else jnp.int32
    key = (jnp.where(mask, dst, n_hosts).astype(kdt) << nb) | jnp.arange(n, dtype=kdt)
    (key_s,) = jax.lax.sort((key,), is_stable=False)
    dst_s = (key_s >> nb).astype(jnp.int32)
    hs = jnp.arange(n_hosts + 1, dtype=jnp.int32)
    seg = jnp.searchsorted(dst_s, hs, side="left")
    n_in = (seg[1:] - seg[:-1]).astype(jnp.int32)            # [H]
    free = buf.kind == K_NONE                                # [C, H]
    free_rank = (jnp.cumsum(free, axis=0) - free).astype(jnp.int32)
    take = free & (free_rank < n_in[None, :])                # slot receives one
    src = jnp.minimum(seg[:-1][None, :] + free_rank, n - 1)
    oidx = (key_s & ((1 << nb) - 1)).astype(jnp.int32)[src]  # [C, H] flat idx
    stacked = jnp.concatenate(
        [
            jnp.stack([_lo(time), _hi(time), _lo(tb), _hi(tb), kind]),
            p,
        ]
    )                                                        # [5+NP, N] i32
    g = stacked[:, oidx]                                     # [5+NP, C, H]
    buf = buf._replace(
        time=jnp.where(take, _join(g[0], g[1]), buf.time),
        tb=jnp.where(take, _join(g[2], g[3]), buf.tb),
        kind=jnp.where(take, g[4], buf.kind),
        p=jnp.where(take[None], g[5:], buf.p),
    )
    free_cnt = free.sum(axis=0, dtype=jnp.int32)
    n_over = mask.sum() - jnp.minimum(n_in, free_cnt).sum()
    return buf, n_over


def _lo(x):
    return (x & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)


def _hi(x):
    return ((x >> 32) & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)


def _join(lo, hi):
    return (
        lo.astype(jnp.uint32).astype(jnp.uint64)
        | (hi.astype(jnp.uint32).astype(jnp.uint64) << jnp.uint64(32))
    ).astype(jnp.int64)
