"""Config front-end: YAML schema, GraphML loading, path compilation, CLI.

The engine-selector seam (BASELINE.json: "CPU and TPU engines are selected
from the same config file") is exercised by running ladder rung 1 from its
YAML file on both engines and asserting identical results.
"""

import os

import numpy as np
import pytest

from shadow1_tpu.config.experiment import (
    build_experiment,
    load_experiment,
    parse_bw_bits,
    parse_time_ns,
)
from shadow1_tpu.config.topology import compile_paths
from shadow1_tpu.consts import MS, SEC

CONFIGS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "configs")


def test_unit_parsers():
    assert parse_time_ns("10 ms") == 10 * MS
    assert parse_time_ns("2 s") == 2 * SEC
    assert parse_time_ns(1500) == 1500
    assert parse_time_ns("250us") == 250_000
    assert parse_bw_bits("10 Mbit") == 10**7
    assert parse_bw_bits("1 Gbit") == 10**9


def test_compile_paths_line_graph():
    # v0 -10ms- v1 -20ms- v2, loss 0.1 each edge.
    inf = np.inf
    lat = np.array([[inf, 10 * MS, inf], [10 * MS, inf, 20 * MS], [inf, 20 * MS, inf]], float)
    loss = np.array([[0, 0.1, 0], [0.1, 0, 0.1], [0, 0.1, 0]], float)
    lat_vv, loss_vv = compile_paths(lat, loss)
    assert lat_vv[0, 2] == 30 * MS
    assert lat_vv[0, 0] == 10 * MS  # intra-vertex default: min edge latency
    np.testing.assert_allclose(loss_vv[0, 2], 1 - 0.9 * 0.9, rtol=1e-6)
    np.testing.assert_allclose(loss_vv[0, 1], 0.1, rtol=1e-6)


def test_rung1_yaml_roundtrip_both_engines():
    exp, params, scheduler = load_experiment(os.path.join(CONFIGS, "rung1_filexfer.yaml"))
    assert scheduler == "tpu"
    assert exp.n_hosts == 2
    assert exp.window == 40 * MS  # GraphML edge latency
    assert exp.model_cfg["server"][1] == 0  # "@server" reference resolved

    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.cpu_engine import CpuEngine

    cpu = CpuEngine(exp, params)
    cm = cpu.run()
    cs = cpu.summary()
    eng = Engine(exp, params)
    st = eng.run()
    tm = Engine.metrics_dict(st)
    ts = eng.model_summary(st)
    assert int(ts["total_flows_done"]) == 1
    assert int(ts["total_rx_bytes"]) == 1_000_000
    for k in ("events", "pkts_sent", "pkts_delivered", "pkts_lost"):
        assert tm[k] == cm[k], k


def test_all_rung_configs_build():
    for name in ("rung2_tgen100.yaml", "rung3_tor1k.yaml",
                 "rung4_tor10k.yaml", "rung5_bitcoin5k.yaml"):
        exp, params, _ = load_experiment(os.path.join(CONFIGS, name))
        exp.validate()
        assert exp.n_hosts in (100, 1000, 10000, 5000), name
    # bitcoin generator produced a symmetric graph
    exp, _, _ = load_experiment(os.path.join(CONFIGS, "rung5_bitcoin5k.yaml"))
    peers = exp.model_cfg["peers"]
    assert peers.shape == (5000, 8)
    for h in (0, 17, 4999):
        for p in peers[h]:
            assert h in peers[p], "peer graph must be symmetric"


def test_cli_runs_rung1(capsys):
    import json

    from shadow1_tpu.cli import main

    rc = main([os.path.join(CONFIGS, "rung1_filexfer.yaml"), "--engine", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["engine"] == "cpu"
    assert out["metrics"]["events"] > 0


def _phold_doc(**over):
    doc = {
        "general": {"seed": 1, "stop_time": "10 ms"},
        "engine": {"scheduler": "tpu"},
        "network": {"single_vertex": {"latency": "1 ms"}},
        "hosts": [{"name": "h", "count": 2}],
        "app": {"model": "phold"},
    }
    doc.update(over)
    return doc


def test_unknown_keys_fail_fast():
    """Config hardening: a typo anywhere in the experiment schema fails at
    load (fault/schedule.py-style rejection), never a silent default run."""
    build_experiment(_phold_doc())  # the baseline doc itself is valid
    cases = [
        _phold_doc(egine={"scheduler": "tpu"}),               # top-level typo
        _phold_doc(general={"seed": 1, "stop_tme": "10 ms"}),  # general typo
        _phold_doc(network={"single_vertex": {"latncy": "1 ms"}}),
        _phold_doc(network={"single_vertex": {"latency": "1 ms"},
                            "jitterr": "1 us"}),
        _phold_doc(hosts=[{"name": "h", "countt": 2}]),        # host typo
        _phold_doc(app={"model": "phold", "prams": {}}),       # app typo
    ]
    for doc in cases:
        with pytest.raises(AssertionError, match="unknown"):
            build_experiment(doc)
    # The engine section already rejected typos; keep that contract pinned.
    with pytest.raises(AssertionError, match="unknown engine params"):
        build_experiment(_phold_doc(engine={"scheduler": "tpu",
                                            "ev_capp": 64}))


def test_stagger_start_times():
    """Group param dict form {start, interval}: host i of the group gets
    start + i*interval (the rung-4 client-bootstrap stagger)."""
    from shadow1_tpu.consts import MS

    exp, _, _ = load_experiment(os.path.join(CONFIGS, "rung4_tor10k.yaml"))
    st = exp.model_cfg["start_time"]
    clients = np.where(exp.model_cfg["role"] == 1)[0]
    assert st[clients[0]] == 200 * MS
    assert st[clients[1]] - st[clients[0]] == 2 * MS
    assert st[clients[-1]] == 200 * MS + (len(clients) - 1) * 2 * MS
    relays = np.where(exp.model_cfg["role"] == 0)[0]
    assert (st[relays] == 200 * MS).all()  # non-staggered groups untouched
