"""Batched event buffers — the tensorized per-host priority queues.

The reference gives every host a binary-heap event queue and a locked async
queue for cross-thread pushes (src/main/core/scheduler/*,
src/main/utility/priority-queue.c). Here all H queues live in one set of
fixed-capacity SoA tensors ``[H, C]``; pop-min is a masked two-stage argmin,
local push writes the first free slot, and cross-host delivery is a sorted
batch scatter performed once per conservative window (SURVEY §7.1).

Total event order matches the reference's (time, host, seq) comparator
(src/main/core/work/event.c): within a host, events pop by (time, tb) where
``tb`` is a deterministic tie-break assigned at creation — local pushes use
the host's own monotone counter, delivered packets use
``consts.packet_tb(src_host, src_pkt_counter)``. Both engines compute the
same keys, so event order is engine-independent.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from shadow1_tpu.consts import K_NONE, NP

I64_MAX = jnp.iinfo(jnp.int64).max


class EventBuf(NamedTuple):
    time: jnp.ndarray      # i64 [H, C]
    tb: jnp.ndarray        # i64 [H, C] tie-break key
    kind: jnp.ndarray      # i32 [H, C] (K_NONE = free slot)
    p: jnp.ndarray         # i32 [H, C, NP] payload columns
    self_ctr: jnp.ndarray  # i64 [H] counter for locally-pushed tb keys


class Popped(NamedTuple):
    mask: jnp.ndarray   # bool [H] — host had an eligible event this round
    time: jnp.ndarray   # i64 [H]
    kind: jnp.ndarray   # i32 [H] (K_NONE where ~mask)
    p: jnp.ndarray      # i32 [H, NP]


def evbuf_init(n_hosts: int, cap: int) -> EventBuf:
    return EventBuf(
        time=jnp.full((n_hosts, cap), I64_MAX, jnp.int64),
        tb=jnp.zeros((n_hosts, cap), jnp.int64),
        kind=jnp.full((n_hosts, cap), K_NONE, jnp.int32),
        p=jnp.zeros((n_hosts, cap, NP), jnp.int32),
        self_ctr=jnp.zeros(n_hosts, jnp.int64),
    )


def push_local(buf: EventBuf, mask, time, kind, p) -> tuple[EventBuf, jnp.ndarray]:
    """Push one event per host where ``mask``; tb from the host's own counter.

    Returns (buf, overflow_mask). Overflowing events are dropped and must be
    surfaced as a metric — capacity is an experiment knob (SURVEY §7.3.2).
    """
    h = jnp.arange(buf.time.shape[0])
    free = buf.kind == K_NONE
    has_free = free.any(axis=1)
    slot = jnp.argmax(free, axis=1)
    ok = mask & has_free
    # Out-of-range slot index + mode="drop" implements the write mask.
    slot = jnp.where(ok, slot, buf.time.shape[1])
    buf = buf._replace(
        time=buf.time.at[h, slot].set(time, mode="drop"),
        tb=buf.tb.at[h, slot].set(buf.self_ctr, mode="drop"),
        kind=buf.kind.at[h, slot].set(kind, mode="drop"),
        p=buf.p.at[h, slot].set(p, mode="drop"),
        self_ctr=buf.self_ctr + ok.astype(jnp.int64),
    )
    return buf, mask & ~has_free


def pop_until(buf: EventBuf, until) -> tuple[EventBuf, Popped]:
    """Per-host pop of the minimum-(time, tb) event with time < until."""
    h = jnp.arange(buf.time.shape[0])
    elig = (buf.kind != K_NONE) & (buf.time < until)
    t_masked = jnp.where(elig, buf.time, I64_MAX)
    min_t = t_masked.min(axis=1)
    mask = elig.any(axis=1)
    tie = elig & (t_masked == min_t[:, None])
    tb_masked = jnp.where(tie, buf.tb, I64_MAX)
    slot = jnp.argmin(tb_masked, axis=1)
    ev = Popped(
        mask=mask,
        time=jnp.where(mask, min_t, 0),
        kind=jnp.where(mask, buf.kind[h, slot], K_NONE),
        p=jnp.where(mask[:, None], buf.p[h, slot], 0),
    )
    slot = jnp.where(mask, slot, buf.time.shape[1])
    buf = buf._replace(
        kind=buf.kind.at[h, slot].set(K_NONE, mode="drop"),
        time=buf.time.at[h, slot].set(I64_MAX, mode="drop"),
    )
    return buf, ev


def any_eligible(buf: EventBuf, until) -> jnp.ndarray:
    return ((buf.kind != K_NONE) & (buf.time < until)).any()


def deliver_batch(buf: EventBuf, dst, time, tb, kind, p, mask) -> tuple[EventBuf, jnp.ndarray]:
    """Scatter N externally-created events into their hosts' buffers.

    This is the tensor analogue of the reference's locked cross-thread event
    push (src/main/utility/async-priority-queue.c): sort by destination, rank
    within each destination segment, and write each event into its host's
    r-th free slot. All (dst, slot) targets are distinct by construction, so
    the scatter is conflict-free. Returns (buf, n_overflow).
    """
    n_hosts, cap = buf.time.shape
    n = dst.shape[0]
    order = jnp.argsort(jnp.where(mask, dst, n_hosts), stable=True)
    dst_s = dst[order]
    mask_s = mask[order]
    # Rank within destination segment.
    idx = jnp.arange(n)
    is_start = jnp.concatenate([jnp.array([True]), dst_s[1:] != dst_s[:-1]])
    seg_start = jnp.maximum.accumulate(jnp.where(is_start, idx, 0))
    rank = idx - seg_start
    # r-th free slot per host: sort slots so free ones come first.
    free = buf.kind == K_NONE
    free_cnt = free.sum(axis=1)
    slot_order = jnp.argsort(~free, axis=1, stable=True)  # [H, C], free slots first
    ok = mask_s & (rank < free_cnt[jnp.where(mask_s, dst_s, 0)])
    slot = slot_order[jnp.where(ok, dst_s, 0), jnp.minimum(rank, cap - 1)]
    d = jnp.where(ok, dst_s, n_hosts)
    s = jnp.where(ok, slot, cap)
    buf = buf._replace(
        time=buf.time.at[d, s].set(time[order], mode="drop"),
        tb=buf.tb.at[d, s].set(tb[order], mode="drop"),
        kind=buf.kind.at[d, s].set(kind[order], mode="drop"),
        p=buf.p.at[d, s].set(p[order], mode="drop"),
    )
    return buf, (mask_s & ~ok).sum()
