"""Fleet mode — batched experiment sweeps as one device program.

``expand``  — jax-free ``sweep:`` section expansion + fleet-contract
              validation (FleetPlan, FleetConfigError).
``engine``  — FleetEngine: E experiment variants vmapped over a leading
              experiment axis through the single-device window loop.
``run``     — the chunked fleet runner (per-experiment ring drain,
              heartbeats, checkpoints, per-experiment final records) and
              the fleet RECOVERY plane: transactional overflow retry over
              the whole [E, ...] pytree, lane quarantine
              (--on-lane-fail), mid-sweep lane finalization
              (--lane-finalize), fleet-global --auto-caps.

Contracts: docs/SEMANTICS.md §"Fleet contract" + §"Fleet recovery
contract"; record schemas: docs/OBSERVABILITY.md §"Fleet records" +
§"Fleet recovery records".
"""

from shadow1_tpu.fleet.expand import (  # noqa: F401
    FleetConfigError,
    FleetPlan,
    expand_sweep,
    expand_sweep_docs,
    load_sweep,
)
