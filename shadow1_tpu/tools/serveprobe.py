"""serveprobe — end-to-end proof of the serving contract.

Spawns a real daemon (subprocess, CPU-safe), drives it like a tenant
population, and verifies the acceptance gates of the serve plane
(docs/SEMANTICS.md §"Serving contract") in one invocation:

1. **round-trip bit-exactness**: every completed job's digest stream
   (the ring rows routed into its ``result.jsonl``) bit-matches the solo
   CLI run of the same config — packed-lane execution is invisible to
   the tenant;
2. **hot-engine cache**: same-shape jobs submitted SEQUENTIALLY (so they
   land in separate batches) must hit the cache from the second batch on
   — asserted from the daemon ledger's hit counter, i.e. no re-trace, no
   recompile;
3. **admission control**: an over-budget submission (``--overbudget``
   config) is rejected pre-compile with the ``error=memory_budget``
   advice record and the submit client exits EXIT_MEMORY — while the
   resident jobs complete normally;
4. **graceful shutdown**: SIGTERM drains the daemon and exits
   EXIT_SERVE_SHUTDOWN.

``--resilience`` runs a second daemon (own spool, tight memory budget,
``--queue-depth 2``) and proves the serve-plane resilience gates on ONE
run: a job admitted as ``waiting_headroom`` (fits idle, not the live
headroom) completes bit-identical to solo once the resident batch
drains; a submission past the queue cap is rejected ``queue_full`` with
retry-after advice (EXIT_QUEUE_FULL taxonomy); a ``--queue-ttl-s`` job
that never got a lane expires ``deadline_expired``; and a batch killed
by an injected transient crash (SHADOW1_SERVE_CRASH_BATCH) retries from
its last committed generation and still bit-matches solo.

Exit codes: 0 = all gates pass; 3 = digest divergence (the fleetprobe
convention — a determinism bug, not a serve bug); 1 = any other failure.

Usage::

    python -m shadow1_tpu.tools.serveprobe CONFIG --seeds 5,6 \
        [--overbudget BIGCONFIG] [--mem-bytes N] [--windows W] \
        [--resilience] [--json-only]

CONFIG needs ``engine: {metrics_ring: W, state_digest: 1}`` so both the
daemon lanes and the solo reference emit the digest stream.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

EXIT_DIVERGED = 3


def _solo_stream(config_path: str, windows, timeout_s: float,
                 env) -> dict[int, tuple]:
    """window → digest-word tuple from a solo CLI run's stderr rings."""
    from shadow1_tpu.core.digest import DIGEST_FIELDS

    cmd = [sys.executable, "-m", "shadow1_tpu", config_path]
    if windows is not None:
        cmd += ["--windows", str(windows)]
    r = subprocess.run(cmd, env=env, stdout=subprocess.DEVNULL,
                       stderr=subprocess.PIPE, text=True,
                       timeout=timeout_s)
    if r.returncode != 0:
        raise RuntimeError(f"solo reference run failed rc={r.returncode}: "
                           f"{r.stderr[-800:]}")
    out = {}
    for line in r.stderr.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("type") == "ring":
            out[rec["window"]] = tuple(rec[f] for f in DIGEST_FIELDS)
    return out


def _served_stream(spool_dir: str, job_id: str) -> dict[int, tuple]:
    from shadow1_tpu.core.digest import DIGEST_FIELDS
    from shadow1_tpu.serve.protocol import Spool

    out = {}
    for rec in Spool(spool_dir).read_results(job_id):
        if rec.get("type") == "ring":
            out[rec["window"]] = tuple(rec[f] for f in DIGEST_FIELDS)
    return out


def _wait_state(spool_dir: str, job_id: str, states: tuple,
                timeout_s: float) -> dict | None:
    from shadow1_tpu.serve.protocol import Spool

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = Spool(spool_dir).read_status(job_id) or {}
        if st.get("state") in states:
            return st
        time.sleep(0.05)
    return None


def _resilience_phase(cfgs, work, env, args, say):
    """The queued-admission / deadline / retry gate (docs ISSUE: all on
    ONE daemon run). Returns (error_message_or_None, verdict_dict)."""
    import yaml

    from shadow1_tpu import mem
    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.consts import (
        EXIT_DEADLINE,
        EXIT_QUEUE_FULL,
        EXIT_SERVE_SHUTDOWN,
    )
    from shadow1_tpu.serve import client
    from shadow1_tpu.serve.protocol import Spool, request

    verdict = {}
    exp, params, _ = load_experiment(cfgs[0])
    est = mem.estimate(exp, params, n_exp=1).peak_bytes
    if est <= 0:
        return "memory estimator returned no estimate", verdict
    spool = os.path.join(work, "spool_resilience")
    crash_path = os.path.join(work, "crash_count")
    with open(crash_path, "w") as f:
        f.write("0")
    # One resident tenant fits with room to spare; two do not — the
    # second admission must queue as waiting_headroom, never reject.
    env2 = dict(env)
    env2["SHADOW1_MEM_BYTES"] = str(int(est * 1.5))
    env2["SHADOW1_SERVE_RETRY_BACKOFF_S"] = "0.05"
    env2["SHADOW1_SERVE_CRASH_BATCH"] = crash_path
    # a TTL tenant in its own shape class: never packs into anyone's
    # batch, so it genuinely waits (and expires) in the queue
    with open(cfgs[0]) as f:
        doc = yaml.safe_load(f.read())
    doc.setdefault("general", {})["seed"] = 99
    eng = doc.setdefault("engine", {})
    eng["ev_cap"] = int(eng.get("ev_cap", 32)) * 2
    ttl_cfg = os.path.join(work, "ttl.yaml")
    with open(ttl_cfg, "w") as f:
        yaml.safe_dump(doc, f)

    err_path = os.path.join(work, "daemon2.stderr")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "shadow1_tpu", "serve", "--spool", spool,
         "--poll-s", "0.05", "--queue-depth", "2",
         "--ckpt-every-s", "0.05"],
        env=env2, stdout=subprocess.DEVNULL, stderr=open(err_path, "w"))
    try:
        deadline = time.monotonic() + 60
        while Spool(spool).daemon_alive() is None:
            if daemon.poll() is not None or time.monotonic() > deadline:
                return (f"resilience daemon did not start "
                        f"(rc={daemon.poll()})"), verdict
            time.sleep(0.1)
        say("[serveprobe] resilience daemon up "
            f"(budget {mem.fmt_bytes(int(est * 1.5))}, queue-depth 2)")

        # A long resident batch to queue behind.
        j_a = client.submit(spool, cfgs[0], windows=300)
        if _wait_state(spool, j_a, ("running",), 120) is None:
            return "long job never started running", verdict
        # B fits idle but not live headroom -> waiting_headroom;
        # C (own shape, low priority, tight TTL) expires in the queue;
        # D overflows the depth-2 queue -> queue_full backpressure.
        j_b = client.submit(spool, cfgs[1 % len(cfgs)])
        j_c = client.submit(spool, ttl_cfg, priority=-1,
                            queue_ttl_s=0.35)
        j_d = client.submit(spool, cfgs[0])

        st_b = _wait_state(spool, j_b, ("waiting_headroom",), 120)
        if st_b is None:
            return ("second tenant never reached waiting_headroom "
                    f"(status {Spool(spool).read_status(j_b)})"), verdict
        verdict["waiting_headroom"] = True
        say("[serveprobe] tenant B admitted waiting_headroom behind the "
            "resident batch")

        st_d = _wait_state(spool, j_d, ("rejected",), 120)
        if st_d is None or (st_d.get("error") or {}).get("error") \
                != "queue_full":
            return f"expected queue_full rejection, got {st_d}", verdict
        if (st_d["error"].get("retry_after_s") or 0) <= 0 \
                or client.exit_code_for(st_d) != EXIT_QUEUE_FULL:
            return f"queue_full record lacks retry advice: {st_d}", verdict
        verdict["queue_full"] = True
        say(f"[serveprobe] over-cap submission rejected queue_full "
            f"(retry after {st_d['error']['retry_after_s']}s)")

        st_c = _wait_state(spool, j_c, ("failed", "done"), 120)
        if st_c is None or st_c.get("reason") != "deadline_expired" \
                or client.exit_code_for(st_c) != EXIT_DEADLINE:
            return f"TTL tenant did not expire: {st_c}", verdict
        verdict["queue_ttl_expired"] = True
        say(f"[serveprobe] TTL tenant expired after "
            f"{st_c['error'].get('waited_s')}s in queue")

        for jid, label in ((j_a, "resident"), (j_b, "waiting")):
            st = _wait_state(spool, jid, ("done", "failed"),
                             args.timeout_s)
            if st is None or st.get("state") != "done":
                return f"{label} tenant did not complete: {st}", verdict

        # Transient-crash retry on the same daemon run: the countdown
        # file buys exactly one injected crash; the batch must retry
        # from its last committed generation and stay bit-exact.
        with open(crash_path, "w") as f:
            f.write("1")
        j_e = client.submit(spool, cfgs[1 % len(cfgs)])
        st_e = _wait_state(spool, j_e, ("done", "failed"),
                           args.timeout_s)
        if st_e is None or st_e.get("state") != "done":
            return f"crash-retried tenant did not complete: {st_e}", \
                verdict
        ledger = request(Spool(spool).sock_path, {"op": "ping"})["ledger"]
        if ledger.get("batch_retries", 0) < 1:
            return f"no batch retry recorded in ledger: {ledger}", verdict
        verdict["transient_retried"] = True
        verdict["ledger"] = ledger
        say(f"[serveprobe] injected crash absorbed: "
            f"{ledger['batch_retries']} batch retry(s)")

        # Bit-exactness across ALL resilience paths on this run.
        solo_b = _solo_stream(cfgs[1 % len(cfgs)], args.windows,
                              args.timeout_s, env)
        compared = 0
        for jid, solo in ((j_a, _solo_stream(cfgs[0], 300,
                                             args.timeout_s, env)),
                          (j_b, solo_b), (j_e, solo_b)):
            served = _served_stream(spool, jid)
            common = sorted(set(served) & set(solo))
            if not common:
                return f"job {jid}: no comparable windows", verdict
            bad = [w for w in common if served[w] != solo[w]]
            if bad:
                return (f"job {jid} diverges from solo at window "
                        f"{bad[0]}"), verdict
            compared += len(common)
        verdict["bit_exact_jobs"] = 3
        verdict["windows_compared"] = compared
        say(f"[serveprobe] 3 resilience-path jobs bit-identical to solo "
            f"({compared} windows)")

        daemon.send_signal(signal.SIGTERM)
        rc = daemon.wait(timeout=60)
        if rc != EXIT_SERVE_SHUTDOWN:
            return f"resilience daemon drain rc={rc}", verdict
        return None, verdict
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.serveprobe")
    ap.add_argument("config", help="YAML experiment file (must carry "
                                   "engine metrics_ring + state_digest)")
    ap.add_argument("--seeds", default="5,6",
                    help="comma list: one same-shape job per seed, "
                         "submitted sequentially (cache-hit proof needs "
                         ">= 2)")
    ap.add_argument("--overbudget", default=None, metavar="CFG",
                    help="config expected to FAIL admission (memory "
                         "budget) — e.g. configs/mem_overbudget.yaml")
    ap.add_argument("--mem-bytes", type=int, default=None,
                    help="SHADOW1_MEM_BYTES for the daemon (the CPU "
                         "backend reports no device memory)")
    ap.add_argument("--windows", type=int, default=None)
    ap.add_argument("--resilience", action="store_true",
                    help="also prove the resilience gates on a second "
                         "daemon: waiting_headroom admission, queue_full "
                         "backpressure, --queue-ttl-s expiry and "
                         "injected-transient-crash retry, all "
                         "bit-compared against solo runs")
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--json-only", action="store_true")
    args = ap.parse_args(argv)

    import yaml

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.consts import EXIT_MEMORY, EXIT_SERVE_SHUTDOWN
    from shadow1_tpu.serve.protocol import Spool, request

    say = (lambda *a: None) if args.json_only else (
        lambda *a: print(*a, file=sys.stderr, flush=True))
    work = tempfile.mkdtemp(prefix="serveprobe_")
    spool = os.path.join(work, "spool")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if args.mem_bytes is not None:
        env["SHADOW1_MEM_BYTES"] = str(args.mem_bytes)

    with open(args.config) as f:
        base_doc = yaml.safe_load(f.read())
    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    cfgs = []
    for i, seed in enumerate(seeds):
        doc = json.loads(json.dumps(base_doc))  # deep copy
        doc.setdefault("general", {})["seed"] = seed
        p = os.path.join(work, f"job{i}.yaml")
        with open(p, "w") as f:
            yaml.safe_dump(doc, f)
        cfgs.append(p)

    def fail(msg: str, rc: int = 1, **extra) -> int:
        print(json.dumps({"ok": False, "error": msg, **extra}))
        return rc

    daemon_err = open(os.path.join(work, "daemon.stderr"), "w")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "shadow1_tpu", "serve", "--spool", spool,
         "--poll-s", "0.05"],
        env=env, stdout=subprocess.DEVNULL, stderr=daemon_err)
    try:
        deadline = time.monotonic() + 60
        while Spool(spool).daemon_alive() is None:
            if daemon.poll() is not None or time.monotonic() > deadline:
                return fail(f"daemon did not start (rc={daemon.poll()})")
            time.sleep(0.1)
        say(f"[serveprobe] daemon up (pid {daemon.pid})")

        # ---- sequential same-shape jobs (cache-hit proof) ---------------
        job_ids = []
        for i, cfg in enumerate(cfgs):
            cmd = [sys.executable, "-m", "shadow1_tpu", "submit", cfg,
                   "--spool", spool, "--timeout-s", str(args.timeout_s),
                   "--json-only"]
            if args.windows is not None:
                cmd += ["--windows", str(args.windows)]
            r = subprocess.run(cmd, env=env, capture_output=True,
                               text=True, timeout=args.timeout_s + 30)
            if r.returncode != 0:
                return fail(f"job {i} (seed {seeds[i]}) failed "
                            f"rc={r.returncode}", stderr=r.stderr[-500:])
            final = json.loads(r.stdout.strip().splitlines()[-1])
            job_ids.append(final["job"])
            say(f"[serveprobe] job {i} done: {final['job']} "
                f"(cache {final.get('cache')})")

        ledger = request(Spool(spool).sock_path,
                         {"op": "ping"})["ledger"]
        if len(seeds) >= 2 and ledger.get("cache_hits", 0) < len(seeds) - 1:
            return fail(f"expected >= {len(seeds) - 1} engine-cache "
                        f"hit(s), ledger says {ledger}", ledger=ledger)

        # ---- over-budget admission rejection ----------------------------
        rejected = None
        if args.overbudget:
            r = subprocess.run(
                [sys.executable, "-m", "shadow1_tpu", "submit",
                 args.overbudget, "--spool", spool,
                 "--timeout-s", str(args.timeout_s), "--json-only"],
                env=env, capture_output=True, text=True,
                timeout=args.timeout_s + 30)
            if r.returncode != EXIT_MEMORY:
                return fail(f"over-budget submit: expected EXIT_MEMORY="
                            f"{EXIT_MEMORY}, got rc={r.returncode}",
                            stderr=r.stderr[-500:])
            rejected = json.loads(r.stdout.strip().splitlines()[-1])
            err = rejected.get("error") or {}
            if err.get("error") != "memory_budget" \
                    or "Remedies" not in (err.get("advice") or ""):
                return fail("over-budget rejection lacks the "
                            "memory_budget advice record", status=rejected)
            say(f"[serveprobe] over-budget job rejected pre-compile "
                f"({err['estimated'] >> 20} MiB est vs "
                f"{err['budget'] >> 20} MiB budget), advice present")

        # ---- digest round-trip vs solo CLI ------------------------------
        mismatches = []
        compared = {}
        for i, (jid, cfg) in enumerate(zip(job_ids, cfgs)):
            served = _served_stream(spool, jid)
            solo = _solo_stream(cfg, args.windows, args.timeout_s, env)
            common = sorted(set(served) & set(solo))
            if not common:
                return fail(f"job {i}: no comparable ring windows "
                            f"(served {len(served)}, solo {len(solo)}) — "
                            f"does the config carry metrics_ring + "
                            f"state_digest?")
            bad = [w for w in common if served[w] != solo[w]]
            compared[jid] = len(common)
            if bad:
                mismatches.append({"job": jid, "first_window": bad[0]})
            say(f"[serveprobe] job {i}: {len(common)} windows compared "
                f"vs solo{' — DIVERGED' if bad else ', bit-identical'}")
        if mismatches:
            print(json.dumps({
                "ok": False, "error": "served digest stream diverges "
                "from the solo CLI run", "mismatches": mismatches,
                "paritytrace": f"python -m shadow1_tpu.tools.paritytrace "
                               f"{args.config} tpu cpu"}))
            return EXIT_DIVERGED

        # ---- graceful shutdown ------------------------------------------
        daemon.send_signal(signal.SIGTERM)
        rc = daemon.wait(timeout=60)
        if rc != EXIT_SERVE_SHUTDOWN:
            return fail(f"daemon drain: expected EXIT_SERVE_SHUTDOWN="
                        f"{EXIT_SERVE_SHUTDOWN}, got rc={rc}")
        say(f"[serveprobe] daemon drained cleanly (rc={rc})")

        resilience = None
        if args.resilience:
            err, resilience = _resilience_phase(cfgs, work, env, args,
                                                say)
            if err:
                return fail(f"resilience gate: {err}",
                            resilience=resilience)
        print(json.dumps({
            "ok": True,
            "jobs": len(job_ids),
            "windows_compared": compared,
            "ledger": ledger,
            "cache_hits": ledger.get("cache_hits", 0),
            "rejected_overbudget": bool(rejected),
            "shutdown_rc": rc,
            "resilience": resilience,
        }))
        return 0
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()
        daemon_err.close()


if __name__ == "__main__":
    sys.exit(main())
