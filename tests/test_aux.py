"""Aux subsystems: DNS registry, pcap capture, logger, tools."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from shadow1_tpu.config.experiment import build_experiment
from shadow1_tpu.consts import MS, SEC


def _doc():
    return {
        "general": {"seed": 3, "stop_time": "2 s"},
        "engine": {"scheduler": "cpu"},
        "hosts": [
            {"name": "server", "count": 1},
            {"name": "client", "count": 3},
        ],
        "app": {
            "model": "filexfer",
            "groups": {
                "server": {"role": 0},
                "client": {"role": 1, "server": "@server", "flow_bytes": 2000,
                           "flow_count": 1, "start_time": "1 ms"},
            },
        },
    }


def test_dns_registry():
    exp, _, _ = build_experiment(_doc())
    dns = exp.dns
    assert dns.resolve("server") == 0
    assert dns.resolve("client-0") == 1 and dns.resolve("client-2") == 3
    assert dns.resolve("client") == 1  # bare group name = first host
    assert dns.reverse(0) == "server" and dns.reverse(3) == "client-2"
    assert dns.vertex_of(2) == 0
    assert len(dns) == 4
    with pytest.raises(KeyError):
        dns.resolve("nonexistent")


def test_pcap_capture(tmp_path):
    from shadow1_tpu.cpu_engine import CpuEngine
    from shadow1_tpu.tools.pcap import PcapWriter

    exp, params, _ = build_experiment(_doc())
    out = tmp_path / "cap.pcap"
    with PcapWriter(str(out)) as w:
        CpuEngine(exp, params, capture=w).run()
        n = w.n_packets
    assert n > 10
    data = out.read_bytes()
    import struct

    magic, _vmaj, _vmin, _tz, _sig, snaplen, linktype = struct.unpack(
        "<IHHiIII", data[:24]
    )
    assert magic == 0xA1B2C3D4 and linktype == 101
    # walk every record; verify IPv4 headers and count
    off, count = 24, 0
    while off < len(data):
        _ts, _us, incl, _orig = struct.unpack("<IIII", data[off:off + 16])
        assert incl <= snaplen
        pkt = data[off + 16: off + 16 + incl]
        assert pkt[0] == 0x45  # IPv4, IHL 5
        off += 16 + incl
        count += 1
    assert count == n


def test_sim_logger_levels(capsys):
    import io

    from shadow1_tpu.log import SimLogger

    buf = io.StringIO()
    log = SimLogger(stream=buf, level="message")
    log.debug("hidden")
    log.message("shown", sim_ns=5 * MS, host=3, extra=1)
    log.error("boom")
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert len(lines) == 2 and log.n_dropped == 1
    assert lines[0]["msg"] == "shown" and lines[0]["host"] == 3
    assert lines[0]["sim_s"] == 0.005 and lines[0]["extra"] == 1


def test_tracker_records_and_report(tmp_path, capsys):
    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.log import tracker_records
    from shadow1_tpu.tools.heartbeat_report import load_records, summarize

    exp, params, _ = build_experiment(_doc())
    eng = Engine(exp, params)
    st = eng.run()
    recs = tracker_records(eng, st)
    assert len(recs) == 4
    assert recs[1]["nic_rx_bytes"] > 0 and recs[0]["nic_tx_bytes"] > 0
    assert recs[0]["rx_bytes"] > 0  # app-level bytes at the server
    assert all("flows_done" in r for r in recs)
    # heartbeat_report consumes a mixed log of heartbeats + tracker records
    log = tmp_path / "run.log"
    hb = {"type": "heartbeat", "sim_time_s": 2.0, "wall_s": 1.0,
          "windows": 100, "events_per_sec": 50.0, "sim_per_wall": 2.0,
          "delta": {"events": 50, "windows": 100, "pkts_delivered": 30}}
    with open(log, "w") as f:
        f.write(json.dumps(hb) + "\n")
        for r in recs:
            f.write(json.dumps(r) + "\n")
    got = load_records(str(log))
    assert len(got) == 5
    s = summarize(got)
    assert s["heartbeats"] == 1 and s["tracker_records"] == 4
    assert s["events"] == 50
