"""Dense per-row update/select primitives — the no-scatter toolkit.

XLA lowers a scatter with dynamic per-row indices (``arr.at[h, col].set``)
to a serialized loop on TPU: measured 4.3 ms for a [4096, 32] single-slot
write and 371 ms for a 131k-element batch scatter — the entire per-window
cost of round 2's engine. Every hot-path "write one slot per row" in this
package therefore goes through these helpers, which express the update as a
one-hot mask + ``where`` (dense, fuses into one cheap elementwise kernel)
instead of a scatter. Reads keep ``take_along_axis`` (gathers are fast).

The semantics are exactly those of ``arr.at[h, col].set(val)`` with an
out-of-range drop: rows where ``mask`` is False (or ``col`` out of range)
are untouched.
"""

from __future__ import annotations

import jax.numpy as jnp


def onehot_col(col, cap: int, mask=None) -> jnp.ndarray:
    """bool [H, cap]: True at (h, col[h]) where mask[h] (and col in range)."""
    sel = jnp.arange(cap, dtype=col.dtype)[None, :] == col[:, None]
    if mask is not None:
        sel = sel & mask[:, None]
    return sel


def set_col(arr, col, val, mask=None):
    """Dense ``arr[h, col[h]] = val[h] where mask[h]`` for [H, C, ...] arrays.

    ``val`` may be scalar or [H] (or [H, ...] matching trailing dims)."""
    sel = onehot_col(col, arr.shape[1], mask)
    val = jnp.asarray(val, arr.dtype)
    if val.ndim == 0:
        return jnp.where(_expand(sel, arr.ndim), val, arr)
    # val [H] or [H, trailing...] -> broadcast over the slot axis.
    val = jnp.expand_dims(val, 1)
    return jnp.where(_expand(sel, arr.ndim), val, arr)


def add_col(arr, col, val, mask=None):
    """Dense ``arr[h, col[h]] += val[h] where mask[h]``."""
    sel = onehot_col(col, arr.shape[1], mask)
    val = jnp.asarray(val, arr.dtype)
    if val.ndim >= 1:
        val = jnp.expand_dims(val, 1)
    return arr + jnp.where(_expand(sel, arr.ndim), val, jnp.zeros((), arr.dtype))


def get_col(arr, col):
    """Gather ``arr[h, col[h]]`` (col clipped into range; gathers are cheap)."""
    c = jnp.clip(col, 0, arr.shape[1] - 1)
    idx = c.reshape(c.shape + (1,) * (arr.ndim - 1))
    return jnp.take_along_axis(arr, idx, axis=1)[:, 0]


def first_true(m) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row first True of a bool [H, C]: (any[H], onehot [H, C])."""
    sel = m & (jnp.cumsum(m, axis=1) == 1)
    return m.any(axis=1), sel


def _expand(sel, ndim):
    return sel.reshape(sel.shape + (1,) * (ndim - sel.ndim))
