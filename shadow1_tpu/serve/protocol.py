"""Serve wire protocol — the job spool layout and the socket framing.

One source of truth for everything the daemon and the client must agree
on: where job files live, what a job/status record contains, which states
a job moves through, and how JSON lines frame the Unix-socket requests.
Both transports are CI-testable on CPU; the spool alone is sufficient
(the socket is a convenience for streaming watches and liveness checks —
every submission lands as a spool file either way, so there is exactly
one accept path for the daemon to make atomic).

Spool layout (``--spool DIR``)::

    DIR/
      inbox/<job_id>.json     submissions (written via write_json_atomic:
                              fsynced temp + rename — a client or daemon
                              killed mid-submit can never leave a torn
                              job record; ``.tmp`` files are invisible to
                              the scan)
      jobs/<job_id>/job.json      the accepted job record (atomic move
                                  out of the inbox — accept is one
                                  os.replace, kill-safe)
      jobs/<job_id>/status.json   current state (atomic rewrite at every
                                  transition)
      jobs/<job_id>/result.jsonl  the per-job record stream: ring rows
                                  (digest words included), quarantine /
                                  finalize events, the final fleet_exp
      batches/                    in-flight batch checkpoints (lineage
                                  generations; evicted batches resume
                                  from here)
      queue.json              persisted scheduler state (graceful
                              shutdown / restart)
      serve.log               the daemon's own JSONL event stream
                              (REC_SERVE / REC_SERVE_JOB records —
                              tools/heartbeat_report.py's serve section)
      daemon.json             daemon liveness: host / pid / socket path /
                              start / heartbeat (mtime refreshed every
                              HEARTBEAT_S — the stale-lock protocol)
      daemon.lock             fcntl flock held for the daemon's lifetime
                              (kernel-released on death; never parsed)
      serve.sock              the Unix socket

Deliberately jax-free: the client, report tools and tests import this
without paying an accelerator import.
"""

from __future__ import annotations

import fcntl
import json
import os
import socket
import time

from shadow1_tpu.lineage import write_json_atomic

SPOOL_VERSION = 1

# Job lifecycle states (the serve_job records' ``state`` field).
J_QUEUED = "queued"      # admitted; waiting for a lane
J_WAITING = "waiting_headroom"  # admitted (fits an idle device) but the
#                          resident batch leaves too little live headroom;
#                          scheduled as soon as resident bytes drain —
#                          never rejected just because someone else runs
J_RUNNING = "running"    # riding a lane of the in-flight fleet batch
J_DONE = "done"          # finished; final fleet_exp in result.jsonl
J_FAILED = "failed"      # quarantined lane / runtime error / deadline
#                          expiry / retries exhausted (reason says)
J_REJECTED = "rejected"  # refused at admission (config / memory budget /
#                          queue_full backpressure)
J_EVICTED = "evicted"    # preempted by a higher-priority tenant;
#                          automatically requeued (transient state —
#                          the job returns to queued with its batch
#                          checkpoint as the resume cursor)
TERMINAL_STATES = (J_DONE, J_FAILED, J_REJECTED)

# Spool-lock liveness protocol (NFS-safe ownership). The daemon holds an
# fcntl flock on DIR/daemon.lock for its whole lifetime — on one host,
# kernel lock release on process death makes takeover race-free. Across
# hosts (an NFS spool where flock may not propagate) daemon.json's
# host/pid plus a heartbeat (the daemon touches daemon.json's mtime every
# HEARTBEAT_S) decide: same host → the pid check is authoritative;
# different host → a heartbeat older than STALE_AFTER_S marks the holder
# dead and the spool reclaimable.
HEARTBEAT_S = 5.0
STALE_AFTER_S = 30.0


def new_job_id() -> str:
    """Collision-safe, sortable-by-submission job id."""
    return f"{time.time_ns():016x}-{os.urandom(3).hex()}"


class Spool:
    """Path arithmetic + atomic record IO for one spool directory."""

    def __init__(self, root: str):
        self.root = root
        self.inbox = os.path.join(root, "inbox")
        self.jobs = os.path.join(root, "jobs")
        self.batches = os.path.join(root, "batches")
        self.queue_path = os.path.join(root, "queue.json")
        self.log_path = os.path.join(root, "serve.log")
        self.daemon_path = os.path.join(root, "daemon.json")
        self.lock_path = os.path.join(root, "daemon.lock")
        self.sock_path = os.path.join(root, "serve.sock")

    def ensure(self) -> "Spool":
        for d in (self.root, self.inbox, self.jobs, self.batches):
            os.makedirs(d, exist_ok=True)
        return self

    # -- job paths ---------------------------------------------------------

    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.jobs, job_id)

    def job_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), "job.json")

    def status_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), "status.json")

    def result_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), "result.jsonl")

    # -- submission (client side) -----------------------------------------

    def submit(self, job: dict) -> str:
        """Write ``job`` into the inbox atomically; returns the job id.
        The ONLY submission path — the socket's submit op calls this too,
        so a kill at any instant leaves either no file or a whole one."""
        job_id = job.get("id") or new_job_id()
        job = {**job, "id": job_id, "spool_version": SPOOL_VERSION}
        os.makedirs(self.inbox, exist_ok=True)
        write_json_atomic(os.path.join(self.inbox, job_id + ".json"), job)
        return job_id

    def scan_inbox(self) -> list[tuple[str, dict | None]]:
        """(path, job-or-None) for every inbox entry, submission order.
        ``None`` marks an unparseable file (hand-written, wrong schema) —
        the atomic-write contract means it was never OUR torn write, so
        the daemon rejects it instead of crashing on it."""
        try:
            names = sorted(os.listdir(self.inbox))
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(".json"):
                continue  # .tmp of an in-flight atomic write, stray files
            path = os.path.join(self.inbox, name)
            try:
                with open(path) as f:
                    job = json.load(f)
                if not isinstance(job, dict) or "config_yaml" not in job:
                    job = None
            except (OSError, ValueError):
                job = None
            out.append((path, job))
        return out

    def accept(self, inbox_path: str, job: dict) -> None:
        """Move an inbox submission into its job directory — one
        os.replace, so a daemon killed mid-accept leaves the record
        intact on exactly one side, never torn or duplicated."""
        os.makedirs(self.job_dir(job["id"]), exist_ok=True)
        os.replace(inbox_path, self.job_path(job["id"]))

    # -- status / results --------------------------------------------------

    def write_status(self, job_id: str, status: dict) -> None:
        os.makedirs(self.job_dir(job_id), exist_ok=True)
        write_json_atomic(self.status_path(job_id),
                          {"type": "serve_job", "job": job_id,
                           "updated_at": time.time(), **status})

    def read_status(self, job_id: str) -> dict | None:
        try:
            with open(self.status_path(job_id)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def append_result(self, job_id: str, rec: dict) -> None:
        os.makedirs(self.job_dir(job_id), exist_ok=True)
        with open(self.result_path(job_id), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def read_results(self, job_id: str) -> list[dict]:
        try:
            with open(self.result_path(job_id)) as f:
                return [json.loads(line) for line in f if line.strip()]
        except OSError:
            return []

    # -- daemon liveness / spool ownership ---------------------------------

    def daemon_info(self) -> dict | None:
        try:
            with open(self.daemon_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def acquire_lock(self) -> int | None:
        """Take the spool's fcntl lock (DIR/daemon.lock) non-blocking;
        returns the held fd — the caller keeps it open for the daemon's
        lifetime (the kernel releases it on ANY process death, including
        SIGKILL) — or None when a live same-host daemon already holds it.
        Holding the flock alone is not ownership: an NFS holder on
        another host may not be visible through flock, so the caller must
        still consult :meth:`holder_liveness` before reclaiming."""
        fd = os.open(self.lock_path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return None
        return fd

    def holder_liveness(self, stale_after_s: float = STALE_AFTER_S
                        ) -> tuple[str, dict | None]:
        """('absent'|'live'|'stale', daemon.json info) — the heartbeat /
        pid stale-lock protocol. Same host: a dead pid is stale no matter
        how fresh the heartbeat (a SIGKILLed daemon can't clean up); a
        live pid counts only with a fresh heartbeat, guarding against pid
        recycling. Different host (NFS spool): the heartbeat mtime is the
        only signal — fresh means live, stale means reclaimable."""
        info = self.daemon_info()
        if not info:
            return "absent", None
        hb = 0.0
        for key in ("heartbeat_at", "started_at"):
            try:
                hb = max(hb, float(info.get(key) or 0))
            except (TypeError, ValueError):
                pass
        try:
            hb = max(hb, os.path.getmtime(self.daemon_path))
        except OSError:
            pass
        fresh = (time.time() - hb) < stale_after_s
        same_host = info.get("host") in (None, socket.gethostname())
        if same_host:
            try:
                os.kill(int(info["pid"]), 0)
            except (OSError, ValueError, KeyError, TypeError):
                return "stale", info
            return ("live" if fresh else "stale"), info
        return ("live" if fresh else "stale"), info

    def touch_heartbeat(self) -> None:
        """Refresh the liveness heartbeat (daemon.json's mtime — the
        cross-host half of the stale-lock protocol)."""
        try:
            os.utime(self.daemon_path)
        except OSError:
            pass

    def daemon_alive(self) -> dict | None:
        """The live daemon's info record, or None. Stale daemon.json
        (dead pid, or a heartbeat past STALE_AFTER_S — a SIGKILLed
        daemon can't clean up) reads as absent, so a restart can always
        take the spool over."""
        liveness, info = self.holder_liveness()
        return info if liveness == "live" else None


# ---------------------------------------------------------------------------
# Socket framing: newline-delimited JSON, request → response(s). Ops:
#   {"op": "ping"}                → {"ok": true, "ledger": {...}}
#   {"op": "submit", "job": {..}} → {"ok": true, "id": "..."}
#   {"op": "status", "id": "..."} → the job's status record
#   {"op": "watch",  "id": "..."} → status records streamed until terminal
#   {"op": "shutdown"}            → {"ok": true}; daemon drains + exits
# ---------------------------------------------------------------------------

def send_line(sock_file, obj: dict) -> None:
    sock_file.write(json.dumps(obj) + "\n")
    sock_file.flush()


def request(sock_path: str, obj: dict, timeout_s: float = 10.0) -> dict:
    """One request → one response over the daemon's Unix socket."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout_s)
        s.connect(sock_path)
        f = s.makefile("rw", encoding="utf-8")
        send_line(f, obj)
        line = f.readline()
        if not line:
            raise ConnectionError("daemon closed the connection")
        return json.loads(line)
