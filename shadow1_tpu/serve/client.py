"""Submit client — ``python -m shadow1_tpu submit CONFIG --spool DIR``.

Submits one standard YAML experiment config to a serve daemon, streams
its status transitions to stderr, tails the per-job record stream
(ring/digest rows, the final ``fleet_exp``) to stdout, and exits the
solo CLI's taxonomy: ``EXIT_OK`` on success, ``EXIT_CONFIG`` for a
config rejection, ``EXIT_MEMORY`` for an admission (memory-budget)
rejection, ``EXIT_CAPACITY`` when the job's lane was quarantined on a
capacity halt, ``EXIT_QUEUE_FULL`` for a backpressure rejection (the
record carries ``retry_after_s`` — back off and resubmit), and
``EXIT_DEADLINE`` when --queue-ttl-s / --deadline-s expired the job —
so scripting against the daemon reads exactly like scripting against
``python -m shadow1_tpu``.

Submission always lands as an atomic spool-inbox file (ONE accept path
for the daemon to make kill-safe); the Unix socket, when live, is used
to nudge the scheduler and to stream status without polling. jax-free —
submitting costs no accelerator import.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

from shadow1_tpu.consts import (
    EXIT_CAPACITY,
    EXIT_CONFIG,
    EXIT_DEADLINE,
    EXIT_MEMORY,
    EXIT_OK,
    EXIT_QUEUE_FULL,
)
from shadow1_tpu.serve.protocol import (
    J_DONE,
    J_FAILED,
    J_REJECTED,
    TERMINAL_STATES,
    Spool,
    new_job_id,
    request,
)


def exit_code_for(status: dict) -> int:
    """Terminal job status → the solo CLI's exit taxonomy."""
    state = status.get("state")
    if state == J_DONE:
        return EXIT_OK
    err = status.get("error") or {}
    kind = err.get("error")
    if state == J_REJECTED:
        if kind == "queue_full":
            return EXIT_QUEUE_FULL
        return EXIT_MEMORY if kind == "memory_budget" else EXIT_CONFIG
    if state == J_FAILED:
        if status.get("reason") == "deadline_expired" \
                or kind == "deadline_expired":
            return EXIT_DEADLINE
        if status.get("reason") == "capacity" or kind == "capacity":
            return EXIT_CAPACITY
        if status.get("reason") == "memory_exhausted" \
                or kind == "memory_exhausted":
            return EXIT_MEMORY
    return 1


def request_retry(sock_path: str, obj: dict, attempts: int = 4,
                  base_s: float = 0.05, timeout_s: float = 10.0,
                  say=None) -> dict:
    """``protocol.request`` with bounded reconnect: OSError /
    ConnectionError retries with jittered exponential backoff (a daemon
    mid-restart, a flapping socket), and a success after a failure
    surfaces a ``reconnected`` stderr event so tenants can SEE the flap
    instead of silently degrading. Raises the last error when every
    attempt fails."""
    say = say or (lambda *a: None)
    last = None
    for attempt in range(max(int(attempts), 1)):
        if attempt:
            delay = base_s * (2 ** (attempt - 1))
            time.sleep(delay * (0.5 + random.random()))
        try:
            out = request(sock_path, obj, timeout_s=timeout_s)
        except (OSError, ConnectionError, ValueError) as e:
            last = e
            continue
        if attempt:
            evt = {"type": "serve", "event": "reconnected",
                   "attempt": attempt + 1, "sock": sock_path}
            print(json.dumps(evt), file=sys.stderr, flush=True)
            say(f"[submit] reconnected to {sock_path} "
                f"(attempt {attempt + 1})")
        return out
    raise last if last is not None else ConnectionError(
        f"no response from {sock_path}")


def watch(sock_path: str, job_id: str, on_status=None,
          timeout_s: float = 600.0, attempts: int = 4,
          base_s: float = 0.1, say=None) -> dict | None:
    """Stream a job's status transitions over the daemon's watch op,
    reconnecting (bounded, jittered backoff) when the stream breaks
    mid-flight; a reconnect surfaces the same ``reconnected`` stderr
    event as :func:`request_retry`. Returns the terminal status, or
    None when the socket path is exhausted — callers fall back to spool
    polling (await_job), which needs no daemon at all."""
    import socket as socketlib

    say = say or (lambda *a: None)
    deadline = time.monotonic() + timeout_s
    failures = 0
    while time.monotonic() < deadline and failures < max(int(attempts), 1):
        try:
            with socketlib.socket(socketlib.AF_UNIX,
                                  socketlib.SOCK_STREAM) as s:
                s.settimeout(max(deadline - time.monotonic(), 1.0))
                s.connect(sock_path)
                f = s.makefile("rw", encoding="utf-8")
                f.write(json.dumps({"op": "watch", "id": job_id}) + "\n")
                f.flush()
                if failures:
                    evt = {"type": "serve", "event": "reconnected",
                           "attempt": failures + 1, "sock": sock_path}
                    print(json.dumps(evt), file=sys.stderr, flush=True)
                    say(f"[submit] reconnected to {sock_path} "
                        f"(attempt {failures + 1})")
                    failures = 0
                while time.monotonic() < deadline:
                    line = f.readline()
                    if not line:
                        raise ConnectionError("watch stream closed")
                    st = json.loads(line)
                    if st.get("ok") is False:
                        return None  # daemon-side refusal; fall back
                    if on_status is not None:
                        on_status(st)
                    if st.get("state") in TERMINAL_STATES:
                        return st
        except (OSError, ConnectionError, ValueError):
            failures += 1
            delay = base_s * (2 ** (failures - 1))
            time.sleep(delay * (0.5 + random.random()))
    return None


class _ResultTail:
    """Incremental reader of a job's append-only result.jsonl: remembers
    the byte offset of the last complete line, so each poll reads only
    the new tail instead of re-parsing the whole stream (a long job
    accumulates thousands of ring rows). A daemon restarted after a
    SIGKILL truncates and rewrites the file from scratch — a shrinking
    file resets the offset."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self._ino = None

    def new_records(self) -> list[dict]:
        try:
            stat = os.stat(self.path)
        except OSError:
            return []
        if stat.st_ino != self._ino or stat.st_size < self.offset:
            # A different inode (the daemon's from-scratch rerun removed
            # and rewrote the file — size alone can already have regrown
            # past the old offset by the time we poll) or a shrink: start
            # over from byte 0.
            self.offset = 0
            self._ino = stat.st_ino
        if stat.st_size == self.offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            chunk = f.read()
        # Consume only whole lines; a partially-appended tail stays for
        # the next poll (writes are line-atomic on close, but a read can
        # land mid-append).
        cut = chunk.rfind(b"\n") + 1
        self.offset += cut
        out = []
        for line in chunk[:cut].splitlines():
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out


def await_job(spool: Spool, job_id: str, timeout_s: float = 600.0,
              poll_s: float = 0.2, on_status=None,
              stream_results=None) -> dict:
    """Poll the spool until the job reaches a terminal state; returns the
    final status. ``on_status`` sees every observed transition;
    ``stream_results`` sees each result record once, as it lands."""
    deadline = time.monotonic() + timeout_s
    last = None
    tail = _ResultTail(spool.result_path(job_id))
    while True:
        if stream_results is not None:
            for rec in tail.new_records():
                stream_results(rec)
        st = spool.read_status(job_id)
        if st is not None and st != last:
            if on_status is not None:
                on_status(st)
            last = st
        if st is not None and st.get("state") in TERMINAL_STATES:
            if stream_results is not None:
                for rec in tail.new_records():
                    stream_results(rec)
            return st
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"job {job_id} not terminal after {timeout_s}s "
                f"(last state: {(st or {}).get('state')!r})")
        time.sleep(poll_s)


def submit(spool_dir: str, config_path: str, priority: int = 0,
           windows: int | None = None, job_id: str | None = None,
           queue_ttl_s: float | None = None,
           deadline_s: float | None = None) -> str:
    """Submit one config; returns the job id. Spool-file submission with
    a socket nudge when the daemon is live."""
    spool = Spool(spool_dir)
    with open(config_path) as f:
        config_yaml = f.read()
    job = {
        "id": job_id or new_job_id(),
        "config_yaml": config_yaml,
        "base_dir": os.path.dirname(os.path.abspath(config_path)),
        "config_name": os.path.basename(config_path),
        "priority": int(priority),
        "submitted_at": time.time(),
    }
    if windows is not None:
        job["windows"] = int(windows)
    if queue_ttl_s is not None:
        job["queue_ttl_s"] = float(queue_ttl_s)
    if deadline_s is not None:
        job["deadline_s"] = float(deadline_s)
    jid = spool.submit(job)
    info = spool.daemon_alive()
    if info:
        try:  # nudge only — the inbox file IS the submission
            request_retry(info.get("sock", spool.sock_path),
                          {"op": "ping"}, attempts=3, timeout_s=2.0)
        except (OSError, ValueError, ConnectionError):
            pass
    return jid


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shadow1_tpu submit",
        description="submit a job to a serve daemon and await the result")
    ap.add_argument("config", help="YAML experiment file")
    ap.add_argument("--spool", required=True, metavar="DIR",
                    help="the daemon's spool directory")
    ap.add_argument("--priority", type=int, default=0,
                    help="scheduling priority (higher preempts: a "
                         "strictly-higher submission EVICTS a running "
                         "batch through the preemption plane)")
    ap.add_argument("--windows", type=int, default=None,
                    help="run only this many conservative windows")
    ap.add_argument("--queue-ttl-s", type=float, default=None,
                    metavar="S",
                    help="expire the job if it has not STARTED within S "
                         "seconds of admission (terminal "
                         "deadline_expired record, EXIT_DEADLINE)")
    ap.add_argument("--deadline-s", type=float, default=None, metavar="S",
                    help="bound the job's running wall time: past S the "
                         "daemon drains it at the next chunk boundary — "
                         "the result stream keeps the committed prefix "
                         "(bit-identical to the same prefix of a solo "
                         "run) and the job exits EXIT_DEADLINE")
    ap.add_argument("--no-wait", action="store_true",
                    help="submit and print the job id without awaiting")
    ap.add_argument("--timeout-s", type=float, default=600.0,
                    help="--wait deadline")
    ap.add_argument("--json-only", action="store_true",
                    help="suppress status prose on stderr")
    args = ap.parse_args(argv)

    spool = Spool(args.spool)
    if not os.path.isdir(spool.root):
        print(f"submit: spool {spool.root} does not exist (start the "
              f"daemon first: python -m shadow1_tpu serve --spool "
              f"{spool.root})", file=sys.stderr, flush=True)
        return EXIT_CONFIG
    job_id = submit(args.spool, args.config, priority=args.priority,
                    windows=args.windows, queue_ttl_s=args.queue_ttl_s,
                    deadline_s=args.deadline_s)
    if not args.json_only:
        print(f"[submit] job {job_id} -> {spool.root}"
              + ("" if spool.daemon_alive() else
                 " (no live daemon — it will run on the next start)"),
              file=sys.stderr, flush=True)
    if args.no_wait:
        print(json.dumps({"type": "serve_job", "job": job_id,
                          "state": "submitted"}))
        return EXIT_OK

    say = (lambda *a: None) if args.json_only else (
        lambda *a: print(*a, file=sys.stderr, flush=True))

    def on_status(st):
        say(f"[submit] {job_id}: {st.get('state')}"
            + (f" (lane {st['lane']}/{st['lanes']}, cache "
               f"{st.get('cache')})" if st.get("state") == "running"
               and "lane" in st else ""))

    # Status prose rides the socket watch when a daemon is live (prompt
    # transitions + visible reconnects on flaps); completion and the
    # result stream ALWAYS come from the spool files — the path that
    # needs no daemon and survives restarts.
    info = spool.daemon_alive()
    if info:
        import threading

        threading.Thread(
            target=watch,
            args=(info.get("sock", spool.sock_path), job_id),
            kwargs={"on_status": on_status, "timeout_s": args.timeout_s,
                    "say": say},
            daemon=True).start()
        poll_status = None
    else:
        poll_status = on_status

    try:
        final = await_job(
            spool, job_id, timeout_s=args.timeout_s,
            on_status=poll_status,
            stream_results=lambda rec: print(json.dumps(rec), flush=True))
    except TimeoutError as e:
        print(f"submit: {e}", file=sys.stderr, flush=True)
        return 1
    if final.get("state") == J_REJECTED:
        err = final.get("error") or {}
        say(f"[submit] rejected: "
            f"{err.get('message') or err.get('advice') or err}")
    print(json.dumps(final))
    return exit_code_for(final)


if __name__ == "__main__":
    sys.exit(main())
