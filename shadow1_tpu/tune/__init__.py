"""Occupancy-driven capacity tuning (the Eiffel/Laminar right-sizing loop).

Every pop/push/clear in the round path is a full ``[cap, H]`` plane pass,
so buffer caps multiply the cost of the whole engine (docs/PERF.md "cap
economics"). This package closes the measure→size loop the telemetry ring
opened:

* ``ladder``  — the geometric cap ladder every tuned cap is quantized to
  (bounds the number of distinct static shapes, hence jit recompiles);
* ``resize``  — bit-exact host-side migration of the event-buffer/outbox
  SoA planes to a new capacity (pad free slots to grow; compact-and-
  truncate occupied slots to shrink — pop order is decided by the
  (time, tb) keys, not slot index, so migration cannot reorder events);
* ``autocap`` — the between-chunk controller behind ``--auto-caps``:
  reads the run-max fill gauges at chunk boundaries (state is already on
  host for the drain), grows before overflow, shrinks after sustained low
  occupancy, and re-jits at the new static shape.

``tools/captune.py`` is the offline half: it reads a finished run's ring
JSONL / final-metrics record and prints recommended ``engine:`` settings.
"""

from shadow1_tpu.tune.autocap import CapController, CapPolicy
from shadow1_tpu.tune.ladder import cap_ladder, next_step, quantize_cap, recommend_cap
from shadow1_tpu.tune.resize import resize_evbuf, resize_outbox, resize_state

__all__ = [
    "CapController",
    "CapPolicy",
    "cap_ladder",
    "next_step",
    "quantize_cap",
    "recommend_cap",
    "resize_evbuf",
    "resize_outbox",
    "resize_state",
]
