"""Chunked fleet runner — heartbeats, rings, checkpoints, final records.

The fleet twin of ``obs.run_with_heartbeat`` + the CLI's final-JSON
assembly, built per-experiment from the ground up:

* the telemetry ring drains PER EXPERIMENT (``type: "ring"`` records with
  an ``exp`` field — the per-window series and digest words of lane e are
  exactly a solo run's, docs/OBSERVABILITY.md §"Fleet records");
* heartbeats carry the fleet-aggregate deltas plus a compact per-
  experiment events vector (one record per chunk, not E);
* ``--on-overflow halt`` and ``--selfcheck`` run their boundary checks
  per experiment — a CapacityExceededError names the experiment (and its
  seed) whose cap overflowed;
* checkpoints snapshot the WHOLE fleet state (one .npz, every leaf with
  its leading [E] axis) at heartbeat boundaries, same atomic write +
  progress sidecar as the solo path — a resumed fleet continues
  bit-identically, and ``fleet.engine.slice_experiment`` extracts any one
  lane as a solo-resumable state.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from shadow1_tpu.consts import SEC
from shadow1_tpu.telemetry.registry import DROP_FIELDS, normalize


class FleetHeartbeat:
    """Per-chunk fleet heartbeat: aggregate deltas + per-experiment events.

    One record per chunk boundary (type ``heartbeat`` with a ``fleet``
    block), so existing consumers (tools/heartbeat_report.py) read the
    aggregate series unchanged while fleet-aware ones use the block."""

    def __init__(self, engine, stream=None, initial_state=None,
                 emit_heartbeat=True, emit_ring=True):
        self.engine = engine
        self.stream = stream if stream is not None else sys.stderr
        self.emit_heartbeat = emit_heartbeat
        self.emit_ring = emit_ring
        self.t_start = time.perf_counter()
        self.t_last = self.t_start
        self.last = (normalize(engine.metrics_dict(initial_state))
                     if initial_state is not None else {})
        self.last_per_exp = (engine.metrics_per_exp(initial_state)
                             if initial_state is not None else None)
        self._ring_next = self.last.get("windows", 0)
        self.records: list[dict] = []
        self.ring_records: list[dict] = []

    def _emit(self, rec: dict) -> None:
        if self.stream:
            print(json.dumps(rec), file=self.stream, flush=True)

    def __call__(self, st, done_windows: int, per_exp=None) -> None:
        now = time.perf_counter()
        m = normalize(self.engine.metrics_dict(st))
        # The chunk runner already fetched the per-experiment dicts for its
        # halt/selfcheck boundary checks — reuse them, don't re-sync.
        if per_exp is None:
            per_exp = self.engine.metrics_per_exp(st)
        ring_recs = self.engine.drain_rings(st, start=self._ring_next)
        self._ring_next = m.get("windows", 0)
        delta = {k: v - self.last.get(k, 0) for k, v in m.items()
                 if isinstance(v, int)}
        dt = now - self.t_last
        d_windows = delta.get("windows", 0)
        ev_per_exp = [int(d["events"]) for d in per_exp]
        if self.last_per_exp is not None:
            ev_per_exp = [e - int(l["events"]) for e, l in
                          zip(ev_per_exp, self.last_per_exp)]
        rec = {
            "type": "heartbeat",
            "sim_time_s": round(int(np.asarray(st.win_start).max()) / SEC, 6),
            "wall_s": round(now - self.t_start, 3),
            "windows": done_windows,
            "events_per_sec": round(delta.get("events", 0) / dt, 1)
            if dt > 0 else None,
            "rounds_per_window": round(delta.get("rounds", 0) / d_windows, 2)
            if d_windows else None,
            "delta": delta,
            "fleet": {
                "experiments": self.engine.n_exp,
                "events_per_exp": ev_per_exp,
            },
        }
        drops = {f: delta.pop(f, 0) for f in DROP_FIELDS}
        rec["drops"] = {"total": sum(drops.values()), **drops}
        self.records.append(rec)
        if self.emit_heartbeat:
            self._emit(rec)
        for r in ring_recs:
            self.ring_records.append(r)
            if self.emit_ring:
                self._emit(r)
        self.t_last = now
        self.last = m
        self.last_per_exp = per_exp


def _check_halt(engine, plan_labels, per_exp, prev_per_exp, done, step):
    """Per-experiment overflow halt: the first lane with fresh overflow
    raises a CapacityExceededError that names it."""
    from shadow1_tpu.txn import CapacityExceededError
    from shadow1_tpu.tune.ladder import recommend_cap

    checks = (("ev_overflow", "ev_cap", "ev_max_fill"),
              ("ob_overflow", "outbox_cap", "ob_max_fill"))
    for e, m in enumerate(per_exp):
        prev = prev_per_exp[e] if prev_per_exp else {}
        for counter, knob, gauge in checks:
            fresh = int(m.get(counter, 0)) - int(prev.get(counter, 0))
            if fresh > 0:
                label = plan_labels[e] if plan_labels else {"exp": e}
                gv = int(m.get(gauge, 0))
                raise CapacityExceededError(
                    knob=knob, counter=counter,
                    cap=getattr(engine.params, knob), overflow=fresh,
                    window_range=(done, done + step),
                    recommended=recommend_cap(gv) if gv else None,
                    detail=(f" (fleet experiment {label.get('exp', e)}, "
                            f"seed {label.get('seed', '?')})"),
                    # The solo remedies (--on-overflow retry / --auto-caps)
                    # are themselves rejected under --fleet — advise only
                    # what works there.
                    remedy=("(--on-overflow retry and --auto-caps are not "
                            "available under --fleet; caps are "
                            "fleet-uniform) — or size the whole sweep from "
                            "a recorded run: python -m "
                            "shadow1_tpu.tools.captune <run.log>"),
                )


def run_fleet(engine, st=None, n_windows=None, every_windows=None,
              stream=None, ckpt_path=None, ckpt_every_s=120.0,
              emit_heartbeat=True, emit_ring=True, selfcheck=False,
              labels=None, ckpt_keep=3, drain=None):
    """Run the fleet in chunks. Returns (final_state, FleetHeartbeat).

    Mirrors ``obs.run_with_heartbeat``: compile excluded from the first
    chunk's rate, checkpoints rotated through a ``ckpt_keep``-deep
    generation set (lineage.Lineage) and throttled to ``ckpt_every_s``,
    the ``.progress`` sidecar refreshed atomically at EVERY chunk boundary
    (the watchdog's liveness signal), per-experiment halt / selfcheck
    boundary checks, and the same signal plane: a pending drain request
    (``drain``) forces the snapshot and raises preempt.PreemptedExit."""
    import jax

    from shadow1_tpu import ckpt as _ckpt
    from shadow1_tpu.lineage import Lineage, write_json_atomic
    from shadow1_tpu.preempt import run_injection_hooks

    total = n_windows if n_windows is not None else engine.n_windows
    if every_windows is None:
        every_windows = max(total // 10, 1)
    if st is None:
        st = engine.init_state()
    try:
        jax.block_until_ready(engine.run(st, n_windows=0))
    except Exception as e:
        from shadow1_tpu import mem

        # OOM taxonomy: this warmup is the compile — tag exhaustion here
        # so the CLI's memory record reports the phase (mem.py).
        if mem.is_oom(e):
            e.shadow1_oom_phase = "compile"
        raise
    hb = FleetHeartbeat(engine, stream=stream, initial_state=st,
                        emit_heartbeat=emit_heartbeat, emit_ring=emit_ring)
    halt = engine.params.on_overflow == "halt"
    prev_per_exp = engine.metrics_per_exp(st)
    lineage = Lineage(ckpt_path, keep=ckpt_keep) if ckpt_path else None
    last_save = time.perf_counter()
    last_done = [0]
    last_seq = [None]

    def on_chunk(s, done):
        nonlocal prev_per_exp
        step = done - last_done[0]
        last_done[0] = done
        per_exp = engine.metrics_per_exp(s)
        if halt:
            _check_halt(engine, labels, per_exp, prev_per_exp,
                        done - step, step)
        if selfcheck:
            from shadow1_tpu.txn import check_boundary_identity

            for e, m in enumerate(per_exp):
                check_boundary_identity(
                    m, where=(f"fleet experiment {e}, chunk boundary, "
                              f"window {m.get('windows', 0)}"))
        prev_per_exp = per_exp
        hb(s, done, per_exp=per_exp)
        sim_ns = int(np.asarray(s.win_start).max())
        # Fault/preemption/hang injection (preempt.run_injection_hooks) —
        # the same chunk-boundary contract as obs.run_with_heartbeat, so
        # the supervisor, drain and watchdog paths are all testable
        # fleet-shaped too. Inert without the env vars.
        run_injection_hooks(sim_ns)
        nonlocal last_save
        now = time.perf_counter()
        draining = drain is not None and drain.requested
        saved = False
        if lineage is not None and (done >= total or draining
                                    or now - last_save > ckpt_every_s):
            last_seq[0] = lineage.save(
                s, {"win_start": sim_ns, "done_windows": done})
            last_save = now
            saved = True
        if ckpt_path:
            write_json_atomic(ckpt_path + ".progress",
                              {"done_windows": done, "total": total,
                               "win_start": sim_ns, "seq": last_seq[0]})
        crash_at = os.environ.get("SHADOW1_OBS_CRASH_AT_NS")
        if saved and crash_at is not None and sim_ns == int(crash_at):
            os._exit(41)

    st = _ckpt.run_chunked(engine, st, n_windows=total, chunk=every_windows,
                           on_chunk=on_chunk, drain=drain)
    return st, hb


def final_records(engine, st, labels, n_windows, wall, resumed=False,
                  metrics0=None):
    """The CLI's end-of-run output: one ``fleet_exp`` record per
    experiment plus one ``fleet_summary`` — schemas in
    docs/OBSERVABILITY.md §"Fleet records". ``metrics0`` (per-exp dicts
    from a resumed snapshot) baselines rates to THIS invocation like the
    solo CLI."""
    per_exp = engine.metrics_per_exp(st)
    params = engine.params
    caps = {"ev_cap": params.ev_cap, "outbox_cap": params.outbox_cap,
            "compact_cap": params.compact_cap}
    sim_s = n_windows * engine.window / 1e9
    recs = []
    ev_run_total = 0
    for e, m in enumerate(per_exp):
        label = labels[e] if labels else {"exp": e}
        ev0 = metrics0[e].get("events", 0) if metrics0 else 0
        ev_run = m["events"] - ev0
        ev_run_total += ev_run
        drops = {f: int(m.get(f, 0)) for f in DROP_FIELDS}
        rec = {
            "type": "fleet_exp",
            **label,
            "engine": "fleet",
            "hosts": engine.exp.n_hosts,
            "window_ns": engine.window,
            "windows": n_windows,
            "caps": caps,
            "metrics": m,
            "drops": {"total": sum(drops.values()), **drops},
        }
        restarts = int(m.get("host_restarts", 0))
        fault_drops = {k: drops[k] for k in
                       ("down_events", "down_pkts", "link_down_pkts")}
        if restarts or any(fault_drops.values()):
            rec["faults"] = {"host_restarts": restarts, **fault_drops}
        recs.append(rec)
    agg = engine.metrics_dict(st)
    summary = {
        "type": "fleet_summary",
        "engine": "fleet",
        "experiments": engine.n_exp,
        "hosts": engine.exp.n_hosts,
        "window_ns": engine.window,
        "windows": n_windows,
        "sim_seconds": round(sim_s, 6),
        "wall_seconds": round(wall, 3),
        "sim_per_wall": round(sim_s / wall, 3) if wall > 0 else None,
        # Aggregate sweep throughput — the fleet-mode headline: events
        # executed across ALL experiments per wall second.
        "events_per_sec": round(ev_run_total / wall, 1) if wall > 0 else None,
        "events_per_exp": [int(m["events"]) for m in per_exp],
        "resumed": bool(resumed),
        "caps": caps,
        "metrics": agg,
    }
    return recs, summary
