"""NIC model: per-host token-bucket-style serialization on both directions.

The reference's NetworkInterface (src/main/host/network-interface.c) gives
each host token-bucket up/down bandwidth with a FIFO send queue. The tensor
model keeps one "link free at" timestamp per direction per host: a packet of
wire length L departs at ``max(now, tx_free)`` and occupies the link for
``ceil(8·L / bw)`` ns; the receive side delays packet *processing* the same
way (SURVEY §3.3–3.4). This reproduces serialization/queueing delay exactly
for FIFO order, which is how both engines process packets.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from shadow1_tpu import rng
from shadow1_tpu.consts import R_AQM, SEC


class NicState(NamedTuple):
    tx_free: jnp.ndarray   # i64 [H] uplink busy until
    rx_free: jnp.ndarray   # i64 [H] downlink busy until
    tx_bytes: jnp.ndarray  # i64 [H]
    rx_bytes: jnp.ndarray  # i64 [H]
    aqm_ctr: jnp.ndarray   # i64 [H] uplink enqueue-attempt counter (RED coin)


def nic_init(n_hosts: int) -> NicState:
    z = lambda: jnp.zeros(n_hosts, jnp.int64)
    return NicState(z(), z(), z(), z(), z())


def ctx_aqm(ctx):
    """The ``aqm`` argument for tx_stamp from an engine Ctx (None = off)."""
    if not ctx.has_aqm:
        return None
    return (ctx.key, ctx.hosts, ctx.aqm_min_ns, ctx.aqm_span_ns,
            ctx.aqm_pmax_thr)


_RED_CERTAIN = np.uint64(1) << np.uint64(32)  # threshold meaning "always"


def ser_delay(wire_bytes, bw_bits):
    """ceil(8e9 · bytes / bw) ns — identical integer math in both engines."""
    w = jnp.asarray(wire_bytes, jnp.int64)
    return (w * (8 * SEC) + bw_bits - 1) // bw_bits


def tx_stamp(nic: NicState, mask, wire_bytes, now, bw_up, qlen_ns=None,
             aqm=None):
    """Reserve the uplink: returns (nic', depart_time[H], ok[H], red[H]).

    Two drop gates, in order (both off by default):

    * **RED early drop** (``aqm`` from ctx_aqm — router.c's upstream AQM):
      with instantaneous backlog q, drop probability ramps linearly 0→pmax
      over [min, min+span) and is 1 at ≥ min+span. The coin is the shared
      counter RNG at (R_AQM, host, per-host attempt counter) — the counter
      advances on EVERY masked attempt (enabled or not, dropped or not), so
      both engines see identical streams. Integer pipeline: Q16 backlog
      ratio × the u64 pmax threshold, compared against the raw 32 coin bits.
    * **drop-tail** (``qlen_ns``, the bound expressed as serialization
      backlog time — router.c's queue bound): a packet is DROPPED (ok=False,
      link not reserved) when the backlog already exceeds the bound.
    """
    red = jnp.zeros_like(mask)
    if aqm is not None:
        key, hosts, min_ns, span_ns, pmax_thr = aqm
        coin = rng.bits(key, R_AQM, hosts, nic.aqm_ctr)
        nic = nic._replace(aqm_ctr=nic.aqm_ctr + mask.astype(jnp.int64))
        backlog = jnp.maximum(nic.tx_free - jnp.asarray(now, jnp.int64), 0)
        delta = jnp.clip(backlog - min_ns, 0, span_ns)
        ratio_q16 = (
            (delta.astype(jnp.uint64) << np.uint64(16))
            // span_ns.astype(jnp.uint64)
        )
        thr = (pmax_thr * ratio_q16) >> np.uint64(16)
        thr = jnp.where(delta >= span_ns, _RED_CERTAIN, thr)
        thr = jnp.where(pmax_thr > np.uint64(0), thr, np.uint64(0))
        red = mask & rng.uniform_lt(coin, thr)
        mask = mask & ~red
    if qlen_ns is not None:
        mask = mask & ((nic.tx_free - jnp.asarray(now, jnp.int64)) <= qlen_ns)
    depart = jnp.maximum(now, nic.tx_free)
    busy = depart + ser_delay(wire_bytes, bw_up)
    w = jnp.asarray(wire_bytes, jnp.int64)
    return (
        nic._replace(
            tx_free=jnp.where(mask, busy, nic.tx_free),
            tx_bytes=nic.tx_bytes + jnp.where(mask, w, 0),
        ),
        depart,
        mask,
        red,
    )


def rx_stamp(nic: NicState, mask, wire_bytes, now, bw_dn, qlen_ns=None):
    """Reserve the downlink: returns (nic', ready_time[H], ok[H]) — the time
    the packet clears the receive queue; drop-tail like tx_stamp."""
    if qlen_ns is not None:
        mask = mask & ((nic.rx_free - jnp.asarray(now, jnp.int64)) <= qlen_ns)
    ready = jnp.maximum(now, nic.rx_free)
    busy = ready + ser_delay(wire_bytes, bw_dn)
    w = jnp.asarray(wire_bytes, jnp.int64)
    return (
        nic._replace(
            rx_free=jnp.where(mask, busy, nic.rx_free),
            rx_bytes=nic.rx_bytes + jnp.where(mask, w, 0),
        ),
        ready,
        mask,
    )
