"""Cross-BACKEND determinism: the engine on the real accelerator vs the
CPU oracle (docs/SEMANTICS.md `Randomness`).

The rest of the suite forces the CPU platform (conftest), so the round-2
regression — identical programs producing different event counts on the
TPU than on CPU, via backend-dependent float transcendentals — was
invisible to it. This test runs the comparison in a SUBPROCESS with the
default (accelerator) platform: skipped cleanly when no live accelerator
is reachable within the probe deadline.

VERDICT r2 #5: ≥1k hosts, ≥50 windows, identical counters.
"""

import json
import os
import re
import subprocess
import sys

import pytest

_CHILD = r"""
import json
import shadow1_tpu
import jax
print("BACKEND_UP", jax.default_backend(), flush=True)  # init sentinel
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine

exp = single_vertex_experiment(
    n_hosts=1024, seed=2024, end_time=60 * MS, latency_ns=1 * MS,
    model="phold", model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 4},
)
params = EngineParams(ev_cap=32, outbox_cap=16, max_rounds=64)
eng = Engine(exp, params)
st = eng.run()  # 60 windows on the DEFAULT backend (accelerator when alive)
m = Engine.metrics_dict(st)
cm = CpuEngine(exp, params).run()
print(json.dumps({"backend": jax.default_backend(), "tpu": m, "cpu": cm}))
"""


def test_accelerator_vs_oracle_counters():
    # Undo conftest's CPU-forcing env mutations for the child so it boots
    # the default accelerator platform. The child run IS the gate: a child
    # that fails/hangs/lands on CPU means no usable accelerator -> skip
    # (probing via shadow1_tpu.platform would inherit the conftest env and
    # could mis-report cpu on machines configured by JAX_PLATFORMS alone).
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    if "XLA_FLAGS" in env:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", env["XLA_FLAGS"]
        ).strip()
        if flags:
            env["XLA_FLAGS"] = flags
        else:
            del env["XLA_FLAGS"]  # whitespace-only XLA_FLAGS is a hard error
    cwd = str(__import__("pathlib").Path(__file__).resolve().parent.parent)
    # Cheap liveness probe first (hung backend init is a known failure mode
    # — platform.py): bounds the dead-accelerator cost to ~60s, not 600s.
    probe_src = "import jax; print(jax.default_backend(), len(jax.devices()))"
    try:
        probe = subprocess.run(
            [sys.executable, "-c", probe_src],
            capture_output=True, text=True, timeout=60, env=env, cwd=cwd,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("accelerator backend init exceeded 60s probe deadline")
    if probe.returncode != 0 or probe.stdout.split()[:1] in ([], ["cpu"]):
        pytest.skip(f"no live accelerator backend: {probe.stdout} {probe.stderr[-300:]}")
    try:
        out = subprocess.run(
            [sys.executable, "-c", _CHILD],
            capture_output=True, text=True, timeout=600, env=env, cwd=cwd,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("accelerator backend run exceeded 600s — unreachable")
    if out.returncode != 0:
        if "BACKEND_UP" in out.stdout:
            # The backend initialized and THEN the engine failed: that is a
            # backend-specific regression, the very thing this test exists
            # to catch — fail, don't skip.
            raise AssertionError(
                f"engine failed on live accelerator backend:\n{out.stderr[-2000:]}"
            )
        pytest.skip(f"accelerator backend failed to initialize: {out.stderr[-500:]}")
    r = json.loads(out.stdout.strip().splitlines()[-1])
    if r["backend"] in ("", "cpu"):
        pytest.skip(f"default backend is {r['backend']!r} — nothing to compare")
    for k in ("events", "pkts_sent", "pkts_delivered", "pkts_lost",
              "ev_overflow", "ob_overflow"):
        assert r["tpu"][k] == r["cpu"][k], (k, r["tpu"][k], r["cpu"][k])
