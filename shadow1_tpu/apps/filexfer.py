"""filexfer — bulk file transfer over the virtual TCP stack.

The model-application analogue of the reference's minimal tgen file-transfer
example (resource/examples/, BASELINE ladder rung 1): clients connect to a
server at a start time, stream ``flow_bytes`` with a FLOW_DONE message
boundary at the end, close, and optionally repeat. Servers listen on socket
0, count delivered bytes and completed flows.

model_cfg (numpy arrays, [H]):
  role        0=server 1=client 2=idle
  server      server host per client
  flow_bytes  bytes per flow
  start_time  first-connect time (ns)
  flow_count  sequential flows per client
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from shadow1_tpu.consts import (
    K_APP,
    N_CLOSED,
    N_DATA,
    N_ESTABLISHED,
    N_MSG,
    N_PEER_FIN,
    N_SPACE,
    NP,
    TCP_LISTEN,
)
from shadow1_tpu.core.events import push_local
from shadow1_tpu.tcp import tcp as T

FLOW_DONE = 1
OP_START = 1


def init(ctx, evbuf, tcpd):
    cfg = ctx.model_cfg
    role = jnp.asarray(cfg["role"], jnp.int32)
    app = {
        "role": role,
        "server": jnp.asarray(cfg["server"], jnp.int32),
        "flow_bytes": jnp.asarray(cfg["flow_bytes"], jnp.int32),
        "remaining": jnp.zeros(ctx.n_hosts, jnp.int32),
        "flows_left": jnp.asarray(cfg["flow_count"], jnp.int32),
        "closed_sent": jnp.zeros(ctx.n_hosts, bool),
        "rx_bytes": jnp.zeros(ctx.n_hosts, jnp.int64),
        "flows_done": jnp.zeros(ctx.n_hosts, jnp.int32),
        "done_time": jnp.zeros(ctx.n_hosts, jnp.int64),
    }
    # Servers listen on socket 0 from t=0.
    tcpd = dict(tcpd)
    tcpd["st"] = tcpd["st"].at[0].set(
        jnp.where(role == 0, TCP_LISTEN, tcpd["st"][0])
    )
    # Clients wake up at their start time.
    is_client = role == 1
    p = jnp.zeros((NP, ctx.n_hosts), jnp.int32).at[0].set(OP_START)
    k = jnp.full(ctx.n_hosts, K_APP, jnp.int32)
    evbuf, over = push_local(
        evbuf, is_client, jnp.asarray(cfg["start_time"], jnp.int64), k, p
    )
    return app, evbuf, over.sum(dtype=jnp.int64), tcpd


def _client_pump(st, ctx, mask, now):
    """Queue as much of the current flow as the send buffer takes; attach
    FLOW_DONE on the final chunk; close once everything is queued."""
    app = st.model.app
    m = mask & (app["remaining"] > 0)
    meta = jnp.full(ctx.n_hosts, FLOW_DONE, jnp.int32)
    zero = jnp.zeros(ctx.n_hosts, jnp.int32)
    st, accepted = T.tcp_send(st, ctx, m, zero, app["remaining"], meta, now)
    app = dict(st.model.app)
    app["remaining"] = app["remaining"] - accepted
    # mask (not m) so zero-byte flows close right at establishment.
    done = mask & (app["remaining"] == 0) & ~app["closed_sent"]
    app["closed_sent"] = app["closed_sent"] | done
    st = st._replace(model=st.model._replace(app=app))
    return T.tcp_close(st, ctx, done, zero, now)


def _client_start(st, ctx, mask, now):
    app = dict(st.model.app)
    app["remaining"] = jnp.where(mask, app["flow_bytes"], app["remaining"])
    app["closed_sent"] = jnp.where(mask, False, app["closed_sent"])
    st = st._replace(model=st.model._replace(app=app))
    zero = jnp.zeros(ctx.n_hosts, jnp.int32)
    return T.tcp_connect(st, ctx, mask, zero, app["server"], zero, now)


def on_wakeup(st, ctx, ev, mask):
    start = mask & (ev.p[0] == OP_START)
    return _client_start(st, ctx, start, ev.time)


def on_notify(st, ctx, nf: T.Notif, now, mask):
    app = st.model.app
    is_client = app["role"] == 1
    is_server = app["role"] == 0
    f = nf.flags

    # Client: connection up or buffer space → pump bytes.
    pump = mask & is_client & (((f & N_ESTABLISHED) != 0) | ((f & N_SPACE) != 0))
    st = _client_pump(st, ctx, pump, now)

    # Server: count stream bytes and completed flows.
    app = dict(st.model.app)
    data = mask & is_server & ((f & N_DATA) != 0)
    app["rx_bytes"] = app["rx_bytes"] + jnp.where(data, nf.dlen.astype(jnp.int64), 0)
    msg = mask & is_server & ((f & N_MSG) != 0) & (nf.meta == FLOW_DONE)
    app["flows_done"] = app["flows_done"] + msg.astype(jnp.int32)
    st = st._replace(model=st.model._replace(app=app))

    # Server: peer finished → close our side (full teardown). Teardown-only
    # blocks run under lax.cond (tcp_close / tcp_connect are the heavy ops;
    # gating is exact since all writes are masked).
    peer_fin = mask & is_server & ((f & N_PEER_FIN) != 0)
    st = jax.lax.cond(
        peer_fin.any(),
        lambda s: T.tcp_close(s, ctx, peer_fin, nf.sock, now),
        lambda s: s, st,
    )

    # Client: connection fully closed → next flow or done.
    closed = mask & is_client & ((f & N_CLOSED) != 0)

    def _closed(st):
        app = dict(st.model.app)
        app["flows_left"] = app["flows_left"] - closed.astype(jnp.int32)
        again = closed & (app["flows_left"] > 0)
        app["done_time"] = jnp.where(
            closed & (app["flows_left"] == 0), now, app["done_time"]
        )
        st = st._replace(model=st.model._replace(app=app))
        return _client_start(st, ctx, again, now)

    return jax.lax.cond(closed.any(), _closed, lambda s: s, st)


def summary(app) -> dict:
    return {
        "rx_bytes": app["rx_bytes"],
        "flows_done": app["flows_done"],
        "done_time": app["done_time"],
        "total_rx_bytes": app["rx_bytes"].sum(),
        "total_flows_done": app["flows_done"].sum(),
    }
