"""Pallas fused pop-min kernel — the event-buffer pop in ONE memory pass.

The XLA pop (core/events.py pop_until) lowers to ~12 full-plane HBM passes
(eligibility, three masked mins with their broadcasts/compares, the one-hot
extraction, the clears); on-chip each [C, H] pass costs ~50-95 us at rung-3
shape and the composite measured ~1.35 ms/round (tools/roundprobe.py,
docs/PERF.md round-5). The whole computation is a per-lane (per-host)
reduction chain over the sublane (slot) axis with NO cross-lane traffic —
exactly the shape a fused VMEM kernel wants: read each plane once, keep
every intermediate in registers/VMEM, write the two updated planes and the
[H]-vector results once.

Semantics are IDENTICAL to events.pop_until(extract="sum") — same
lexicographic (t32, tb_hi, tb_lo) masked-min chain, same equality one-hot
(exact: the key triple is unique per host, events.py module docstring),
same masked-sum extraction — asserted bit-equal in tests/test_events.py
and selectable per-run via EngineParams.pop_impl = "pallas".

The kernels run GRIDLESS: one program instance, whole-array blocks. The
axon tunnel's AOT Mosaic pipeline fails to legalize ANY grid-ful kernel
(even a trivial ``grid=(1,)`` copy kernel dies with ``failed to legalize
operation 'func.return'`` — measured round 5, docs/PERF.md), so the full
plane set (keys + NP payload planes) must fit the ~12 MB VMEM budget;
``preflight`` checks this and the engine falls back to the XLA impls when
it cannot hold. The updated t32/kind planes alias their inputs (in-place
update, no spare HBM copy).

Reference anchor: this kernel is the batched analogue of the per-host
binary-heap pop in the reference's worker loop
(src/main/core/scheduler/scheduler.c runNextEvent path,
src/main/utility/priority-queue.c).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from shadow1_tpu.consts import K_NONE, NP
from shadow1_tpu.core import events as ev


# Plane counts per kernel call (inputs + aliased outputs resident in VMEM);
# shared by the per-call checks and the engine-facing preflight so the two
# cannot drift.
POP_PLANES = 6 + NP
PUSH_PLANES = 7 + NP
OBOX_PLANES = 5 + NP


def _check_vmem(cap: int, h: int, planes: int, knob: str = "ev_cap") -> None:
    """The kernels run GRIDLESS — one program instance, whole-array blocks —
    because the axon tunnel's AOT Mosaic pipeline fails to legalize any
    grid-ful kernel (``failed to legalize operation 'func.return'`` for even
    a trivial ``grid=(1,)`` copy kernel; measured round 5, docs/PERF.md).
    Whole-array blocks mean the full plane set must fit VMEM; reject loudly
    instead of silently compiling an over-VMEM kernel."""
    need = 4 * planes * cap * h
    if need > 12 * 2**20:
        raise ValueError(
            f"{knob}={cap} x {h} hosts needs {need / 2**20:.1f} MB of VMEM "
            "for the gridless fused kernels; use pop_impl/push_impl='xla' "
            "for shapes this large"
        )


def preflight(ev_cap: int, outbox_cap: int, h: int,
              pop_pallas: bool, push_pallas: bool) -> None:
    """Raise ValueError if any SELECTED fused kernel cannot hold its plane
    set in VMEM at this shape. No-op off-TPU: every other backend runs the
    kernels in interpret mode (_resolve_interpret), which has no VMEM."""
    if jax.default_backend() != "tpu":
        return
    if pop_pallas:
        _check_vmem(ev_cap, h, planes=POP_PLANES)
    if push_pallas:
        _check_vmem(ev_cap, h, planes=PUSH_PLANES)
        _check_vmem(outbox_cap, h, planes=OBOX_PLANES, knob="outbox_cap")


# Mosaic cannot lower i64, and under x64 a Python int scalar crossing a jit
# boundary (jnp.where's) commits as i64 — as does jnp.sum's default integer
# accumulator. Every scalar constant inside the kernels is therefore an
# explicit jnp.int32 (built INSIDE the kernel body: Pallas rejects captured
# array constants) and every sum pins dtype=int32; a stray i64 here makes
# Mosaic's i64->i32 convert rule recurse to a RecursionError at lowering.


def _consts32():
    return (jnp.int32(ev.I32_FREE), jnp.int32(ev.I32_MAX),
            jnp.int32(K_NONE), jnp.int32(0))


def _pop_kernel(until_ref, t32_ref, hi_ref, lo_ref, kind_ref, p_ref,
                t32o_ref, kindo_ref, mt_ref, mhi_ref, mlo_ref, ko_ref,
                po_ref):
    _I32_FREE, _I32_MAX, _K_NONE32, _ZERO32 = _consts32()
    u = until_ref[0]
    t = t32_ref[:, :]                                   # [C, BH] i32
    k = kind_ref[:, :]
    elig = (k != _K_NONE32) & (t < u)
    tm = jnp.where(elig, t, _I32_FREE)
    mint = tm.min(axis=0, keepdims=True)                # [1, BH]
    tie = elig & (tm == mint)
    him = jnp.where(tie, hi_ref[:, :], _I32_MAX)
    minhi = him.min(axis=0, keepdims=True)
    tie2 = tie & (him == minhi)
    lom = jnp.where(tie2, lo_ref[:, :], _I32_MAX)
    minlo = lom.min(axis=0, keepdims=True)
    sel = tie2 & (lom == minlo)                         # one-hot per host
    t32o_ref[:, :] = jnp.where(sel, _I32_FREE, t)
    kindo_ref[:, :] = jnp.where(sel, _K_NONE32, k)
    mt_ref[:, :] = mint
    mhi_ref[:, :] = minhi
    mlo_ref[:, :] = minlo
    ko_ref[:, :] = jnp.where(sel, k, _ZERO32).sum(axis=0, keepdims=True,
                                                  dtype=jnp.int32)
    po_ref[:, :, :] = jnp.where(sel[None], p_ref[:, :, :], _ZERO32).sum(
        axis=1, keepdims=True, dtype=jnp.int32
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pop_call(t32, tb_hi, tb_lo, kind, p, u32, *, interpret=False):
    cap, h = kind.shape
    if not interpret:
        _check_vmem(cap, h, planes=POP_PLANES)
    blk2 = pl.BlockSpec((cap, h), lambda: (0, 0))
    vec = pl.BlockSpec((1, h), lambda: (0, 0))
    out_shapes = (
        jax.ShapeDtypeStruct((cap, h), jnp.int32),   # t32'
        jax.ShapeDtypeStruct((cap, h), jnp.int32),   # kind'
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # min_t
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # min_hi
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # min_lo
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # kind_out
        jax.ShapeDtypeStruct((NP, 1, h), jnp.int32),  # p_out
    )
    return pl.pallas_call(
        _pop_kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # until32 (1,)
            blk2, blk2, blk2, blk2,
            pl.BlockSpec((NP, cap, h), lambda: (0, 0, 0)),
        ],
        out_specs=(
            blk2, blk2, vec, vec, vec, vec,
            pl.BlockSpec((NP, 1, h), lambda: (0, 0, 0)),
        ),
        out_shape=out_shapes,
        input_output_aliases={1: 0, 4: 1},           # t32, kind in-place
        interpret=interpret,
    )(jnp.asarray(u32).reshape(1), t32, tb_hi, tb_lo, kind, p)


def _resolve_interpret(interpret):
    """Mosaic compiles only for TPU; every other backend (the CPU test
    platform, virtual device meshes) runs the kernels in interpret mode.
    Resolved here so call sites cannot forget the incantation."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def pop_until_fused(buf: ev.EventBuf, until, *,
                    interpret: bool | None = None) -> tuple[ev.EventBuf, ev.Popped]:
    """Drop-in fused replacement for events.pop_until (extract="sum")."""
    interpret = _resolve_interpret(interpret)
    u32 = ev.until32(buf, until)
    t32o, kindo, mt, mhi, mlo, ko, po = _pop_call(
        buf.t32, buf.tb_hi, buf.tb_lo, buf.kind, buf.p, u32,
        interpret=interpret,
    )
    mt, mhi, mlo, ko = mt[0], mhi[0], mlo[0], ko[0]
    mask = mt < u32
    popped = ev.Popped(
        mask=mask,
        time=jnp.where(mask, buf.epoch + mt.astype(jnp.int64), 0),
        kind=ko,
        p=po[:, 0, :],
        tb=jnp.where(mask, ev.tb_join(mhi, mlo), 0),
    )
    buf = buf._replace(
        t32=t32o, kind=kindo,
        n_elig=buf.n_elig - mask.astype(jnp.int32),
    )
    return buf, popped


def _push_kernel(maskv_ref, thi_v, tlo_v, t32_v, bhi_v, blo_v, kind_v, p_v,
                 thi_ref, tlo_ref, t32_ref, bhi_ref, blo_ref, kind_ref, p_ref,
                 thi_o, tlo_o, t32_o, bhi_o, blo_o, kind_o, p_o, over_o):
    _I32_FREE, _I32_MAX, _K_NONE32, _ZERO32 = _consts32()
    k = kind_ref[:, :]                                  # [C, BH]
    free = k == _K_NONE32
    idx = jax.lax.broadcasted_iota(jnp.int32, k.shape, 0)
    cap = jnp.int32(k.shape[0])
    fidx = jnp.where(free, idx, cap).min(axis=0, keepdims=True)  # [1, BH]
    has = fidx < cap
    mv = maskv_ref[:, :] != _ZERO32
    ok = mv & has
    w = free & (idx == fidx) & ok
    thi_o[:, :] = jnp.where(w, thi_v[:, :], thi_ref[:, :])
    tlo_o[:, :] = jnp.where(w, tlo_v[:, :], tlo_ref[:, :])
    t32_o[:, :] = jnp.where(w, t32_v[:, :], t32_ref[:, :])
    bhi_o[:, :] = jnp.where(w, bhi_v[:, :], bhi_ref[:, :])
    blo_o[:, :] = jnp.where(w, blo_v[:, :], blo_ref[:, :])
    kind_o[:, :] = jnp.where(w, kind_v[:, :], k)
    p_o[:, :, :] = jnp.where(w[None], p_v[:, :, :], p_ref[:, :, :])
    over_o[:, :] = (mv & ~has).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _push_call(maskv, thi_v, tlo_v, t32_v, bhi_v, blo_v, kind_v, p_v,
               thi, tlo, t32, bhi, blo, kind, p, *, interpret=False):
    cap, h = kind.shape
    if not interpret:
        _check_vmem(cap, h, planes=PUSH_PLANES)
    blk2 = pl.BlockSpec((cap, h), lambda: (0, 0))
    vec = pl.BlockSpec((1, h), lambda: (0, 0))
    pvec = pl.BlockSpec((NP, 1, h), lambda: (0, 0, 0))
    pblk = pl.BlockSpec((NP, cap, h), lambda: (0, 0, 0))
    plane = jax.ShapeDtypeStruct((cap, h), jnp.int32)
    out_shapes = (
        plane, plane, plane, plane, plane, plane,
        jax.ShapeDtypeStruct((NP, cap, h), jnp.int32),
        jax.ShapeDtypeStruct((1, h), jnp.int32),     # overflow
    )
    return pl.pallas_call(
        _push_kernel,
        in_specs=[vec, vec, vec, vec, vec, vec, vec, pvec,
                  blk2, blk2, blk2, blk2, blk2, blk2, pblk],
        out_specs=(blk2, blk2, blk2, blk2, blk2, blk2, pblk, vec),
        out_shape=out_shapes,
        # The seven buffer planes update in place.
        input_output_aliases={8: 0, 9: 1, 10: 2, 11: 3, 12: 4, 13: 5, 14: 6},
        interpret=interpret,
    )(maskv, thi_v, tlo_v, t32_v, bhi_v, blo_v, kind_v, p_v,
      thi, tlo, t32, bhi, blo, kind, p)


def _push_fused(buf: ev.EventBuf, mask, time, tb, kind, p, *,
                advance_ctr: bool, interpret: bool | None = None):
    """Shared body of the fused push_local/push_back (tb = self_ctr or the
    original tie-break, per events.py semantics)."""
    interpret = _resolve_interpret(interpret)
    time = jnp.asarray(time, jnp.int64)
    thi_v, tlo_v = ev.tb_split(time)
    bhi_v, blo_v = ev.tb_split(jnp.asarray(tb, jnp.int64))
    t32_v = ev._t32_of(time, buf.epoch)
    row = lambda x: jnp.asarray(x, jnp.int32).reshape(1, -1)
    thi, tlo, t32, bhi, blo, kindo, po, over = _push_call(
        row(mask), row(thi_v), row(tlo_v), row(t32_v), row(bhi_v),
        row(blo_v), row(jnp.broadcast_to(jnp.asarray(kind, jnp.int32),
                                         time.shape)),
        jnp.asarray(p, jnp.int32)[:, None, :],
        buf.time_hi, buf.time_lo, buf.t32, buf.tb_hi, buf.tb_lo, buf.kind,
        buf.p, interpret=interpret,
    )
    over = (over[0] != 0) & mask
    ok = mask & ~over
    buf = buf._replace(
        time_hi=thi, time_lo=tlo, t32=t32, tb_hi=bhi, tb_lo=blo,
        kind=kindo, p=po,
        n_elig=buf.n_elig + (ok & (t32_v < buf.u32)).astype(jnp.int32),
    )
    if advance_ctr:
        buf = buf._replace(self_ctr=buf.self_ctr + ok.astype(jnp.int64))
    return buf, over


def push_local_fused(buf: ev.EventBuf, mask, time, kind, p, *,
                     interpret: bool | None = None):
    """Drop-in fused replacement for events.push_local."""
    return _push_fused(buf, mask, time, buf.self_ctr, kind, p,
                       advance_ctr=True, interpret=interpret)


def push_back_fused(buf: ev.EventBuf, mask, time, tb, kind, p, *,
                    interpret: bool | None = None):
    """Drop-in fused replacement for events.push_back."""
    return _push_fused(buf, mask, time, tb, kind, p,
                       advance_ctr=False, interpret=interpret)


def _obox_kernel(cnt_ref, okv_ref, dst_v, kind_v, dhi_v, dlo_v, ctr_v, p_v,
                 dst_ref, kind_ref, dhi_ref, dlo_ref, ctr_ref, p_ref,
                 dst_o, kind_o, dhi_o, dlo_o, ctr_o, p_o):
    _ZERO32 = _consts32()[3]
    cap = dst_ref.shape[0]
    idx = jax.lax.broadcasted_iota(jnp.int32, (cap,) + cnt_ref.shape[1:], 0)
    w = (idx == cnt_ref[:, :]) & (okv_ref[:, :] != _ZERO32)
    dst_o[:, :] = jnp.where(w, dst_v[:, :], dst_ref[:, :])
    kind_o[:, :] = jnp.where(w, kind_v[:, :], kind_ref[:, :])
    dhi_o[:, :] = jnp.where(w, dhi_v[:, :], dhi_ref[:, :])
    dlo_o[:, :] = jnp.where(w, dlo_v[:, :], dlo_ref[:, :])
    ctr_o[:, :] = jnp.where(w, ctr_v[:, :], ctr_ref[:, :])
    p_o[:, :, :] = jnp.where(w[None], p_v[:, :, :], p_ref[:, :, :])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _obox_call(cnt, okv, dst_v, kind_v, dhi_v, dlo_v, ctr_v, p_v,
               dst, kind, dhi, dlo, ctr, p, *, interpret=False):
    cap, h = dst.shape
    if not interpret:
        _check_vmem(cap, h, planes=OBOX_PLANES, knob="outbox_cap")
    blk2 = pl.BlockSpec((cap, h), lambda: (0, 0))
    vec = pl.BlockSpec((1, h), lambda: (0, 0))
    pvec = pl.BlockSpec((NP, 1, h), lambda: (0, 0, 0))
    pblk = pl.BlockSpec((NP, cap, h), lambda: (0, 0, 0))
    plane = jax.ShapeDtypeStruct((cap, h), jnp.int32)
    return pl.pallas_call(
        _obox_kernel,
        in_specs=[vec, vec, vec, vec, vec, vec, vec, pvec,
                  blk2, blk2, blk2, blk2, blk2, pblk],
        out_specs=(blk2, blk2, blk2, blk2, blk2, pblk),
        out_shape=(plane, plane, plane, plane, plane,
                   jax.ShapeDtypeStruct((NP, cap, h), jnp.int32)),
        input_output_aliases={8: 0, 9: 1, 10: 2, 11: 3, 12: 4, 13: 5},
        interpret=interpret,
    )(cnt, okv, dst_v, kind_v, dhi_v, dlo_v, ctr_v, p_v,
      dst, kind, dhi, dlo, ctr, p)


def outbox_append_fused(ob, mask, dst, kind, depart, p, *,
                        interpret: bool | None = None):
    """Drop-in fused replacement for outbox.outbox_append: the write slot is
    ``cnt[h]`` (not a first-free search), so the kernel is a pure one-hot
    write pass over the [P, H] planes."""
    interpret = _resolve_interpret(interpret)
    cap = ob.dst.shape[0]
    ok = mask & (ob.cnt < cap)
    dhi_v, dlo_v = ev.tb_split(jnp.asarray(depart, jnp.int64))
    row = lambda x: jnp.asarray(x, jnp.int32).reshape(1, -1)
    h = ob.cnt.shape[0]
    dsto, kindo, dhio, dloo, ctro, po = _obox_call(
        row(ob.cnt), row(ok), row(jnp.broadcast_to(jnp.asarray(dst, jnp.int32), (h,))),
        row(jnp.broadcast_to(jnp.asarray(kind, jnp.int32), (h,))),
        row(dhi_v), row(dlo_v), row(ob.pkt_ctr.astype(jnp.int32)),
        jnp.asarray(p, jnp.int32)[:, None, :],
        ob.dst, ob.kind, ob.depart_hi, ob.depart_lo, ob.ctr, ob.p,
        interpret=interpret,
    )
    ob = ob._replace(
        dst=dsto, kind=kindo, depart_hi=dhio, depart_lo=dloo, ctr=ctro, p=po,
        cnt=ob.cnt + ok.astype(jnp.int32),
        pkt_ctr=ob.pkt_ctr + ok.astype(jnp.int64),
    )
    return ob, ok
