"""tgen model-app parity: batched engine vs CPU oracle (BASELINE rung 2).

A small all-active tgen mesh: every host serves and streams random-sized
payloads to random peers with think pauses — the shape of the reference's
100-host tgen bulk-traffic config, scaled down for test time. Parity must
be exact including under loss.
"""

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from tests.parity import assert_parity, run_both

TGEN_KEYS = ("rx_bytes", "streams_served", "streams_done", "done_time")


def tgen_exp(n_hosts=12, seed=21, loss=0.0, streams=2, mean_bytes=20_000,
             end=30 * SEC, bw=10**7):
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        loss=loss,
        bw_bits=bw,
        model="net",
        model_cfg={
            "app": "tgen",
            "active": np.ones(n_hosts, np.int64),
            "streams": np.full(n_hosts, streams, np.int64),
            "mean_bytes": np.full(n_hosts, mean_bytes, np.float64),
            "mean_think_ns": np.full(n_hosts, 50 * MS, np.float64),
            "start_time": np.full(n_hosts, 1 * MS, np.int64),
        },
    )


def test_tgen_mesh_parity():
    exp = tgen_exp()
    cm, cs, tm, ts = run_both(exp, EngineParams(ev_cap=256))
    # All clients finish their streams within the horizon.
    assert int(ts["total_streams_done"]) == 12 * 2
    assert int(ts["total_streams_served"]) == 12 * 2
    assert int(ts["total_rx_bytes"]) > 0
    assert_parity(cm, cs, tm, ts, keys=TGEN_KEYS)


@pytest.mark.slow  # tier-1 wall budget (PR 9): the 60-sim-second loss run;
# loss+retransmit parity stays in the fast tier via
# test_bitcoin_parity.test_bitcoin_flood_under_loss_parity and the rung-1
# loss paths; ./ci.sh all runs this.
def test_tgen_mesh_under_loss_parity():
    exp = tgen_exp(seed=8, loss=0.02, mean_bytes=30_000, end=60 * SEC)
    cm, cs, tm, ts = run_both(exp, EngineParams(ev_cap=256))
    assert int(ts["total_streams_done"]) == 12 * 2
    assert tm["tcp_rto"] + tm["tcp_fast_rtx"] > 0
    assert_parity(cm, cs, tm, ts, keys=TGEN_KEYS)


def test_tgen_fixed_size_parity():
    exp = tgen_exp(n_hosts=6, seed=4, streams=3, mean_bytes=15_000, end=30 * SEC)
    exp.model_cfg["fixed_size"] = True
    cm, cs, tm, ts = run_both(exp, EngineParams(ev_cap=256))
    assert int(ts["total_streams_done"]) == 6 * 3
    assert int(ts["total_rx_bytes"]) == 6 * 3 * 15_000
    assert_parity(cm, cs, tm, ts, keys=TGEN_KEYS)
