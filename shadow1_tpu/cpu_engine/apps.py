"""CPU oracle mirrors of the model applications (tgen / tor / bitcoin).

Per-host object implementations of exactly the semantics in
shadow1_tpu/apps/*.py — same draw keys, same operation order, same integer
arithmetic — so event streams match the batched engine bit-for-bit. These
play the role of the reference's real plugin binaries (shadow-plugin-tgen /
-tor / -bitcoin) in the sanctioned model-application substitution
(SURVEY §2.4).
"""

from __future__ import annotations

import numpy as np

from shadow1_tpu.consts import (
    K_APP,
    N_ACCEPTED,
    N_CLOSED,
    N_DATA,
    N_ESTABLISHED,
    N_MSG,
    N_PEER_FIN,
    N_SPACE,
    R_APP,
)

# Mirrors of apps/tgen.py constants.
TGEN_STREAM_DONE = 1
TGEN_OP_START = 1
TGEN_SIZE_MAX = 1 << 30


class CpuTgen:
    """Mirror of shadow1_tpu/apps/tgen.py."""

    def __init__(self, model):
        self.m = model
        cfg = model.eng.exp.model_cfg
        h = model.n_hosts
        self.active = np.asarray(cfg["active"], np.int32)
        self.streams_left = np.asarray(cfg["streams"], np.int32).copy()
        self.mean_bytes = np.asarray(cfg["mean_bytes"], np.float64)
        self.mean_think = np.asarray(cfg["mean_think_ns"], np.float64)
        self.start_time = np.asarray(cfg["start_time"], np.int64)
        self.fixed_size = bool(cfg.get("fixed_size"))
        self.remaining = np.zeros(h, np.int64)
        self.closed_sent = np.zeros(h, bool)
        self.ctr = np.zeros(h, np.int64)
        self.rx_bytes = np.zeros(h, np.int64)
        self.streams_served = np.zeros(h, np.int32)
        self.streams_done = np.zeros(h, np.int32)
        self.done_time = np.zeros(h, np.int64)

    def start(self):
        for h in range(self.m.n_hosts):
            self.m.listen(h, 0)
            if self.active[h] == 1 and self.streams_left[h] > 0:
                self.m.eng.schedule_local(
                    h, int(self.start_time[h]), K_APP, (TGEN_OP_START,)
                )

    def _start_stream(self, h, now):
        d = self.m.eng.draws
        c = int(self.ctr[h])
        raw = d.randint(R_APP, h, 3 * c + 0, self.m.eng.exp.n_hosts - 1)
        dst = raw + (1 if raw >= h else 0)
        if self.fixed_size:
            size = max(int(self.mean_bytes[h]), 1)
        else:
            size = min(
                max(d.exponential_ns(R_APP, h, 3 * c + 1, float(self.mean_bytes[h])), 1),
                TGEN_SIZE_MAX,
            )
        self.remaining[h] = size
        self.closed_sent[h] = False
        self.ctr[h] += 1
        self.m.connect(h, 1, dst, 0, now)

    def _client_pump(self, h, now):
        if self.remaining[h] > 0:
            acc = self.m.tcp_send(h, 1, int(self.remaining[h]), TGEN_STREAM_DONE, now)
            self.remaining[h] -= acc
        if self.remaining[h] == 0 and not self.closed_sent[h]:
            self.closed_sent[h] = True
            self.m.close(h, 1, now)

    def on_wakeup(self, h, now, p):
        if p[0] == TGEN_OP_START:
            self._start_stream(h, now)

    def on_notify(self, h, sock, flags, meta, meta2, dlen, space, now):
        if sock == 1:
            if flags & (N_ESTABLISHED | N_SPACE):
                self._client_pump(h, now)
            if flags & N_CLOSED:
                self.streams_left[h] -= 1
                self.streams_done[h] += 1
                c = int(self.ctr[h]) - 1
                if self.streams_left[h] > 0:
                    think = self.m.eng.draws.exponential_ns(
                        R_APP, h, 3 * c + 2, float(self.mean_think[h])
                    )
                    self.m.eng.schedule_local(h, now + think, K_APP, (TGEN_OP_START,))
                else:
                    self.done_time[h] = now
        else:
            if flags & N_DATA:
                self.rx_bytes[h] += dlen
            if (flags & N_MSG) and meta == TGEN_STREAM_DONE:
                self.streams_served[h] += 1
            if flags & N_PEER_FIN:
                self.m.close(h, sock, now)

    def summary(self):
        return {
            "rx_bytes": self.rx_bytes,
            "streams_served": self.streams_served,
            "streams_done": self.streams_done,
            "done_time": self.done_time,
            "total_rx_bytes": int(self.rx_bytes.sum()),
            "total_streams_served": int(self.streams_served.sum()),
            "total_streams_done": int(self.streams_done.sum()),
        }


# --------------------------------------------------------------------------
# bitcoin (mirror of shadow1_tpu/apps/bitcoin.py)
# --------------------------------------------------------------------------
BTC_OP_CONNECT_ONE = 1
BTC_OP_TX_CREATE = 2
BTC_OP_TX_MSG = 3
BTC_CMD_INV = 1
BTC_CMD_GET = 2
BTC_CMD_TX = 3
BTC_TXID_BITS = 20
BTC_TXID_MASK = (1 << BTC_TXID_BITS) - 1


class CpuBitcoin:
    """Mirror of shadow1_tpu/apps/bitcoin.py (including its event-deferred
    fan-out: dials and announcements are self-scheduled one-conn events)."""

    def __init__(self, model):
        self.m = model
        cfg = model.eng.exp.model_cfg
        self.peers = np.asarray(cfg["peers"], np.int32)
        self.tx_origin = np.asarray(cfg["tx_origin"], np.int64)
        self.tx_time = np.asarray(cfg["tx_time"], np.int64)
        self.tx_size = int(cfg.get("tx_size", 400))
        self.inv_size = int(cfg.get("inv_size", 36))
        self.connect_time = int(cfg.get("connect_time", 0))
        h = model.n_hosts
        n_tx = len(self.tx_origin)
        self.nbr_sock = np.full(self.peers.shape, -1, np.int32)
        self.seen = np.zeros((h, n_tx), bool)
        self.req = np.zeros((h, n_tx), bool)
        self.seen_time = np.zeros((h, n_tx), np.int64)
        self.tx_rx = np.zeros(h, np.int64)
        self.msg_retries = np.zeros(h, np.int64)

    @staticmethod
    def _meta(cmd, txid):
        return (cmd << BTC_TXID_BITS) | txid

    def _push_msg(self, h, sock, meta, nbytes, now):
        self.m.eng.schedule_local(h, now, K_APP, (BTC_OP_TX_MSG, sock, meta, nbytes))

    def start(self):
        # Push order mirrors apps/bitcoin.py init: per host, one
        # OP_CONNECT_ONE per outbound slot (j ascending), then that host's
        # tx creations in tx order.
        for h in range(self.m.n_hosts):
            self.m.listen(h, 0)
        for j in range(self.peers.shape[1]):
            for h in range(self.m.n_hosts):
                if self.peers[h, j] > h:
                    self.m.eng.schedule_local(
                        h, self.connect_time, K_APP, (BTC_OP_CONNECT_ONE, j)
                    )
        for t in range(len(self.tx_origin)):
            self.m.eng.schedule_local(
                int(self.tx_origin[t]), int(self.tx_time[t]), K_APP,
                (BTC_OP_TX_CREATE, t),
            )

    def _announce(self, h, txid, skip_sock, now):
        for j in range(self.peers.shape[1]):
            ns = int(self.nbr_sock[h, j])
            if ns >= 0 and ns != skip_sock:
                self._push_msg(h, ns, self._meta(BTC_CMD_INV, txid), self.inv_size, now)

    def _mark_seen(self, h, txid, now) -> bool:
        if self.seen[h, txid]:
            return False
        self.seen[h, txid] = True
        self.seen_time[h, txid] = now
        return True

    def on_wakeup(self, h, now, p):
        if p[0] == BTC_OP_CONNECT_ONE:
            j = p[1]
            self.nbr_sock[h, j] = 1 + j
            self.m.connect(h, 1 + j, int(self.peers[h, j]), 0, now)
        elif p[0] == BTC_OP_TX_CREATE:
            t = p[1]
            if self._mark_seen(h, t, now):
                self._announce(h, t, -1, now)
        elif p[0] == BTC_OP_TX_MSG:
            # Admission-checked send (mirror of bitcoin.py OP_TX_MSG).
            _op, sock, meta, nbytes = p
            k = self.m.socks[h][sock]
            from shadow1_tpu.consts import seq_sub
            buffered = seq_sub(k.app_end, k.snd_una) - (1 if k.snd_una == 0 else 0)
            fits = (self.m.pr.sndbuf - buffered) >= nbytes
            mq_ok = len(k.mq) < self.m.pr.msgq_cap
            if fits and mq_ok:
                self.m.tcp_send(h, sock, nbytes, meta, now)
            else:
                self.msg_retries[h] += 1
                t_retry = (now // self.m.eng.window + 1) * self.m.eng.window
                self.m.eng.schedule_local(h, t_retry, K_APP, p)

    def on_notify(self, h, sock, flags, meta, meta2, dlen, space, now):
        if flags & N_ACCEPTED:
            peer = self.m.socks[h][sock].peer_host
            for j in range(self.peers.shape[1]):
                if self.peers[h, j] == peer and self.nbr_sock[h, j] < 0:
                    self.nbr_sock[h, j] = sock
        if flags & N_MSG:
            cmd = meta >> BTC_TXID_BITS
            txid = meta & BTC_TXID_MASK
            if cmd == BTC_CMD_INV and not self.seen[h, txid] and not self.req[h, txid]:
                self.req[h, txid] = True
                self._push_msg(h, sock, self._meta(BTC_CMD_GET, txid), self.inv_size, now)
            elif cmd == BTC_CMD_GET and self.seen[h, txid]:
                self._push_msg(h, sock, self._meta(BTC_CMD_TX, txid), self.tx_size, now)
            elif cmd == BTC_CMD_TX:
                self.tx_rx[h] += 1
                if self._mark_seen(h, txid, now):
                    self._announce(h, txid, sock, now)

    def summary(self):
        return {
            "seen": self.seen,
            "seen_time": self.seen_time,
            "tx_rx": self.tx_rx,
            "reach": self.seen.sum(axis=0),
            "msg_retries": self.msg_retries,
            "total_seen": int(self.seen.sum()),
            "total_tx_rx": int(self.tx_rx.sum()),
        }


# --------------------------------------------------------------------------
# tor (mirror of shadow1_tpu/apps/tor.py)
# --------------------------------------------------------------------------
TOR_CELL = 512
TOR_C_CREATE = 1
TOR_C_CREATED = 2
TOR_C_EXTEND = 3
TOR_C_EXTENDED = 4
TOR_C_BEGIN = 5
TOR_C_DATA = 6
TOR_C_END = 7
TOR_C_DIRREQ = 8
TOR_C_DIRRESP = 9
TOR_OP_START = 1
TOR_OP_TX_CELL = 2
TOR_OP_CONNECT_RELAY = 3
TOR_OP_DRAIN = 4
TOR_OP_THINK = 5
TOR_CL_DIR_CONN = 1
TOR_CL_DIR_FETCH = 2
TOR_CL_GUARD_CONN = 3
TOR_CL_BUILDING = 4
TOR_CL_STREAM = 5
TOR_CL_DONE = 7


class CpuTor:
    """Mirror of shadow1_tpu/apps/tor.py (same draws, same push order)."""

    def __init__(self, model):
        from shadow1_tpu.apps.tor import tables
        from shadow1_tpu.consts import R_TOR_PATH

        self.m = model
        self.R = R_TOR_PATH
        cfg = model.eng.exp.model_cfg
        self.cfg = cfg
        self.t = tables(cfg)
        h = model.n_hosts
        self.role = np.asarray(cfg["role"], np.int32)
        self.n_streams_cfg = np.asarray(cfg["n_streams"], np.int32)
        self.mean_cells = np.asarray(cfg["mean_stream_cells"], np.float64)
        self.mean_think = np.asarray(cfg["mean_think_ns"], np.float64)
        self.start_time = np.asarray(cfg["start_time"], np.int64)
        self.consensus_bytes = int(cfg.get("consensus_bytes", 2048))
        self.cells_max = int(cfg.get("cells_max", 120))
        ct = int(cfg.get("ct_cap", 64))
        s = model.pr.sockets_per_host
        self.cl_state = np.zeros(h, np.int32)
        self.cl_guard = np.full(h, -1, np.int32)
        self.cl_circ = np.zeros(h, np.int32)
        self.cl_hop = np.zeros(h, np.int32)
        self.cl_mid = np.zeros(h, np.int32)
        self.cl_exit = np.zeros(h, np.int32)
        self.cl_circs_left = np.asarray(cfg["n_circuits"], np.int32).copy()
        self.cl_streams_left = np.zeros(h, np.int32)
        self.cl_cells_want = np.zeros(h, np.int32)
        self.ctr = np.zeros(h, np.int64)
        self.streams_done = np.zeros(h, np.int32)
        self.cells_rx = np.zeros(h, np.int64)
        self.bootstrap_time = np.zeros(h, np.int64)
        self.done_time = np.zeros(h, np.int64)
        self.rc_peer = np.full((h, s), -1, np.int32)
        self.rc_next_circ = np.ones((h, s), np.int32)
        self.ct_used = np.zeros((h, ct), bool)
        self.ct_in_sock = np.zeros((h, ct), np.int32)
        self.ct_in_circ = np.zeros((h, ct), np.int32)
        self.ct_out_sock = np.full((h, ct), -1, np.int32)
        self.ct_out_circ = np.zeros((h, ct), np.int32)
        self.ct_pend = np.zeros((h, ct), bool)
        self.cells_fwd = np.zeros(h, np.int64)
        self.ct_overflow = np.zeros(h, np.int64)
        self.cell_retries = np.zeros(h, np.int64)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _meta(circ, aux, cmd):
        return (int(circ) << 18) | (int(aux) << 4) | cmd

    @staticmethod
    def _decode(meta):
        return meta >> 18, (meta >> 4) & 0x3FFF, meta & 0xF

    def _draw(self, h):
        c = int(self.ctr[h])
        self.ctr[h] += 1
        return c

    def _pick_weighted(self, h, ids, cum):
        u = self.m.eng.draws.randint(self.R, h, self._draw(h), int(cum[-1]))
        idx = int(np.searchsorted(cum, u, side="right"))
        return int(ids[min(idx, len(ids) - 1)])

    def _push_cell(self, h, sock, meta, nbytes, now):
        self.m.eng.schedule_local(h, now, K_APP, (TOR_OP_TX_CELL, sock, meta, nbytes))

    # -- client steps ------------------------------------------------------
    def _begin_circuit(self, h, now):
        self.cl_mid[h] = self._pick_weighted(h, self.t["relay_ids"], self.t["relay_cum"])
        self.cl_exit[h] = self._pick_weighted(h, self.t["exit_ids"], self.t["exit_cum"])
        self.cl_circ[h] += 1
        self.cl_hop[h] = 1
        self.cl_state[h] = TOR_CL_BUILDING
        self.cl_streams_left[h] = self.n_streams_cfg[h]
        self._push_cell(h, 1, self._meta(self.cl_circ[h], 0, TOR_C_CREATE), TOR_CELL, now)

    def _begin_stream(self, h, now):
        want = min(max(self.m.eng.draws.exponential_ns(
            self.R, h, self._draw(h), float(self.mean_cells[h])), 1), self.cells_max)
        self.cl_cells_want[h] = want
        self.cl_state[h] = TOR_CL_STREAM
        self._push_cell(h, 1, self._meta(self.cl_circ[h], want, TOR_C_BEGIN), TOR_CELL, now)

    def _think(self, h, now):
        think = self.m.eng.draws.exponential_ns(
            self.R, h, self._draw(h), float(self.mean_think[h])
        )
        self.m.eng.schedule_local(h, now + think, K_APP, (TOR_OP_THINK,))

    # -- wakeups -----------------------------------------------------------
    def start(self):
        for h in range(self.m.n_hosts):
            if self.role[h] in (0, 2):
                self.m.listen(h, 0)
            if self.role[h] == 1 and self.cl_circs_left[h] > 0:
                self.m.eng.schedule_local(h, int(self.start_time[h]), K_APP, (TOR_OP_START,))

    def on_wakeup(self, h, now, p):
        if p[0] == TOR_OP_START:
            d_idx = self.m.eng.draws.randint(self.R, h, self._draw(h), len(self.t["dir_ids"]))
            self.cl_state[h] = TOR_CL_DIR_CONN
            self.m.connect(h, 2, int(self.t["dir_ids"][d_idx]), 0, now)
        elif p[0] == TOR_OP_TX_CELL:
            _op, sock, meta, nbytes = p
            k = self.m.socks[h][sock]
            from shadow1_tpu.consts import seq_sub
            buffered = seq_sub(k.app_end, k.snd_una) - (1 if k.snd_una == 0 else 0)
            fits = (self.m.pr.sndbuf - buffered) >= nbytes
            mq_ok = len(k.mq) < self.m.pr.msgq_cap
            if fits and mq_ok:
                self.m.tcp_send(h, sock, nbytes, meta, now)
            else:
                self.cell_retries[h] += 1
                t_retry = (now // self.m.eng.window + 1) * self.m.eng.window
                self.m.eng.schedule_local(h, t_retry, K_APP, p)
        elif p[0] == TOR_OP_CONNECT_RELAY:
            self.m.connect(h, p[1], p[2], 0, now)
        elif p[0] == TOR_OP_DRAIN:
            sock = p[1]
            pend = [
                i for i in range(self.ct_used.shape[1])
                if self.ct_used[h, i] and self.ct_pend[h, i]
                and self.ct_out_sock[h, i] == sock
            ]
            if pend:
                i = pend[0]
                self.ct_pend[h, i] = False
                self._push_cell(
                    h, sock, self._meta(self.ct_out_circ[h, i], 0, TOR_C_CREATE),
                    TOR_CELL, now,
                )
                if len(pend) > 1:
                    self.m.eng.schedule_local(h, now, K_APP, (TOR_OP_DRAIN, sock))
        elif p[0] == TOR_OP_THINK:
            if self.cl_streams_left[h] > 0:
                self._begin_stream(h, now)
            elif self.cl_circs_left[h] > 0:
                self._begin_circuit(h, now)

    # -- notifications -----------------------------------------------------
    def on_notify(self, h, sock, flags, meta, meta2, dlen, space, now):
        role = self.role[h]
        est = bool(flags & N_ESTABLISHED)
        msg = bool(flags & N_MSG)
        circ, aux, cmd = self._decode(meta)

        if role == 1:
            if est and sock == 2 and self.cl_state[h] == TOR_CL_DIR_CONN:
                self.cl_state[h] = TOR_CL_DIR_FETCH
                self._push_cell(h, 2, self._meta(0, 0, TOR_C_DIRREQ), TOR_CELL, now)
            if msg and sock == 2 and cmd == TOR_C_DIRRESP and self.cl_state[h] == TOR_CL_DIR_FETCH:
                self.cl_guard[h] = self._pick_weighted(h, self.t["guard_ids"], self.t["guard_cum"])
                self.bootstrap_time[h] = now
                self.cl_state[h] = TOR_CL_GUARD_CONN
                self.m.close(h, 2, now)
                self.m.connect(h, 1, int(self.cl_guard[h]), 0, now)
            if est and sock == 1 and self.cl_state[h] == TOR_CL_GUARD_CONN:
                self._begin_circuit(h, now)
            if msg and sock == 1 and circ == self.cl_circ[h]:
                if cmd == TOR_C_CREATED and self.cl_hop[h] == 1:
                    self.cl_hop[h] = 2
                    self._push_cell(
                        h, 1, self._meta(circ, self.cl_mid[h], TOR_C_EXTEND), TOR_CELL, now
                    )
                elif cmd == TOR_C_EXTENDED and self.cl_hop[h] == 2:
                    self.cl_hop[h] = 3
                    self._push_cell(
                        h, 1, self._meta(circ, self.cl_exit[h], TOR_C_EXTEND), TOR_CELL, now
                    )
                elif cmd == TOR_C_EXTENDED and self.cl_hop[h] == 3:
                    self._begin_stream(h, now)
                elif cmd == TOR_C_DATA and self.cl_state[h] == TOR_CL_STREAM:
                    self.cells_rx[h] += aux
                elif cmd == TOR_C_END and self.cl_state[h] == TOR_CL_STREAM:
                    self.streams_done[h] += 1
                    self.cl_streams_left[h] -= 1
                    if self.cl_streams_left[h] == 0:
                        self.cl_circs_left[h] -= 1
                        if self.cl_circs_left[h] == 0:
                            self.done_time[h] = now
                            self.cl_state[h] = TOR_CL_DONE
                            return
                    self._think(h, now)
            return

        if role == 2:
            if msg and cmd == TOR_C_DIRREQ:
                self._push_cell(
                    h, sock, self._meta(0, 0, TOR_C_DIRRESP), self.consensus_bytes, now
                )
            if flags & N_PEER_FIN:
                self.m.close(h, sock, now)
            return

        if role != 0:
            return
        # Relay.
        if est and self.rc_peer[h, sock] >= 0:
            self.m.eng.schedule_local(h, now, K_APP, (TOR_OP_DRAIN, sock))
        if not msg:
            return
        self._relay_on_cell(h, sock, meta, now)

    def _relay_on_cell(self, h, sock, meta, now):
        circ, aux, cmd = self._decode(meta)
        ct = self.ct_used.shape[1]
        if cmd == TOR_C_CREATE:
            slot = next((i for i in range(ct) if not self.ct_used[h, i]), None)
            if slot is None:
                self.ct_overflow[h] += 1
                return
            self.ct_used[h, slot] = True
            self.ct_in_sock[h, slot] = sock
            self.ct_in_circ[h, slot] = circ
            self.ct_out_sock[h, slot] = -1
            self.ct_pend[h, slot] = False
            self._push_cell(h, sock, self._meta(circ, 0, TOR_C_CREATED), TOR_CELL, now)
            return
        # locate by in-side then out-side
        idx = from_in = from_out = None
        for i in range(ct):
            if self.ct_used[h, i] and self.ct_in_sock[h, i] == sock and self.ct_in_circ[h, i] == circ:
                idx, from_in = i, True
                break
        if idx is None:
            for i in range(ct):
                if self.ct_used[h, i] and self.ct_out_sock[h, i] == sock and self.ct_out_circ[h, i] == circ:
                    idx, from_out = i, True
                    break
        if idx is None:
            return
        from_in = bool(from_in)
        from_out = bool(from_out)

        if from_in and cmd == TOR_C_EXTEND and self.ct_out_sock[h, idx] < 0:
            target = aux
            r_sock = next(
                (s for s in range(self.rc_peer.shape[1]) if self.rc_peer[h, s] == target),
                None,
            )
            if r_sock is not None:
                osock = r_sock
            else:
                socks = self.m.socks[h]
                from shadow1_tpu.consts import TCP_FREE as _FREE
                osock = next(
                    (s for s in range(1, len(socks)) if socks[s].st == _FREE), None
                )
                if osock is None:
                    self.ct_overflow[h] += 1
                    return
            ocirc = int(self.rc_next_circ[h, osock])
            self.rc_next_circ[h, osock] += 1
            if r_sock is None:
                self.rc_peer[h, osock] = target
            self.ct_out_sock[h, idx] = osock
            self.ct_out_circ[h, idx] = ocirc
            from shadow1_tpu.consts import TCP_ESTABLISHED as _EST
            conn_up = r_sock is not None and self.m.socks[h][osock].st == _EST
            self.ct_pend[h, idx] = not conn_up
            if conn_up:
                self._push_cell(h, osock, self._meta(ocirc, 0, TOR_C_CREATE), TOR_CELL, now)
            if r_sock is None:
                self.m.eng.schedule_local(
                    h, now, K_APP, (TOR_OP_CONNECT_RELAY, osock, target)
                )
            return

        if from_out and cmd == TOR_C_CREATED:
            self._push_cell(
                h, int(self.ct_in_sock[h, idx]),
                self._meta(self.ct_in_circ[h, idx], 0, TOR_C_EXTENDED), TOR_CELL, now,
            )
            return

        if from_in and cmd == TOR_C_BEGIN and self.ct_out_sock[h, idx] < 0:
            self._push_cell(h, sock, self._meta(circ, aux, TOR_C_DATA), aux * TOR_CELL, now)
            self._push_cell(h, sock, self._meta(circ, 0, TOR_C_END), TOR_CELL, now)
            return

        # EXTEND with an existing out leg telescopes onward (mirror of tor.py
        # fwd_in; the fresh-out-leg case returned above).
        nbytes = aux * TOR_CELL if cmd == TOR_C_DATA else TOR_CELL
        if from_in and cmd != TOR_C_CREATED and self.ct_out_sock[h, idx] >= 0:
            self.cells_fwd[h] += 1
            self._push_cell(
                h, int(self.ct_out_sock[h, idx]),
                self._meta(self.ct_out_circ[h, idx], aux, cmd), nbytes, now,
            )
        elif from_out and cmd != TOR_C_CREATED:
            self.cells_fwd[h] += 1
            self._push_cell(
                h, int(self.ct_in_sock[h, idx]),
                self._meta(self.ct_in_circ[h, idx], aux, cmd), nbytes, now,
            )

    def summary(self):
        return {
            "streams_done": self.streams_done,
            "cells_rx": self.cells_rx,
            "bootstrap_time": self.bootstrap_time,
            "done_time": self.done_time,
            "cells_fwd": self.cells_fwd,
            "ct_overflow": self.ct_overflow,
            "cell_retries": self.cell_retries,
            "total_streams_done": int(self.streams_done.sum()),
            "total_cells_rx": int(self.cells_rx.sum()),
            "total_cells_fwd": int(self.cells_fwd.sum()),
            "total_ct_overflow": int(self.ct_overflow.sum()),
            "clients_done": int((self.done_time > 0).sum()),
        }
