"""Checkpoint/resume exactness + heartbeat stream.

Determinism makes checkpointing exact: run A→(save)→resume→B must equal an
uninterrupted A+B run bit-for-bit — the engine-state analogue of the
reference's determinism diff-test (SURVEY §4).
"""

import io
import json

import pytest

import jax
import numpy as np

from shadow1_tpu.ckpt import load_state, run_chunked, save_state
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.obs import run_with_heartbeat


def phold_engine():
    exp = single_vertex_experiment(
        n_hosts=32,
        seed=17,
        end_time=100 * MS,
        latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 2},
    )
    return Engine(exp, EngineParams())


def state_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def test_checkpoint_resume_bit_exact(tmp_path):
    eng = phold_engine()
    # Uninterrupted 100-window run.
    ref = eng.run(n_windows=100)
    # 40 windows → snapshot → load → 60 more.
    st = eng.run(n_windows=40)
    path = str(tmp_path / "snap.npz")
    save_state(st, path)
    st2 = load_state(eng.init_state(), path)
    final = eng.run(st2, n_windows=60)
    assert state_equal(ref, final)


def test_checkpoint_rejects_config_mismatch(tmp_path):
    eng = phold_engine()
    st = eng.run(n_windows=10)
    path = str(tmp_path / "snap.npz")
    save_state(st, path)
    other = Engine(
        single_vertex_experiment(
            n_hosts=64, seed=17, end_time=100 * MS, latency_ns=1 * MS,
            model="phold", model_cfg={"mean_delay_ns": float(2 * MS)},
        ),
        EngineParams(),
    )
    try:
        load_state(other.init_state(), path)
        raise AssertionError("expected ValueError on shape mismatch")
    except ValueError as e:
        assert "config mismatch" in str(e)


def test_run_chunked_matches_straight_run():
    eng = phold_engine()
    ref = eng.run(n_windows=100)
    chunked = run_chunked(eng, n_windows=100, chunk=17)  # uneven tail chunk
    assert state_equal(ref, chunked)


def test_heartbeat_stream():
    eng = phold_engine()
    buf = io.StringIO()
    st, hb = run_with_heartbeat(eng, n_windows=100, every_windows=25, stream=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert len(lines) == 4
    assert lines[-1]["windows"] == 100
    assert sum(r["delta"]["events"] for r in lines) == int(st.metrics.events)
    assert all(r["type"] == "heartbeat" for r in lines)


@pytest.mark.slow  # tier-1 wall budget (PR 4): subsumed in the fast tier
# by tests/test_fault.py::test_supervise_survives_crash_and_corrupt_checkpoint
# (same crash-injection recipe PLUS a corrupted leftover checkpoint);
# ./ci.sh all still runs this plain-crash variant.
def test_cli_supervise_survives_device_fault(tmp_path):
    """End-to-end --ckpt supervision: the child process is killed hard (the
    fault-injection hook dies like a wedged TPU worker) after its first
    checkpoint; the parent must respawn a fresh child that resumes from the
    snapshot and finishes, and the final state must bit-match an
    uninterrupted run of the same config."""
    import os
    import subprocess
    import sys

    cfg = os.path.join(os.path.dirname(__file__), "..", "configs",
                       "rung1_filexfer.yaml")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    ref_npz = str(tmp_path / "ref.npz")
    sup_npz = str(tmp_path / "sup.npz")
    ck = str(tmp_path / "ck.npz")
    base = [sys.executable, "-m", "shadow1_tpu", cfg, "--windows", "40"]
    r = subprocess.run([*base, "--save-state", ref_npz],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-800:]

    # Crash at the sim clock of window 20 (window size from the config).
    from shadow1_tpu.config.experiment import load_experiment

    exp, _, _ = load_experiment(cfg)
    env["SHADOW1_OBS_CRASH_AT_NS"] = str(20 * exp.window)
    r = subprocess.run(
        [*base, "--ckpt", ck, "--ckpt-every-s", "0", "--heartbeat", "10",
         "--save-state", sup_npz],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, (r.stdout[-400:], r.stderr[-800:])
    assert "respawning" in r.stderr  # the fault actually fired + recovered
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["resumed"] is True
    with np.load(ref_npz) as a, np.load(sup_npz) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_cli_supervise_discards_stale_checkpoint(tmp_path):
    """A snapshot left by an interrupted run of a DIFFERENT config must not
    hijack a later run that happens to share tensor shapes: the supervisor
    fingerprints the config and deletes mismatched leftovers."""
    import os
    import subprocess
    import sys

    cfg = os.path.join(os.path.dirname(__file__), "..", "configs",
                       "rung1_filexfer.yaml")
    ck = str(tmp_path / "ck.npz")
    # Manufacture a leftover from "some other config": a real snapshot of
    # this engine (shapes match) with a wrong config fingerprint.
    eng = phold_engine()
    run_with_heartbeat(eng, n_windows=20, every_windows=10, stream=False,
                       ckpt_path=ck, ckpt_every_s=0.0)
    with open(ck + ".meta", "w") as f:
        json.dump({"config_sha256": "not-this-config"}, f)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", cfg, "--windows", "5",
         "--ckpt", ck],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    assert "discarding stale checkpoint" in r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["resumed"] is False  # ran fresh, not from the leftover


def test_heartbeat_ckpt_and_fault_resume(tmp_path):
    """The fault-tolerant heartbeat path (round-4 postmortem: a device fault
    mid-heartbeat-run lost the whole run): run_with_heartbeat(ckpt_path=...)
    must leave a resumable snapshot + progress sidecar, and a fresh process'
    worth of resume (load snapshot, run the remaining windows) must bit-match
    an uninterrupted run — exactly what cli._supervise does after a crash."""
    eng = phold_engine()
    ref = eng.run(n_windows=100)
    path = str(tmp_path / "hb.npz")
    # "Crashed" run: only 50 of 100 windows happened before the fault.
    run_with_heartbeat(eng, n_windows=50, every_windows=25, stream=False,
                       ckpt_path=path, ckpt_every_s=0.0)
    with open(path + ".progress") as f:
        prog = json.load(f)
    assert prog["done_windows"] == 50
    assert prog["win_start"] == 50 * eng.window
    # Supervised respawn: resume from the snapshot, finish the total.
    st2 = load_state(eng.init_state(), path)
    done = prog["win_start"] // eng.window
    final, _hb = run_with_heartbeat(eng, st2, n_windows=100 - done,
                                    every_windows=25, stream=False,
                                    ckpt_path=path, ckpt_every_s=0.0)
    assert state_equal(ref, final)
