from shadow1_tpu.cli import main

raise SystemExit(main())
