"""Host address registry — the DNS/address analogue.

The reference allocates an IP per virtual host and keeps a hostname↔IP
registry queryable during the run (src/main/routing/address.c, dns.c). In
the tensor engines a host's "address" IS its dense host id (packets carry
src/dst ids), so the registry maps names ↔ ids ↔ topology vertices:

* each config host group ``name`` with count N owns hostnames
  ``name-0 .. name-(N-1)`` (and bare ``name`` = its first host, matching
  the config loader's ``@name`` references);
* ``resolve``/``reverse`` are O(1) dict/array lookups, usable at runtime
  by tools and model apps (apps address peers by id; the registry is how
  humans and analysis scripts name them).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class Dns:
    names: list[str]          # canonical hostname per host id
    _by_name: dict[str, int]
    host_vertex: np.ndarray   # i32 [H]

    @classmethod
    def from_groups(cls, groups, host_vertex) -> "Dns":
        seen = [g.name for g in groups]
        assert len(set(seen)) == len(seen), (
            f"duplicate host group names: {sorted(set(n for n in seen if seen.count(n) > 1))}"
        )
        names: list[str] = []
        by_name: dict[str, int] = {}
        for g in groups:
            for i in range(g.count):
                hid = g.start + i
                name = f"{g.name}-{i}" if g.count > 1 else g.name
                names.append(name)
                by_name[name] = hid
            by_name.setdefault(g.name, g.start)  # bare group name = first
        return cls(names=names, _by_name=by_name,
                   host_vertex=np.asarray(host_vertex, np.int32))

    def resolve(self, name: str) -> int:
        """hostname → host id (KeyError on unknown, like NXDOMAIN)."""
        return self._by_name[name]

    def reverse(self, host_id: int) -> str:
        return self.names[host_id]

    def vertex_of(self, host_id: int) -> int:
        return int(self.host_vertex[host_id])

    def __len__(self) -> int:
        return len(self.names)
