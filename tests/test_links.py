"""Link-telemetry plane: per-edge counters, drop attribution, resume.

The link contract (network-observability acceptance): the cumulative
per-edge snapshots are bit-identical cpu-oracle ↔ tpu ↔ sharded(8) ↔
fleet-lane, a resumed run's stream continues the straight run's exactly,
every per-edge drop column reconciles with its global drop counter on
both engines, and links-off leaves the state pytree (and thus the traced
program) untouched.

The straight filexfer run and the solo churn run are module-scoped
fixtures — one engine compile each, shared across the parity, resume,
gap, digest and reconciliation tests.
"""

import numpy as np
import pytest

from shadow1_tpu.consts import EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.telemetry.links import drain_links
from shadow1_tpu.telemetry.registry import LINK_FIELDS, LINK_MAX_COL
from tests.test_net_parity import filexfer_exp

N_WINDOWS = 25
PARAMS = EngineParams(link_telem=1)
CHURN_PARAMS = EngineParams(ev_cap=256, link_telem=1, x2x_cap=64)


def _key(r):
    return (r.get("exp", -1), r.get("src_vertex", -1),
            r.get("dst_vertex", -1), r.get("window", -1))


def tpu_rows(exp, params=PARAMS, n_windows=N_WINDOWS, st=None, start=0):
    eng = Engine(exp, params)
    st = eng.run(st, n_windows=n_windows)
    return st, sorted(drain_links(st, eng.window, start=start), key=_key)


def cpu_rows(exp, params=PARAMS, n_windows=N_WINDOWS):
    eng = CpuEngine(exp, params)
    eng.run(n_windows=n_windows)
    return sorted(eng.link_rows, key=_key)


@pytest.fixture(scope="module")
def straight():
    """One full 25-window filexfer run with links on: (engine, state, rows)."""
    exp = filexfer_exp()
    eng = Engine(exp, PARAMS)
    st = eng.run(n_windows=N_WINDOWS)
    rows = sorted(drain_links(st, eng.window), key=_key)
    return exp, eng, st, rows


@pytest.fixture(scope="module")
def churn():
    """One full solo churn-matrix run: (exp, rows, metrics).

    The churn matrix (8 hosts, outage + ramp + host cycles) exercises
    every drop column of the link accumulator, not just pkts/bytes.
    """
    from tests.test_fault import _churn_matrix_exp

    exp = _churn_matrix_exp()
    eng = Engine(exp, CHURN_PARAMS)
    st = eng.run()
    rows = sorted(drain_links(st, eng.window), key=_key)
    return exp, rows, Engine.metrics_dict(st)


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_link_rows_bit_identical_cpu_vs_tpu(straight):
    exp, _, _, trows = straight
    crows = cpu_rows(exp)
    assert trows == crows
    assert trows  # an empty parity proves nothing
    for r in trows:
        assert all(f in r and isinstance(r[f], int) for f in LINK_FIELDS)
    # Traffic actually crossed the edge.
    assert any(r["pkts"] > 0 and r["bytes"] > 0 for r in trows)


@pytest.mark.slow
def test_link_rows_bit_identical_sharded(churn):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from shadow1_tpu.shard.engine import ShardedEngine

    exp, solo, _ = churn
    sh = ShardedEngine(exp, CHURN_PARAMS)
    st = sh.run(sh.init_state(), n_windows=sh.n_windows)
    shrows = sorted(drain_links(st, sh.window), key=_key)
    assert shrows == solo
    assert any(r["link_down_drops"] > 0 for r in solo)


@pytest.mark.slow
def test_link_rows_fleet_lane_vs_solo():
    from shadow1_tpu.fleet.engine import FleetEngine

    exp_a = filexfer_exp(seed=11)
    exp_b = filexfer_exp(seed=12)
    fleet = FleetEngine([exp_a, exp_b], PARAMS)
    st = fleet.run(n_windows=N_WINDOWS)
    recs = fleet.drain_rings(st)
    links = [r for r in recs if r["type"] == "link"]
    assert {r["exp"] for r in links} == {0, 1}
    for gid, exp in ((0, exp_a), (1, exp_b)):
        lane = sorted(
            ({k: v for k, v in r.items() if k != "exp"}
             for r in links if r["exp"] == gid), key=_key)
        _, solo = tpu_rows(exp)
        assert lane == solo, f"lane {gid} diverged from its solo run"


@pytest.mark.slow
def test_link_resume_reproduces_straight_run(tmp_path, straight):
    from shadow1_tpu.ckpt import load_state, save_state

    exp, _, _, straight_rows = straight
    eng = Engine(exp, PARAMS)
    st = eng.run(n_windows=12)
    first = drain_links(st, eng.window)
    assert all(r["window"] == 11 for r in first)
    path = str(tmp_path / "link.ckpt")
    save_state(st, path)
    eng2 = Engine(exp, PARAMS)
    st2 = load_state(eng2.init_state(), path)
    st2 = eng2.run(st2, n_windows=N_WINDOWS - 12)
    # Cumulative snapshots: the resumed run's boundary drain is the
    # straight run's, bit-identical — no baseline bookkeeping to restore.
    rest = sorted(drain_links(st2, eng2.window, start=12), key=_key)
    assert rest == straight_rows
    # The cursor never re-emits an already-drained boundary.
    assert drain_links(st2, eng2.window, start=N_WINDOWS) == []


def test_link_gap_on_cursor_regression(straight):
    # A fleet lane rebinding to a new experiment mid-sweep regresses the
    # window count below the stream cursor: one rebase marker, no rows.
    _, eng, st, _ = straight
    recs = drain_links(st, eng.window, start=N_WINDOWS + 5)
    assert recs == [{"type": "link_gap", "window": N_WINDOWS,
                     "expected_window": N_WINDOWS + 5}]


# ---------------------------------------------------------------------------
# drop attribution reconciles with the global counters (both engines)
# ---------------------------------------------------------------------------

def test_link_drop_columns_reconcile_with_global_counters(churn):
    exp, trows, tm = churn
    ceng = CpuEngine(exp, CHURN_PARAMS)
    ceng.run()
    crows = sorted(ceng.link_rows, key=_key)
    assert trows == crows
    for rows, m in ((trows, tm), (crows, ceng.metrics)):
        assert sum(r["pkts"] for r in rows) == m["pkts_sent"]
        assert sum(r["loss_drops"] for r in rows) == m["pkts_lost"]
        assert sum(r["link_down_drops"] for r in rows) == m["link_down_pkts"]
    # The scenario actually produced each drop class.
    assert tm["pkts_lost"] > 0 and tm["link_down_pkts"] > 0


@pytest.mark.slow
def test_link_nic_backlog_attribution():
    from tests.test_fidelity import _filexfer

    # A 3000-byte tx queue forces drop-tail: the per-edge column must
    # equal the global nic_tx_drops counter exactly (RED drops excluded).
    exp = _filexfer(qlen=3000)
    params = EngineParams(ev_cap=256, link_telem=1)
    eng = Engine(exp, params)
    st = eng.run()
    trows = sorted(drain_links(st, eng.window), key=_key)
    tm = Engine.metrics_dict(st)
    ceng = CpuEngine(exp, params)
    ceng.run()
    assert trows == sorted(ceng.link_rows, key=_key)
    assert tm["nic_tx_drops"] > 0
    for rows, m in ((trows, tm), (ceng.link_rows, ceng.metrics)):
        assert sum(r["nic_backlog_drops"] for r in rows) == m["nic_tx_drops"]


# ---------------------------------------------------------------------------
# off-state and guards
# ---------------------------------------------------------------------------

def test_links_off_leaves_state_layout_unchanged():
    import jax

    exp = filexfer_exp()
    off = Engine(exp, EngineParams())
    assert off.init_state().links is None
    # Same treedef as a pre-link state: checkpoints, sharding specs and
    # the traced program are untouched unless the plane is actually on
    # (the --state-digest zero-cost rule; opcensus guards the op counts).
    on = Engine(exp, PARAMS)
    t_off = jax.tree_util.tree_structure(off.init_state())
    t_on = jax.tree_util.tree_structure(on.init_state())
    assert t_off != t_on
    n_off = len(jax.tree_util.tree_leaves(off.init_state()))
    n_on = len(jax.tree_util.tree_leaves(on.init_state()))
    assert n_on == n_off + 1  # exactly the [V, V, F] accumulator


def test_link_buf_shape_and_dtype(straight):
    exp, _, st, _ = straight
    v = np.asarray(exp.lat_vv).shape[0]
    assert st.links.buf.shape == (v, v, len(LINK_FIELDS))
    assert st.links.buf.dtype == np.int64
    assert LINK_FIELDS[LINK_MAX_COL] == "queued_ns_max"


def test_link_telem_guards():
    from shadow1_tpu.telemetry.links import check_link_params

    from types import SimpleNamespace

    # EngineParams itself rejects anything but 0/1 at construction...
    with pytest.raises(AssertionError):
        EngineParams(link_telem=2)
    # ...and the engine-side guard reserves >1 for the top-K follow-up
    # (configs built outside the dataclass) and bounds the dense tensor.
    with pytest.raises(ValueError, match="top-K"):
        check_link_params(SimpleNamespace(link_telem=2), 4)
    with pytest.raises(ValueError, match="dense"):
        check_link_params(EngineParams(link_telem=1), 2000)


def test_link_records_digest_neutral(straight):
    # Turning the plane on must not perturb the state digests: the
    # accumulator is observability-only, never part of simulated state.
    import jax.numpy as jnp

    from shadow1_tpu.core.digest import state_digests

    exp, on, st_on, _ = straight
    off = Engine(exp, EngineParams())
    st_off = off.run(n_windows=N_WINDOWS)
    zero = jnp.zeros((), jnp.int64)
    d_off = np.asarray(state_digests(st_off, off.ctx, zero))
    d_on = np.asarray(state_digests(st_on, on.ctx, zero))
    assert (d_on == d_off).all()


# ---------------------------------------------------------------------------
# edge resolution (pcapdump --edge) and heartbeat emission
# ---------------------------------------------------------------------------

def test_resolve_edges_forms():
    from shadow1_tpu.config.experiment import resolve_edges

    names = ["nyc", "lon", "fra"]
    got = resolve_edges(["nyc:lon", "1:2", "fra:0", "nyc:lon"], names)
    assert got == ((0, 1), (1, 2), (2, 0))  # duplicates collapse


def test_resolve_edges_rejects_typos_with_suggestion():
    from shadow1_tpu.config.experiment import WatchlistError, resolve_edges

    names = ["nyc", "lon", "fra"]
    with pytest.raises(WatchlistError, match="did you mean 'lon'"):
        resolve_edges(["nyc:lno"], names)
    with pytest.raises(WatchlistError, match="out of range"):
        resolve_edges(["0:7"], names)
    with pytest.raises(WatchlistError, match="SRC_VERTEX:DST_VERTEX"):
        resolve_edges(["nyc"], names)
    with pytest.raises(WatchlistError, match="SRC_VERTEX:DST_VERTEX"):
        resolve_edges(["nyc:"], names)


def test_heartbeat_emits_link_records():
    import io
    import json

    from shadow1_tpu.obs import run_with_heartbeat

    exp = filexfer_exp()
    eng = Engine(exp, PARAMS)
    buf = io.StringIO()
    _, hb = run_with_heartbeat(eng, n_windows=20, every_windows=10,
                               stream=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    links = [r for r in lines if r["type"] == "link"]
    # Two chunk boundaries, one cumulative snapshot per active edge each.
    assert sorted({r["window"] for r in links}) == [9, 19]
    assert hb.link_records == links
