"""Fault plane: churn/outage semantics, digest parity, hardened recovery.

The deterministic fault plane (shadow1_tpu/fault/, docs/SEMANTICS.md
§"Fault plane") is only trustworthy if killing hosts and links perturbs
every engine identically — so the tests here are parity tests first:
dead-host discards, restart resets, link outages and loss ramps must land
bit-identically on the CPU oracle, the batched engine, and the sharded
engine, with the per-window digest stream as the continuous witness. The
recovery half covers the hardened checkpoint path: integrity-digest
rejection of truncated/bit-flipped snapshots, and the supervisor surviving
an injected crash plus a corrupted checkpoint in one run.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from shadow1_tpu.config.compiled import NO_STOP, single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.fault.schedule import (
    FaultSchedule,
    host_interval_tensors,
    parse_faults,
)

CFG_DIR = os.path.join(os.path.dirname(__file__), "..", "configs")

FAULT_KEYS = [
    "events", "pkts_sent", "pkts_delivered", "pkts_lost", "link_down_pkts",
    "down_events", "down_pkts", "host_restarts", "tcp_rto", "tcp_fast_rtx",
    "tcp_ooo_drops", "ev_overflow", "ob_overflow",
]


def assert_fault_parity(cm, tm):
    from shadow1_tpu.telemetry.registry import normalize

    cm, tm = normalize(cm), normalize(tm)
    assert tm["ev_overflow"] == 0 and tm["ob_overflow"] == 0, (
        "fault tests must be provisioned overflow-free (parity contract)"
    )
    for k in FAULT_KEYS:
        assert cm[k] == tm[k], (k, cm[k], tm[k])


# ---------------------------------------------------------------------------
# Schedule compilation
# ---------------------------------------------------------------------------

def test_host_interval_tensors_merge_and_quantize():
    exp = single_vertex_experiment(
        n_hosts=4, seed=1, end_time=100 * MS, latency_ns=10 * MS,
        model="phold", model_cfg={"mean_delay_ns": float(MS)},
    )
    exp.stop_time[3] = 55 * MS  # legacy knob merges in
    exp.faults = FaultSchedule(
        host_id=[1, 1], host_down=[15 * MS, 61 * MS],
        host_up=[23 * MS, 75 * MS],  # neither is window-aligned
    )
    down, up = host_interval_tensors(exp)
    assert down.shape == (2, 4)
    # host 1: two cycles, up times quantized UP to the 10 ms window
    assert down[:, 1].tolist() == [15 * MS, 61 * MS]
    assert up[:, 1].tolist() == [30 * MS, 80 * MS]
    # host 3: the legacy stop_time is a [stop, never) interval
    assert down[0, 3] == 55 * MS and up[0, 3] == NO_STOP
    # untouched hosts: empty-interval padding
    assert down[:, 0].tolist() == [NO_STOP, NO_STOP]


def test_host_intervals_overlap_after_quantization_rejected():
    exp = single_vertex_experiment(
        n_hosts=2, seed=1, end_time=100 * MS, latency_ns=10 * MS,
        model="phold", model_cfg={"mean_delay_ns": float(MS)},
    )
    exp.faults = FaultSchedule(
        host_id=[0, 0], host_down=[15 * MS, 22 * MS],
        host_up=[21 * MS, 40 * MS],  # up quantizes to 30ms > next down 22ms
    )
    with pytest.raises(ValueError, match="overlap"):
        host_interval_tensors(exp)


def test_faults_yaml_parsing():
    from shadow1_tpu.config.experiment import build_experiment

    doc = {
        "general": {"seed": 3, "stop_time": "2 s"},
        "network": {"single_vertex": {"latency": "10 ms"}},
        "hosts": [{"name": "a", "count": 2}, {"name": "b", "count": 2}],
        "app": {"model": "phold"},
        "faults": {
            "hosts": [
                {"group": "b", "down_at": "100 ms", "up_at": "200 ms"},
                {"host": 0, "down_at": "1 s"},  # no up_at = kill
            ],
            "links": [{"src_vertex": 0, "dst_vertex": 0,
                       "down_at": "300 ms", "up_at": "400 ms"}],
            "loss": [{"src_vertex": 0, "dst_vertex": 0, "from": "1 s",
                      "until": "1.5 s", "loss": 0.25}],
        },
    }
    exp, _params, _sched = build_experiment(doc)
    fs = exp.faults
    assert fs.host_id.tolist() == [2, 3, 0]
    assert fs.host_up[2] == NO_STOP
    assert len(fs.link_src) == 1  # src == dst: no bidirectional double
    assert fs.ramp_loss.tolist() == [0.25]
    # empty section → None
    assert parse_faults({}, [], []) is None


# ---------------------------------------------------------------------------
# Churn semantics parity (oracle vs batched)
# ---------------------------------------------------------------------------

def _phold_churn_exp():
    exp = single_vertex_experiment(
        n_hosts=8, seed=3, end_time=40 * MS, latency_ns=2 * MS,
        model="phold", model_cfg={"mean_delay_ns": float(MS),
                                  "init_events": 2},
    )
    exp.faults = FaultSchedule(
        host_id=[1, 1, 5], host_down=[5 * MS, 20 * MS, 11 * MS],
        host_up=[9 * MS, 26 * MS, NO_STOP],
    )
    return exp


def test_dead_host_drop_accounting_parity():
    """Dead-host event discards and delivery drops are counted identically
    by both engines, and every routed packet is accounted for."""
    exp = _phold_churn_exp()
    pr = EngineParams()
    cm = CpuEngine(exp, pr).run()
    st = Engine(exp, pr).run()
    tm = Engine.metrics_dict(st)
    assert_fault_parity(cm, tm)
    assert tm["down_pkts"] > 0 and tm["host_restarts"] == 2
    # accounting: sent packets all land somewhere counted
    assert tm["pkts_sent"] == (tm["pkts_delivered"] + tm["pkts_lost"]
                               + tm["down_pkts"] + tm["link_down_pkts"])


def test_restart_resets_model_state():
    """A restarted host comes back with its post-init model state: the
    PHOLD draw counters reset (so its post-restart draws replay the t=0
    stream), bit-identically on both engines."""
    exp = _phold_churn_exp()
    pr = EngineParams()
    cpu = CpuEngine(exp, pr)
    cm = cpu.run()
    eng = Engine(exp, pr)
    st = eng.run()
    assert_fault_parity(cm, Engine.metrics_dict(st))
    ts = eng.model_summary(st)
    cs = cpu.summary()
    np.testing.assert_array_equal(np.asarray(ts["hops"]),
                                  np.asarray(cs["hops"]))
    # Host 5 died for good at 11 ms: its counters froze well below the
    # healthy hosts'. Host 1 restarted twice: each reset zeroed its hops.
    hops = np.asarray(ts["hops"])
    assert hops[1] < hops[0]


# ---------------------------------------------------------------------------
# Link outage + loss ramp (net model, TCP recovery)
# ---------------------------------------------------------------------------

def _outage_exp():
    h = 2
    cfg = dict(
        app="filexfer",
        role=np.array([0, 1]), server=np.zeros(h, np.int64),
        flow_bytes=np.full(h, 1_200_000, np.int64),
        start_time=np.full(h, 1 * MS, np.int64),
        flow_count=np.array([0, 1], np.int64),
    )
    exp = single_vertex_experiment(
        n_hosts=h, seed=5, end_time=4 * SEC, latency_ns=20 * MS,
        model="net", model_cfg=cfg, bw_bits=10**7,
    )
    exp.faults = FaultSchedule(
        link_src=[0], link_dst=[0], link_t0=[300 * MS], link_t1=[500 * MS],
        # Ramp covers the post-outage recovery stretch so it provably hits
        # traffic (the flow completes ~2.0 s in).
        ramp_src=[0], ramp_dst=[0], ramp_t0=[1200 * MS],
        ramp_t1=[1800 * MS], ramp_loss=[0.05],
    )
    return exp


def test_tcp_flow_survives_link_outage_via_rto():
    """A 200 ms outage mid-transfer drops the in-flight window; the sender
    must recover via the retransmit timer and still complete the flow —
    with both engines agreeing on every counter, including the outage's
    own drop reason and the loss-ramp casualties."""
    exp = _outage_exp()
    pr = EngineParams(ev_cap=256)
    cpu = CpuEngine(exp, pr)
    cm = cpu.run()
    eng = Engine(exp, pr)
    st = eng.run()
    tm = Engine.metrics_dict(st)
    assert_fault_parity(cm, tm)
    assert tm["link_down_pkts"] > 0, "outage never hit traffic"
    assert tm["tcp_rto"] >= 1, "recovery must ride the RTO path"
    assert tm["pkts_lost"] > 0, "loss ramp never hit traffic"
    ts = eng.model_summary(st)
    assert int(np.asarray(ts["flows_done"]).sum()) == 1, (
        "flow must complete despite the outage")
    np.testing.assert_array_equal(np.asarray(ts["rx_bytes"]),
                                  np.asarray(cpu.summary()["rx_bytes"]))


# ---------------------------------------------------------------------------
# Digest-stream parity matrix + checkpoint/resume under an active schedule
# ---------------------------------------------------------------------------

def _churn_matrix_exp():
    """8 hosts (sharding-friendly), host cycles + outage + ramp all active
    inside 150 windows (every fault counter verified nonzero below)."""
    h = 8
    cfg = dict(
        app="filexfer",
        role=np.array([0] + [1] * 7),
        server=np.zeros(h, np.int64),
        flow_bytes=np.full(h, 150_000, np.int64),
        start_time=(1 * MS + np.arange(h) * 10 * MS).astype(np.int64),
        flow_count=np.array([0] + [6] * 7, np.int64),
    )
    exp = single_vertex_experiment(
        n_hosts=h, seed=5, end_time=3 * SEC, latency_ns=20 * MS,
        model="net", model_cfg=cfg, bw_bits=10**7,
    )
    exp.faults = FaultSchedule(
        host_id=[3, 3, 5],
        host_down=[200 * MS, 900 * MS, 400 * MS],
        host_up=[400 * MS, 1200 * MS, 700 * MS],
        link_src=[0], link_dst=[0], link_t0=[600 * MS], link_t1=[750 * MS],
        ramp_src=[0], ramp_dst=[0], ramp_t0=[1300 * MS], ramp_t1=[1800 * MS],
        ramp_loss=[0.05],
    )
    return exp


def _digest_tuples(rows):
    from shadow1_tpu.core.digest import DIGEST_FIELDS

    return {r["window"]: tuple(r[f] for f in DIGEST_FIELDS) for r in rows
            if r.get("type") in ("ring", "digest")}


def test_digest_parity_cpu_tpu_sharded_under_faults():
    """The acceptance matrix: with host churn (restarts included), a link
    outage and a loss ramp all firing, the per-window digest stream is
    bit-identical cpu ↔ tpu ↔ sharded, and so is every fault counter."""
    from shadow1_tpu.shard.engine import ShardedEngine
    from shadow1_tpu.telemetry.ring import drain_ring

    exp = _churn_matrix_exp()
    n_win = int(-(-exp.end_time // exp.window))
    pr = EngineParams(ev_cap=256, metrics_ring=n_win, state_digest=1)

    cpu = CpuEngine(exp, pr)
    cm = cpu.run()
    cpu_dg = _digest_tuples(cpu.digest_rows)

    eng = Engine(exp, pr)
    st = eng.run()
    tm = Engine.metrics_dict(st)
    assert_fault_parity(cm, tm)
    assert tm["host_restarts"] == 3 and tm["link_down_pkts"] > 0
    tpu_dg = _digest_tuples(drain_ring(st, exp.window))
    assert len(tpu_dg) == n_win
    assert tpu_dg == cpu_dg, "digest stream diverged cpu↔tpu"

    sh = ShardedEngine(exp, pr)
    sst = sh.run()
    assert_fault_parity(cm, ShardedEngine.metrics_dict(sst))
    assert _digest_tuples(drain_ring(sst, exp.window)) == cpu_dg, (
        "digest stream diverged cpu↔sharded")


def test_ckpt_resume_mid_outage_bit_identical():
    """Snapshot taken while a host is DOWN and the link outage is armed;
    the resumed run must continue the restart schedule and digest stream
    bit-identically to the straight run."""
    from shadow1_tpu.ckpt import load_state, save_state

    exp = _churn_matrix_exp()
    n_win = int(-(-exp.end_time // exp.window))
    pr = EngineParams(ev_cap=256, metrics_ring=n_win, state_digest=1)
    eng = Engine(exp, pr)
    ref = eng.run(n_windows=n_win)
    # Window 50 = sim 1.0 s: host 3 is inside its second down interval.
    mid = eng.run(n_windows=50)
    path = "/tmp/shadow1_fault_mid.npz"
    save_state(mid, path)
    resumed = eng.run(load_state(eng.init_state(), path),
                      n_windows=n_win - 50)
    la = jax.tree_util.tree_leaves(ref)
    lb = jax.tree_util.tree_leaves(resumed)
    for i, (a, b) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"leaf {i}")


# ---------------------------------------------------------------------------
# Checkpoint integrity
# ---------------------------------------------------------------------------

def _small_engine():
    exp = single_vertex_experiment(
        n_hosts=16, seed=9, end_time=50 * MS, latency_ns=1 * MS,
        model="phold", model_cfg={"mean_delay_ns": float(2 * MS)},
    )
    return Engine(exp, EngineParams())


def test_checkpoint_rejects_truncated_and_bitflipped(tmp_path):
    from shadow1_tpu.ckpt import (
        CorruptCheckpointError,
        load_state,
        save_state,
        verify_file,
    )

    eng = _small_engine()
    st = eng.run(n_windows=10)
    path = str(tmp_path / "snap.npz")
    save_state(st, path)
    ok, why = verify_file(path)
    assert ok, why
    load_state(eng.init_state(), path)  # intact: loads fine

    raw = open(path, "rb").read()
    # Truncation: half the zip is gone.
    trunc = str(tmp_path / "trunc.npz")
    with open(trunc, "wb") as f:
        f.write(raw[: len(raw) // 2])
    assert verify_file(trunc)[0] is False
    with pytest.raises(CorruptCheckpointError):
        load_state(eng.init_state(), trunc)

    # Single flipped bit inside one leaf's payload. (Flipping a raw file
    # byte can land in zip padding or trip the member CRC first; rewriting
    # one payload bit while keeping the stored integrity word is the exact
    # scenario the digest exists for: plausible-looking state that is not
    # the state that was saved.)
    flip = str(tmp_path / "flip.npz")
    with np.load(path) as d:
        arrs = {k: d[k].copy() for k in d.files}
    leaf = next(k for k in arrs if k.startswith("leaf_")
                and arrs[k].size and arrs[k].dtype != np.bool_)
    arrs[leaf].reshape(-1).view(np.uint8)[0] ^= 0x10
    np.savez(flip, **arrs)  # stored integrity word is now stale
    ok, why = verify_file(flip)
    assert ok is False, "bit flip must not verify"
    assert "integrity" in (why or "")
    with pytest.raises(CorruptCheckpointError, match="integrity"):
        load_state(eng.init_state(), flip)


# ---------------------------------------------------------------------------
# Supervisor: crash + corrupt checkpoint in ONE run; failure classification
# ---------------------------------------------------------------------------

def test_supervise_survives_crash_and_corrupt_checkpoint(tmp_path):
    """The acceptance recovery run: a leftover checkpoint is bit-corrupted
    AND the child crashes mid-run. The supervisor must discard the corrupt
    snapshot (not crash-loop), respawn through the injected crash, and the
    final state must bit-match an uninterrupted run."""
    cfg = os.path.join(CFG_DIR, "rung1_filexfer.yaml")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0"}
    ref_npz = str(tmp_path / "ref.npz")
    sup_npz = str(tmp_path / "sup.npz")
    ck = str(tmp_path / "ck.npz")
    base = [sys.executable, "-m", "shadow1_tpu", cfg, "--windows", "40"]
    r = subprocess.run([*base, "--save-state", ref_npz], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-800:]

    # A corrupt leftover checkpoint with a MATCHING config fingerprint —
    # exactly the state after a crash flipped bits in the snapshot.
    import hashlib

    with open(cfg, "rb") as f:
        fp = hashlib.sha256(f.read()).hexdigest()
    body = bytearray(open(ref_npz, "rb").read())
    body[len(body) // 2] ^= 0x40
    with open(ck, "wb") as f:
        f.write(bytes(body))
    with open(ck + ".meta", "w") as f:
        json.dump({"config_sha256": fp}, f)

    from shadow1_tpu.config.experiment import load_experiment

    exp, _, _ = load_experiment(cfg)
    env["SHADOW1_OBS_CRASH_AT_NS"] = str(20 * exp.window)
    r = subprocess.run(
        [*base, "--ckpt", ck, "--ckpt-every-s", "0", "--heartbeat", "10",
         "--save-state", sup_npz],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, (r.stdout[-400:], r.stderr[-800:])
    assert "discarding corrupt checkpoint" in r.stderr
    assert "respawning" in r.stderr
    with np.load(ref_npz) as a, np.load(sup_npz) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_supervise_classifies_deterministic_no_progress_crash(tmp_path):
    """Two crashes with zero forward progress at the same point must abort
    early with a diagnosis (pointing at the probe tools), not burn all
    MAX_RESPAWNS."""
    cfg = os.path.join(CFG_DIR, "rung1_filexfer.yaml")
    from shadow1_tpu.config.experiment import load_experiment

    exp, _, _ = load_experiment(cfg)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0",
           # Die at the first chunk boundary BEFORE the checkpoint is
           # written: every attempt crashes with no recorded progress.
           "SHADOW1_OBS_CRASH_PRE_SAVE_AT_NS": str(10 * exp.window)}
    ck = str(tmp_path / "ck.npz")
    r = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", cfg, "--windows", "40",
         "--ckpt", ck, "--ckpt-every-s", "0", "--heartbeat", "10"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 41, (r.returncode, r.stderr[-600:])
    assert "no forward progress" in r.stderr
    assert "faultprobe" in r.stderr and "paritytrace" in r.stderr
    # Classified after exactly two attempts: one respawn line, not seven.
    assert r.stderr.count("respawning") == 1, r.stderr[-800:]
