"""Deterministic fault plane — simulated host churn and link failure.

The reference schedules host lifetimes in its experiment file precisely so
churn experiments are reproducible (config start/shutdown times,
src/main/core/support/configuration.c); this package is the tensorized
generalization: a ``faults:`` config section compiles to dense device
tensors (``schedule.py``) that the engines apply with zero host syncs
(``plane.py`` holds the traced helpers; the CPU oracle mirrors the same
numpy tables). Semantics contract: docs/SEMANTICS.md §"Fault plane".
"""

from shadow1_tpu.fault.schedule import (  # noqa: F401
    FaultSchedule,
    host_interval_tensors,
    link_tables,
    parse_faults,
    ramp_tables,
)
