"""Per-round cost decomposition by shape ablation.

    python -m shadow1_tpu.tools.perfprobe [probe ...]

The axon tunnel reports zero-duration device ops in profiler traces, so
op-level profiling is unavailable; instead this times warm window loops on
synthetic workloads that isolate one cost axis each (SURVEY §7.1-style
measurement; VERDICT r2 weak #4 asked for exactly this breakdown):

* ``phold``      — pop/push/route/deliver fixed cost at [H, ev_cap] shapes,
                   no transport (the floor every net round pays).
* ``fx_s{8,64}`` — the TCP stack at sockets_per_host S: [H, S] state ops.
* ``fx_mq{8,64}``— message-boundary FIFO capacity: [H, S, mq] state ops.

Every probe reports ms/window, rounds/window and ms/round; comparing
ms/round across probes attributes the per-round cost to the axis that
changed. One JSON line per probe on stdout.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _pairs_filexfer(n_hosts: int, flow_bytes: int = 120_000):
    """n/2 independent (server <- client) pairs: per-host socket load is
    constant, so S / mq knobs change only tensor shapes, not behavior."""
    from shadow1_tpu.config.compiled import single_vertex_experiment
    from shadow1_tpu.consts import MS

    n = n_hosts
    role = (np.arange(n) % 2).astype(np.int64)        # even=server, odd=client
    server = (np.arange(n) - 1).clip(0).astype(np.int64)
    return single_vertex_experiment(
        n_hosts=n, seed=77, end_time=10**12, latency_ns=30 * MS,
        bw_bits=10**8, model="net",
        model_cfg={
            "app": "filexfer",
            "role": role,
            "server": server,
            "flow_bytes": np.full(n, flow_bytes, np.int64),
            "start_time": np.full(n, 1 * MS, np.int64),
            # keep flows alive for the whole probe
            "flow_count": np.where(role == 1, 1_000_000, 0),
        },
    )


def _phold(n_hosts: int):
    from shadow1_tpu.config.compiled import single_vertex_experiment
    from shadow1_tpu.consts import MS

    return single_vertex_experiment(
        n_hosts=n_hosts, seed=77, end_time=10**12, latency_ns=30 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(60 * MS), "init_events": 4},
    )


def time_engine(exp, params, warm=20, measure=40) -> dict:
    import jax

    from shadow1_tpu.core.engine import Engine

    eng = Engine(exp, params)
    jax.block_until_ready(eng.run(eng.init_state(), n_windows=0))  # compile
    st = eng.run(eng.init_state(), n_windows=warm)
    jax.block_until_ready(st)
    m0 = Engine.metrics_dict(st)
    t0 = time.perf_counter()
    st = eng.run(st, n_windows=measure)
    jax.block_until_ready(st)
    wall = time.perf_counter() - t0
    m1 = Engine.metrics_dict(st)
    rounds = m1["rounds"] - m0["rounds"]
    events = m1["events"] - m0["events"]
    return {
        "ms_per_window": round(1000 * wall / measure, 2),
        "rounds_per_window": round(rounds / measure, 2),
        "ms_per_round": round(1000 * wall / max(rounds, 1), 3),
        "events_per_sec": round(events / wall, 1),
        "ev_overflow": m1["ev_overflow"],
        "ob_overflow": m1["ob_overflow"],
    }


def probes(n_hosts: int):
    from shadow1_tpu.consts import EngineParams

    yield "phold", _phold(n_hosts), EngineParams(ev_cap=256)
    # fx_s64 doubles as the msgq=8 anchor of the mq sweep (identical config
    # — don't pay its compile twice).
    yield ("fx_s8", _pairs_filexfer(n_hosts),
           EngineParams(ev_cap=256, sockets_per_host=8, msgq_cap=8))
    yield ("fx_s64", _pairs_filexfer(n_hosts),
           EngineParams(ev_cap=256, sockets_per_host=64, msgq_cap=8))
    yield ("fx_mq64", _pairs_filexfer(n_hosts),
           EngineParams(ev_cap=256, sockets_per_host=64, msgq_cap=64))


def main() -> None:
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax

    n_hosts = 1000
    only = set(sys.argv[1:])
    for name, exp, params in probes(n_hosts):
        if only and name not in only:
            continue
        try:
            r = time_engine(exp, params)
        except Exception as e:  # noqa: BLE001
            r = {"error": repr(e)[:300]}
        row = {"probe": name, "n_hosts": n_hosts,
               "backend": jax.default_backend(), **r}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
