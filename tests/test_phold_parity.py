"""PHOLD: batched TPU engine vs the sequential CPU oracle.

The reference's analogous gate is its PHOLD scheduler stress plus its
determinism diff-tests (SURVEY §4): identical seeds must yield identical
event streams regardless of execution strategy. Here the two strategies are
a heapq loop and windowed tensor rounds; event counts, per-host hop vectors,
and packet counters must match exactly.
"""

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from tests.parity import assert_parity, run_both


def make_exp(n_hosts=16, seed=7, loss=0.0, end=1 * SEC, mean=20 * MS):
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        loss=loss,
        model="phold",
        model_cfg={"mean_delay_ns": mean, "init_events": 2},
    )


@pytest.mark.parametrize("loss", [0.0, 0.3])
def test_phold_parity(loss):
    exp = make_exp(loss=loss)
    cm, cs, tm, ts = run_both(exp, EngineParams(ev_cap=64, outbox_cap=64))
    assert cm["ev_overflow"] == 0 and cm["ob_overflow"] == 0
    assert_parity(cm, cs, tm, ts, keys=("hops",),
                  metric_keys=("events", "pkts_sent", "pkts_delivered",
                               "pkts_lost"))


def test_phold_pallas_pop_parity():
    """The fused Pallas pop (EngineParams.pop_impl="pallas", interpret mode
    on the CPU test platform) leaves the full engine bit-identical to the
    XLA pop across a complete PHOLD run — metrics and per-host hops."""
    exp = make_exp(n_hosts=8, end=300 * MS)
    a = Engine(exp, EngineParams(ev_cap=32, outbox_cap=32))
    b = Engine(exp, EngineParams(ev_cap=32, outbox_cap=32,
                                 pop_impl="pallas"))
    sa, sb = a.run(), b.run()
    assert Engine.metrics_dict(sa) == Engine.metrics_dict(sb)
    np.testing.assert_array_equal(
        np.asarray(a.model_summary(sa)["hops"]),
        np.asarray(b.model_summary(sb)["hops"]),
    )


def test_phold_pallas_push_parity():
    """The fused Pallas push/outbox-append (EngineParams.push_impl="pallas")
    is likewise engine-level bit-exact (trace-scoped dispatch,
    events.push_impl_ctx; PHOLD exercises outbox_append every round)."""
    exp = make_exp(n_hosts=8, end=300 * MS)
    a = Engine(exp, EngineParams(ev_cap=32, outbox_cap=32))
    b = Engine(exp, EngineParams(ev_cap=32, outbox_cap=32,
                                 pop_impl="pallas", push_impl="pallas"))
    sa, sb = a.run(), b.run()
    assert Engine.metrics_dict(sa) == Engine.metrics_dict(sb)
    np.testing.assert_array_equal(
        np.asarray(a.model_summary(sa)["hops"]),
        np.asarray(b.model_summary(sb)["hops"]),
    )


def test_phold_seed_determinism():
    exp = make_exp(seed=123)
    e1 = Engine(exp)
    e2 = Engine(exp)
    s1, s2 = e1.run(), e2.run()
    np.testing.assert_array_equal(
        np.asarray(e1.model_summary(s1)["hops"]), np.asarray(e2.model_summary(s2)["hops"])
    )
    assert Engine.metrics_dict(s1) == Engine.metrics_dict(s2)


def test_phold_seeds_differ():
    m1 = Engine.metrics_dict(Engine(make_exp(seed=1)).run())
    m2 = Engine.metrics_dict(Engine(make_exp(seed=2)).run())
    assert m1["events"] != m2["events"] or m1["pkts_sent"] != m2["pkts_sent"]
