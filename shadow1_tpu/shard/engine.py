"""Multi-device engine: the host axis sharded over a JAX mesh.

The reference scales by partitioning hosts across worker threads
(src/main/core/scheduler/scheduler-policy-host-steal.c et al., SURVEY §2.5);
the TPU-native equivalent shards the host axis of every state tensor over a
``jax.sharding.Mesh`` with ``jax.shard_map``. Inside a window each device
runs its local block's rounds completely independently (the conservative
lookahead guarantees no mid-window cross-host interaction — the same
invariant the reference's barrier rounds rely on); at the window end the
shard buckets its routed packets by destination shard and ONE
``lax.all_to_all`` over the mesh axis delivers every bucket to its owner;
each shard then scatters the packets addressed to its hosts. That single
collective per window is the entire communication schedule — it rides ICI
within a slice and DCN across slices, replacing the reference's locked
cross-thread event push (src/main/utility/async-priority-queue.c).
Exchanged bytes scale with the per-destination bucket capacity
(``EngineParams.x2x_cap``, auto-sized to 2× the uniform-traffic
expectation), NOT with ×n_dev as the earlier all_gather did. Bucket-full
drops are counted in ``x2x_overflow``. When the cap was auto-sized and a
bucket overflows — which the flagship *convergent* workloads (every
client → one server; Tor clients → few relays) can always do, since one
bucket may need the shard's entire outbox — ``run()`` retries the same
run from the same (immutable) input state at the guaranteed-fit cap
``h_local·outbox_cap``, so results are exact and never silently lossy;
an explicitly-set cap that overflows raises instead (the user's knob is
a contract). The retry costs one recompile; pass an explicit cap to
pin the exchange size for perf-critical runs. Caps beyond
``h_local·outbox_cap`` are clamped to it — a bucket physically cannot
hold more than the shard's whole outbox, so larger values only waste
exchange bytes.

Determinism across shardings: within a shard's outbound, the bucket sort is
stable in flat source order and received buckets concatenate in
source-shard order, so each destination sees its packets in shard-major ×
host-major = global host-major order — exactly the single-device flatten
order — and all event/tie-break keys are computed from global host ids, so the
delivered event streams are identical for any device count. The
``rounds``/``round_cap_hits`` metrics are the one exception (each shard
counts its own inner rounds; they are summed), so they are performance
counters, not semantic invariants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from shadow1_tpu import rng
from shadow1_tpu.config.compiled import CompiledExperiment
from shadow1_tpu.consts import EngineParams
from shadow1_tpu.core.engine import (
    Ctx,
    Engine,
    FlatPackets,
    SimState,
    _metrics_init,
    _model_module,
    fidelity_ctx_kwargs,
    window_step,
)
from shadow1_tpu.core.events import _hi, _join, _lo, evbuf_init
from shadow1_tpu.core.outbox import outbox_init


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the top-level API (with its
    check_vma flag) when present, else the experimental one (check_rep).
    Replication checking is off either way — the metrics psum pattern
    intentionally returns locally-diverged values under replicated specs."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


class ShardedEngine:
    """Engine running one CompiledExperiment over an n-device host-axis mesh.

    API mirrors core.engine.Engine: init_state() → run() → metrics_dict /
    model_summary. n_hosts must divide evenly by the device count.
    """

    def __init__(
        self,
        exp: CompiledExperiment,
        params: EngineParams | None = None,
        devices=None,
        axis: str = "hosts",
    ):
        exp.validate()
        self.exp = exp
        self.params = params or EngineParams()
        from shadow1_tpu.core.engine import (check_digest_params,
                                             check_probe_params)

        check_digest_params(self.params)
        check_probe_params(self.params)
        from shadow1_tpu.telemetry.links import check_link_params

        check_link_params(self.params, np.asarray(exp.lat_vv).shape[0])
        devices = list(devices if devices is not None else jax.devices())
        self.n_dev = len(devices)
        if exp.n_hosts % self.n_dev:
            raise ValueError(
                f"n_hosts={exp.n_hosts} not divisible by {self.n_dev} devices"
            )
        self.h_local = exp.n_hosts // self.n_dev
        from shadow1_tpu.core.engine import _resolve_kernel_impls

        self.params = _resolve_kernel_impls(self.params, self.h_local)
        self.axis = axis
        self.mesh = jax.make_mesh((self.n_dev,), (axis,), devices=devices)
        self.window = exp.window
        self.n_windows = int(-(-exp.end_time // self.window))
        # Global-view ctx: used for state init (which runs unsharded) and for
        # model summaries. Semantically identical to the single-device ctx.
        self.global_ctx = Ctx(
            n_hosts=exp.n_hosts,
            n_total=exp.n_hosts,
            params=self.params,
            window=self.window,
            key=rng.base_key(exp.seed),
            lat_vv=jnp.asarray(exp.lat_vv, jnp.int64),
            loss_vv=jnp.asarray(exp.loss_vv, jnp.float32),
            host_vertex=jnp.asarray(exp.host_vertex, jnp.int32),
            bw_up=jnp.asarray(exp.bw_up, jnp.int64),
            bw_dn=jnp.asarray(exp.bw_dn, jnp.int64),
            model_cfg=exp.model_cfg,
            **fidelity_ctx_kwargs(exp),
        )
        self._model = _model_module(exp.model)
        # Restart target for the fault plane (mirrors Engine.__init__): the
        # post-init model pytree, kept as a HOST-side numpy tree here and
        # passed through shard_map with the state's specs so each block
        # restores from its own host columns.
        self._init_model = None
        if self.global_ctx.has_restart:
            model0, _, _ = self._model.init(
                self.global_ctx,
                evbuf_init(exp.n_hosts, self.params.ev_cap),
            )
            self._init_model = jax.tree.map(np.asarray, model0)
        # Per-(src→dst shard) bucket capacity. The worst case is convergent
        # traffic: ONE bucket holding the shard's entire outbox, so
        # ``_full_cap`` always fits by construction. The auto default is 2×
        # the uniform-traffic expectation (cheap exchange); run() escalates
        # to _full_cap on overflow.
        self._full_cap = self.h_local * self.params.outbox_cap
        auto = max(16, -(-2 * self._full_cap // self.n_dev))
        self._x2x_cap = min(self.params.x2x_cap or auto, self._full_cap)
        # n_windows traced: one compiled program for every window count.
        # Keyed by bucket cap (the overflow-retry path recompiles once).
        self._run_jits: dict[int, object] = {}

    # -- sharding specs ----------------------------------------------------
    def _spec_for(self, leaf) -> P:
        # Every rank≥1 state tensor is host-MINOR by design (the host axis
        # is the last/lane axis — core/dense.py layout contract); scalars
        # are replicated. (Guarded by the n_hosts match so aux leaves of
        # other shapes would fail loudly in shard_map rather than mis-shard.)
        if (hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[-1] == self.exp.n_hosts):
            return P(*([None] * (leaf.ndim - 1)), self.axis)
        return P()

    def _state_specs(self, st: SimState):
        # The telemetry ring is [W, F] with NO host axis — replicated like
        # win_start (window_step globalizes each row via telem_reduce).
        # Spec'd explicitly so a ring whose trailing dim happens to equal
        # n_hosts can never be mis-sharded by the shape heuristic.
        specs = jax.tree.map(self._spec_for, st._replace(telem=None,
                                                         probes=None,
                                                         links=None))
        if st.telem is not None:
            specs = specs._replace(telem=jax.tree.map(lambda _: P(), st.telem))
        # The probe ring is [W, K, F] — replicated for the same reason (the
        # one-hot psum in probe_reduce makes every shard carry the owning
        # shard's rows), and spec'd explicitly for the same shape-collision
        # safety.
        if st.probes is not None:
            specs = specs._replace(
                probes=jax.tree.map(lambda _: P(), st.probes))
        # The link accumulator is [V, V, F] vertex-keyed — no host axis, so
        # it is replicated; link_reduce globalizes each window's deltas.
        if st.links is not None:
            specs = specs._replace(
                links=jax.tree.map(lambda _: P(), st.links))
        return specs

    # -- state -------------------------------------------------------------
    def init_state(self) -> SimState:
        from shadow1_tpu.telemetry.links import link_init
        from shadow1_tpu.telemetry.probes import probe_init
        from shadow1_tpu.telemetry.ring import ring_init

        evbuf = evbuf_init(self.exp.n_hosts, self.params.ev_cap)
        model, evbuf, seed_over = self._model.init(self.global_ctx, evbuf)
        metrics = _metrics_init()
        st = SimState(
            win_start=jnp.zeros((), jnp.int64),
            evbuf=evbuf,
            outbox=outbox_init(self.exp.n_hosts, self.params.outbox_cap),
            model=model,
            metrics=metrics._replace(ev_overflow=metrics.ev_overflow + seed_over),
            cpu_busy=jnp.zeros(self.exp.n_hosts, jnp.int64),
            telem=ring_init(self.params.metrics_ring),
            probes=probe_init(self.params.metrics_ring, self.params.probes),
            links=link_init(self.params.link_telem,
                            np.asarray(self.exp.lat_vv).shape[0]),
        )
        return self.place_state(st)

    def place_state(self, st: SimState) -> SimState:
        """Shard a (host-built) state pytree over the mesh — used at init
        and after a tune/resize.py cap migration (the migrated planes are
        plain numpy; the specs are shape-derived, so a new cap reshards
        correctly)."""
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._state_specs(st)
        )
        return jax.device_put(st, shardings)

    # -- the sharded program ----------------------------------------------
    def _get_run(self, x2x_cap: int):
        f = self._run_jits.get(x2x_cap)
        if f is None:
            f = self._run_jits[x2x_cap] = jax.jit(self._make_run(x2x_cap))
        return f

    def _make_run(self, x2x_cap: int):
        exp, pr, axis = self.exp, self.params, self.axis
        n_dev, h_local = self.n_dev, self.h_local
        if pr.compact_cap:
            # compact_cap is sized against the GLOBAL active set (configs,
            # tools/activeprobe.py); each shard block sees ~1/n_dev of it.
            # Scale to per-shard lanes, rounded up to a lane tile (128) so
            # the bucket stays tiling-friendly; shards whose active count
            # overflows the bucket fall back full-width per window (exact
            # either way — core/compact.py).
            local_cap = -(-pr.compact_cap // n_dev)
            tile = 128 if local_cap >= 128 else 8
            local_cap = min(-(-local_cap // tile) * tile, h_local)
            import dataclasses as _dc

            pr = _dc.replace(pr, compact_cap=local_cap)
        window, model = self.window, self._model
        key = self.global_ctx.key
        lat_vv = self.global_ctx.lat_vv
        loss_vv = self.global_ctx.loss_vv
        loss_thr_vv = self.global_ctx.loss_thr_vv
        host_vertex = self.global_ctx.host_vertex  # full, replicated
        gctx = self.global_ctx
        # Per-host columns sharded alongside the state (host-minor last
        # axis, P(..., axis) each — fault_down/fault_up are [K, H]).
        cols_g = dict(
            hosts=gctx.hosts, bw_up=gctx.bw_up, bw_dn=gctx.bw_dn,
            fault_down=gctx.fault_down, fault_up=gctx.fault_up,
            cpu_cost=gctx.cpu_cost,
            tx_qlen_ns=gctx.tx_qlen_ns, rx_qlen_ns=gctx.rx_qlen_ns,
            aqm_min_ns=gctx.aqm_min_ns, aqm_span_ns=gctx.aqm_span_ns,
            aqm_pmax_thr=gctx.aqm_pmax_thr,
        )
        flags = dict(
            has_jitter=gctx.has_jitter, has_stop=gctx.has_stop,
            has_restart=gctx.has_restart,
            has_link_fault=gctx.has_link_fault,
            has_loss_ramp=gctx.has_loss_ramp,
            has_cpu=gctx.has_cpu, has_tx_qlen=gctx.has_tx_qlen,
            has_rx_qlen=gctx.has_rx_qlen, has_aqm=gctx.has_aqm,
        )
        jitter_vv = gctx.jitter_vv
        # Vertex-keyed fault tables are tiny and host-free: replicated
        # closure constants, like lat_vv.
        link_fault, loss_ramp = gctx.link_fault, gctx.loss_ramp
        init_model_g = self._init_model

        def block(st: SimState, cols, imodel, n_windows) -> SimState:
            ctx = Ctx(
                n_hosts=h_local,
                n_total=exp.n_hosts,
                params=pr,
                window=window,
                key=key,
                lat_vv=lat_vv,
                loss_vv=loss_vv,
                host_vertex=host_vertex,
                bw_up=cols["bw_up"],
                bw_dn=cols["bw_dn"],
                model_cfg=exp.model_cfg,
                hosts=cols["hosts"],
                loss_thr_vv=loss_thr_vv,
                jitter_vv=jitter_vv,
                fault_down=cols["fault_down"],
                fault_up=cols["fault_up"],
                link_fault=link_fault,
                loss_ramp=loss_ramp,
                init_model=imodel,
                cpu_cost=cols["cpu_cost"],
                tx_qlen_ns=cols["tx_qlen_ns"],
                rx_qlen_ns=cols["rx_qlen_ns"],
                aqm_min_ns=cols["aqm_min_ns"],
                aqm_span_ns=cols["aqm_span_ns"],
                aqm_pmax_thr=cols["aqm_pmax_thr"],
                **flags,
            )
            handlers = model.make_handlers(ctx)
            pre_window = getattr(model, "make_pre_window", lambda c: None)(ctx)

            def exchange(fp: FlatPackets):
                # The one collective per window (SURVEY §2.5): bucket local
                # packets by destination shard (stable in flat source order),
                # all_to_all the fixed-capacity buckets, concatenate received
                # buckets in source-shard order. All fields ride one stacked
                # i32 tensor (i64 halves split like core/events.deliver_batch).
                n = fp.dst.shape[0]
                nb = max((n - 1).bit_length(), 1)
                wide = (n_dev + 1) << nb > 2**31 - 1
                kdt = jnp.int64 if wide else jnp.int32
                dshard = jnp.where(fp.keep, fp.dst // h_local, n_dev)
                skey = (dshard.astype(kdt) << nb) | jnp.arange(n, dtype=kdt)
                (skey_s,) = jax.lax.sort((skey,), is_stable=False)
                dshard_s = (skey_s >> nb).astype(jnp.int32)
                idx_s = (skey_s & ((1 << nb) - 1)).astype(jnp.int32)
                seg = jnp.searchsorted(
                    dshard_s, jnp.arange(n_dev + 1, dtype=jnp.int32), side="left"
                )
                pos = seg[:-1, None] + jnp.arange(x2x_cap, dtype=jnp.int32)[None, :]
                valid = pos < seg[1:, None]                   # [n_dev, K]
                src = idx_s[jnp.minimum(pos, n - 1)]          # [n_dev, K]
                dropped = (
                    fp.keep.sum(dtype=jnp.int64) - valid.sum(dtype=jnp.int64)
                )
                # Occupancy: the DEMANDED fill of this shard's busiest
                # outbound bucket this window (can exceed x2x_cap — that is
                # exactly when overflow happens), reduced so every shard
                # carries the same global high-water mark. NOT lax.pmax: the
                # axon tunnel's AOT compiler lowers only Sum all-reduces
                # (measured round 5), so the max rides a psum'd one-hot
                # [n_dev] vector — bit-identical result, sum-only collective.
                local_fill = (seg[1:] - seg[:-1]).max().astype(jnp.int64)
                slot = jnp.arange(n_dev) == jax.lax.axis_index(axis)
                fill_vec = jax.lax.psum(
                    jnp.where(slot, local_fill, 0), axis
                )
                fill_hw = fill_vec.max()
                stacked = jnp.concatenate(
                    [
                        jnp.stack(
                            [
                                fp.dst,
                                _lo(fp.arrival), _hi(fp.arrival),
                                _lo(fp.tb), _hi(fp.tb),
                                fp.kind,
                            ],
                            axis=1,
                        ),
                        fp.p.T,
                    ],
                    axis=1,
                )                                             # [N, 6+NP] i32
                send = jnp.where(valid[:, :, None], stacked[src], 0)
                send = jnp.concatenate(
                    [send, valid[:, :, None].astype(jnp.int32)], axis=2
                )                                             # [n_dev, K, 7+NP]
                recv = jax.lax.all_to_all(
                    send, axis, split_axis=0, concat_axis=0
                )                                             # row s = from shard s
                r = recv.reshape(n_dev * x2x_cap, recv.shape[2])
                keep = r[:, -1] != 0
                out = FlatPackets(
                    dst=jnp.where(keep, r[:, 0], 0),
                    arrival=_join(r[:, 1], r[:, 2]),
                    tb=_join(r[:, 3], r[:, 4]),
                    kind=r[:, 5],
                    p=r[:, 6:-1].T,
                    keep=keep,
                )
                return out, dropped, fill_hw

            def pmax_(x):
                # max across shards of a scalar or [G] vector, carried by a
                # psum'd one-hot [n_dev, ...] (sum-only collectives — the
                # axon tunnel's AOT compiler lowers no pmax, measured
                # round 5).
                slot = jnp.arange(n_dev) == jax.lax.axis_index(axis)
                x = jnp.asarray(x)
                shaped = slot.reshape((n_dev,) + (1,) * x.ndim)
                vec = jax.lax.psum(jnp.where(shaped, x[None], 0), axis)
                return vec.max(axis=0)

            def telem_reduce(counters, gauges):
                # Globalize one ring row: counter deltas are additive across
                # shards (psum); the occupancy gauge vector needs an
                # elementwise max. The state-digest words (appended to the
                # counter vector by ring_record) are per-shard partial sums
                # of globally-host-keyed element hashes, so the same psum
                # yields the exact single-device digest on every shard.
                return jax.lax.psum(counters, axis), pmax_(gauges)

            def probe_reduce(row):
                # Globalize one [K, F] probe row: probe_sample zeroes every
                # probe another shard's block owns, so the psum IS the
                # owning shard's row — every shard then carries the
                # identical replicated ring (same one-hot-sum trick as
                # pmax_, sum-only collectives).
                return jax.lax.psum(row, axis)

            def link_reduce(entry, cur):
                # Globalize the [V, V, F] link accumulator at a window
                # boundary. route_outbox runs per-shard PRE-exchange, so
                # every offered packet is scattered exactly once (on its
                # source shard) and the NIC drop sites hit the source shard
                # only — the per-window counter deltas partition across
                # shards and their psum, added back onto the replicated
                # entry baseline, is bit-identical to the single-device
                # tensor. The queued_ns_max column is a high-water gauge:
                # cross-shard max via the one-hot psum (sum-only
                # collectives, see pmax_).
                from shadow1_tpu.telemetry.links import LINK_MAX_COL
                d = cur.buf - entry.buf
                ctr = entry.buf[..., :LINK_MAX_COL] + jax.lax.psum(
                    d[..., :LINK_MAX_COL], axis)
                mx = pmax_(cur.buf[..., LINK_MAX_COL])
                return cur._replace(buf=jnp.concatenate(
                    [ctr, mx[..., None]], axis=-1))

            init_metrics = st.metrics
            st = jax.lax.fori_loop(
                0, n_windows,
                lambda _, s: window_step(s, ctx, handlers, exchange, pre_window,
                                         make_handlers=model.make_handlers,
                                         telem_reduce=telem_reduce,
                                         probe_reduce=probe_reduce,
                                         link_reduce=link_reduce),
                st,
            )
            # Each shard accumulated its own partials on top of the (replicated)
            # input metrics; psum then re-subtract the duplicated baseline.
            mfin = jax.tree.map(
                lambda f, i: jax.lax.psum(f, axis) - (n_dev - 1) * i,
                st.metrics,
                init_metrics,
            )
            # ``windows`` advances identically on every shard (replicated, like
            # win_start) — keep the local count rather than the 8× sum; same
            # for the pmax-replicated exchange high-water mark. The capacity
            # gauges accumulated per-shard LOCAL maxima inside the loop; one
            # cross-shard max here makes them the global run high-water —
            # bit-identical to the single-device values (max of per-window
            # maxes commutes). compact_max_fill stays a per-shard bucket
            # gauge semantically (like ``rounds``), but the max over shards
            # is exactly the number that sizes the per-shard bucket.
            return st._replace(metrics=mfin._replace(
                windows=st.metrics.windows,
                x2x_max_fill=st.metrics.x2x_max_fill,
                ev_max_fill=pmax_(st.metrics.ev_max_fill),
                ob_max_fill=pmax_(st.metrics.ob_max_fill),
                compact_max_fill=pmax_(st.metrics.compact_max_fill),
            ))

        def run(st: SimState, n_windows) -> SimState:
            specs = self._state_specs(st)
            col_specs = {
                k: P(*([None] * (v.ndim - 1)), axis)
                for k, v in cols_g.items()
            }
            imodel_specs = jax.tree.map(self._spec_for, init_model_g)
            f = _shard_map(
                block,
                mesh=self.mesh,
                in_specs=(specs, col_specs, imodel_specs, P()),
                out_specs=specs,
            )
            return f(st, cols_g, init_model_g, n_windows)

        return run

    # -- public ------------------------------------------------------------
    def run(self, st: SimState | None = None, n_windows: int | None = None,
            check_x2x: bool = True) -> SimState:
        if st is None:
            st = self.init_state()
        n = n_windows if n_windows is not None else self.n_windows
        base = int(st.metrics.x2x_overflow)
        out = self._get_run(self._x2x_cap)(st, jnp.asarray(n, jnp.int32))
        if not check_x2x:
            # A supervising OverflowGuard passes check_x2x=False (through
            # ckpt.run_chunked): the chunk-boundary policy then owns the
            # response — retry grows the bucket via grow_x2x() and replays
            # the chunk transactionally, halt raises the structured
            # CapacityExceededError — so the eager escalate/raise below
            # must not preempt it. The psum'd metrics already carry the
            # global x2x_overflow count every shard agrees on. Guard-LESS
            # callers keep this eager safety net no matter what
            # params.on_overflow says: a policy nobody supervises must
            # never mean silent loss.
            return out
        drops = int(out.metrics.x2x_overflow) - base
        if (drops and not base and not self.params.x2x_cap
                and self._x2x_cap < self._full_cap):
            # Auto-sized cap overflowed (convergent traffic). The input
            # state is immutable, so re-running it at the guaranteed-fit
            # cap is exact — results bit-match a single-device run. The
            # larger cap sticks for subsequent chunks of this engine.
            import warnings

            warnings.warn(
                f"x2x bucket overflow ({drops} pkts) at auto cap "
                f"{self._x2x_cap}; retrying at worst-case cap "
                f"{self._full_cap} (one recompile) — set "
                f"EngineParams.x2x_cap to pin the exchange size",
                RuntimeWarning,
                stacklevel=2,
            )
            self._x2x_cap = self._full_cap
            out = self._get_run(self._x2x_cap)(st, jnp.asarray(n, jnp.int32))
        total = int(out.metrics.x2x_overflow)
        if total:
            # Loud failure beats silently-wrong results: a full all_to_all
            # bucket means packets vanished and single-device parity is
            # gone. Cumulative on purpose: a state carrying drops from an
            # earlier check_x2x=False run (or a lossy checkpoint) is
            # already divergent and must not pass a checked run silently.
            raise RuntimeError(
                f"{total} packets dropped by full all_to_all buckets "
                f"(x2x_cap too small for this traffic pattern) — results "
                f"diverge from the single-device engine; raise "
                f"EngineParams.x2x_cap or pass check_x2x=False"
            )
        return out

    def grow_x2x(self) -> bool:
        """Escalate the exchange bucket to its guaranteed-fit cap (the
        overflow-retry hook, txn.OverflowGuard._grow). The bucket is not a
        state shape, so no plane migration is involved — the grown cap
        simply selects a different compiled program for the replay and all
        subsequent chunks. Returns False when already at the fit cap (a
        bucket physically cannot need more than the shard's whole outbox,
        so a False here means the overflow is not bucket-sized — the guard
        raises with that diagnosis)."""
        if self._x2x_cap >= self._full_cap:
            return False
        self._x2x_cap = self._full_cap
        return True

    metrics_dict = staticmethod(Engine.metrics_dict)

    def model_summary(self, st: SimState):
        return jax.tree.map(np.asarray, self._model.summary(st.model, self.global_ctx))
