"""The C++ thread-per-core comparator simulates the identical experiment.

Counter equality against the Python oracle (itself parity-locked to the
TPU engine) is what entitles bench.py to quote the comparator's wall clock
as the honest thread-per-core baseline (SURVEY §7.3.5).
"""

import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.cpu_engine import CpuEngine

native = pytest.importorskip("shadow1_tpu.native")


def _config(n_hosts=256, windows=40, init=3):
    exp = single_vertex_experiment(
        n_hosts=n_hosts, seed=77, end_time=windows * MS, latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": init},
    )
    params = EngineParams(ev_cap=32, outbox_cap=16, max_rounds=64)
    return exp, params, windows


def _run_native(exp, params, windows, n_threads):
    try:
        return native.run_phold(
            n_hosts=exp.n_hosts, seed=exp.seed, n_windows=windows,
            window_ns=exp.window, mean_delay_ns=exp.model_cfg["mean_delay_ns"],
            init_events=exp.model_cfg["init_events"], ev_cap=params.ev_cap,
            outbox_cap=params.outbox_cap, n_threads=n_threads,
        )
    except native.NativeUnavailable as e:
        pytest.skip(str(e))


@pytest.mark.parametrize("n_threads", [1, 4])
def test_native_matches_oracle(n_threads):
    exp, params, windows = _config()
    cm = CpuEngine(exp, params).run()
    assert cm["ev_overflow"] == 0 and cm["ob_overflow"] == 0, (
        "config must be overflow-free for exact parity"
    )
    nm = _run_native(exp, params, windows, n_threads)
    for k in ("events", "pkts_sent", "pkts_delivered", "ev_overflow", "ob_overflow"):
        assert nm[k] == cm[k], (k, nm[k], cm[k], f"threads={n_threads}")
