"""The "net" workload model: NIC + TCP/UDP transport + model applications.

This composes the tensor equivalents of the reference's host stack
(SURVEY §2.3): NetworkInterface (net/nic.py), the descriptor/TCP subsystem
(tcp/tcp.py), and the application layer (apps/*) that replaces real plugin
binaries with state-machine traffic models (the sanctioned substitution,
SURVEY §2.4). Event flow per arrived packet mirrors the reference call
stack §3.4: K_PKT (NIC receive queue) → K_PKT_DELIVER (TCP/UDP processing)
→ app notification → app reaction (sends, closes) in the same round.

model_cfg: ``{"app": <name>, ...app-specific numpy arrays}``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from shadow1_tpu.consts import (
    F_DGRAM,
    K_APP,
    K_NONE,
    K_PKT,
    K_PKT_DELIVER,
    K_TCP_TIMER,
    K_TX_RESUME,
    N_DGRAM,
    SEC,
    WIRE_OVERHEAD,
)
from shadow1_tpu.core.dense import payload
from shadow1_tpu.core.events import I64_MAX, push_local, tb_split
from shadow1_tpu.core.outbox import outbox_append
from shadow1_tpu.net.nic import NicState, ctx_aqm, nic_init, rx_stamp, tx_stamp
from shadow1_tpu.tcp import tcp as T


class NetState(NamedTuple):
    nic: NicState
    tcp: dict
    app: Any


def _app_module(name: str):
    if name == "filexfer":
        from shadow1_tpu.apps import filexfer

        return filexfer
    if name == "dgram":
        from shadow1_tpu.apps import dgram

        return dgram
    if name == "tgen":
        from shadow1_tpu.apps import tgen

        return tgen
    if name == "tor":
        from shadow1_tpu.apps import tor

        return tor
    if name == "bitcoin":
        from shadow1_tpu.apps import bitcoin

        return bitcoin
    raise ValueError(f"unknown app {name!r}")


def init(ctx, evbuf):
    pr = ctx.params
    nic = nic_init(ctx.n_hosts)
    tcpd = T.tcp_init(ctx.n_hosts, pr.sockets_per_host, pr.msgq_cap, pr)
    app_mod = _app_module(ctx.model_cfg["app"])
    app, evbuf, over, tcpd = app_mod.init(ctx, evbuf, tcpd)
    return NetState(nic=nic, tcp=tcpd, app=app), evbuf, over


def udp_send(st, ctx, mask, dst_host, dst_sock, length, meta, meta2, now):
    """Datagram send: NIC uplink stamp + outbox packet with F_DGRAM.

    The reference's UDP socket (src/main/host/descriptor/udp.c): no
    handshake, no reliability; loss/latency/bandwidth still apply.
    """
    p = payload(
        ctx.n_hosts, ctx.hosts, T.pack_meta(0, dst_sock, F_DGRAM), None, None,
        jnp.asarray(length, jnp.int32), None, None,
        jnp.asarray(meta, jnp.int32), jnp.asarray(meta2, jnp.int32),
    )
    wire = jnp.asarray(length, jnp.int64) + WIRE_OVERHEAD
    nic, depart, sent, red = tx_stamp(
        st.model.nic, mask, wire, now, ctx.bw_up,
        ctx.tx_qlen_ns if ctx.has_tx_qlen else None,
        aqm=ctx_aqm(ctx),
    )
    k = jnp.full(ctx.n_hosts, K_PKT, jnp.int32)
    outbox, ok = outbox_append(st.outbox, sent, dst_host, k, depart, p)
    m = st.metrics
    st = st._replace(
        model=st.model._replace(nic=nic),
        outbox=outbox,
        metrics=m._replace(
            ob_overflow=m.ob_overflow + (sent & ~ok).sum(dtype=jnp.int64),
            nic_tx_drops=m.nic_tx_drops
            + (mask & ~sent & ~red).sum(dtype=jnp.int64),
            nic_aqm_drops=m.nic_aqm_drops + red.sum(dtype=jnp.int64),
        ),
    )
    if st.links is not None:
        # Link plane: drop-tail losses never reach route_outbox, so their
        # egress-edge attribution happens here, at the tx site.
        from shadow1_tpu.telemetry.links import link_nic_drops

        st = st._replace(links=link_nic_drops(
            st.links, ctx, mask & ~sent & ~red, dst_host))
    return st


def make_pre_window(ctx):
    """Batched NIC-arrival processing — the K_PKT round eliminator.

    Packet arrivals dominated the inner-round count: every delivered packet
    cost its host one K_PKT round (NIC receive-queue stamp) before its
    K_PKT_DELIVER round, and a busy relay's round count is the per-window
    maximum. But the NIC rx chain depends ONLY on arrival order and the
    rx_free clock — never on interleaved app/timer events — and every
    K_PKT eligible in a window exists in the event buffer at window start
    (packets are created only by the window-end exchange). So one batched
    per-host pass computes the exact FIFO schedule the per-round handler
    would: sort each host's eligible K_PKT slots by (time, tb), run a
    max-plus associative scan ``free_j = max(free_{j-1}, arr_j) + ser_j``,
    and convert each slot IN PLACE to K_PKT_DELIVER at its queue-cleared
    time, keeping the packet's own tie-break (docs/SEMANTICS.md §packet
    path — the oracle mirrors this exactly, so parity is bit-identical).

    Returns None (keeping the per-round K_PKT handler) when the rx
    drop-tail queue is configured (its drop decisions feed back into the
    clock recurrence, which breaks the max-plus associativity) or when the
    virtual-CPU model is on (arrival events must charge per-event cpu time
    — round-3 advisor finding; the oracle mirrors both gates)."""
    if ctx.has_rx_qlen or ctx.has_cpu:
        return None
    neg = -(1 << 62)

    def pre_window(st, _ctx, win_end):
        buf = st.evbuf
        cap, h = buf.kind.shape
        # Absolute times join once per window (the buffer planes are i32 —
        # core/events.py EventBuf); writes below split back via tb_split.
        abs_t = buf.abs_time()
        sel = (buf.kind == K_PKT) & (abs_t < win_end)
        kind0, time0 = buf.kind, abs_t
        m = st.metrics
        if ctx.has_stop:
            from shadow1_tpu.fault.plane import hosts_down_at

            # A dead host discards arrivals unprocessed (run_round rule);
            # they must not reserve the downlink.
            down = sel & hosts_down_at(ctx.fault_down, ctx.fault_up, abs_t)
            sel = sel & ~down
            kind0 = jnp.where(down, K_NONE, kind0)
            time0 = jnp.where(down, I64_MAX, time0)
            m = m._replace(down_events=m.down_events
                           + down.sum(dtype=jnp.int64))
        t_key = jnp.where(sel, abs_t, I64_MAX)
        # Tie-break ordering over the pre-split (hi, lo) i32 planes
        # (core/events.py tb_split): lexicographic (time, tb_hi, tb_lo)
        # equals the (time, tb) i64 order.
        hi_key = jnp.where(sel, buf.tb_hi, jnp.iinfo(jnp.int32).max)
        lo_key = jnp.where(sel, buf.tb_lo, jnp.iinfo(jnp.int32).max)
        idx = jnp.broadcast_to(
            jnp.arange(cap, dtype=jnp.int32)[:, None], (cap, h)
        )
        t_s, _hi_s, _lo_s, idx_s = jax.lax.sort(
            (t_key, hi_key, lo_key, idx), dimension=0, num_keys=3
        )
        valid = t_s < I64_MAX
        plen = jnp.take_along_axis(buf.p[4], idx_s, axis=0)
        wire = jnp.where(valid, plen.astype(jnp.int64) + WIRE_OVERHEAD, 0)
        bw = ctx.bw_dn[None, :]
        ser = jnp.where(valid, (wire * (8 * SEC) + bw - 1) // bw, 0)
        # Max-plus prefix: each packet is the affine map x ↦ max(x+p, q)
        # with p = ser, q = arr + ser; invalid slots are the identity.
        pq = (ser, jnp.where(valid, t_s + ser, neg))
        p_pre, q_pre = jax.lax.associative_scan(
            lambda a, b: (a[0] + b[0], jnp.maximum(a[1] + b[0], b[1])),
            pq, axis=0,
        )
        free0 = st.model.nic.rx_free[None, :]
        free = jnp.maximum(free0 + p_pre, q_pre)      # clock after packet j
        ready = free - ser                            # = max(free_{j-1}, arr)
        # Un-sort: order by slot index restores original positions.
        _i, ready_o, valid_o = jax.lax.sort(
            (idx_s, ready, valid.astype(jnp.int32)), dimension=0, num_keys=1
        )
        vo = valid_o != 0
        nic = st.model.nic._replace(
            rx_free=free[-1, :],
            rx_bytes=st.model.nic.rx_bytes + wire.sum(axis=0),
        )
        new_time = jnp.where(vo, ready_o, time0)
        thi, tlo = tb_split(new_time)
        evbuf = buf._replace(
            kind=jnp.where(vo, K_PKT_DELIVER, kind0),
            time_hi=thi,
            time_lo=tlo,
        )
        return st._replace(
            evbuf=evbuf, model=st.model._replace(nic=nic), metrics=m
        )

    return pre_window


def make_handlers(ctx):
    app_mod = _app_module(ctx.model_cfg["app"])
    app_on_notify = app_mod.on_notify
    app_on_wakeup = app_mod.on_wakeup

    def on_pkt(st, ev):
        """K_PKT: packet reached the dst NIC — model the receive queue
        (drop-tail when the downlink queue bound is exceeded)."""
        m = ev.mask & (ev.kind == K_PKT)
        wire = jnp.asarray(ev.p[4], jnp.int64) + WIRE_OVERHEAD
        nic, ready, okq = rx_stamp(
            st.model.nic, m, wire, ev.time, ctx.bw_dn,
            ctx.rx_qlen_ns if ctx.has_rx_qlen else None,
        )
        st = st._replace(model=st.model._replace(nic=nic))
        k = jnp.full(ctx.n_hosts, K_PKT_DELIVER, jnp.int32)
        evbuf, over = push_local(st.evbuf, okq, ready, k, ev.p)
        met = st.metrics
        return st._replace(
            evbuf=evbuf,
            metrics=met._replace(
                ev_overflow=met.ev_overflow + over.sum(dtype=jnp.int64),
                nic_rx_drops=met.nic_rx_drops + (m & ~okq).sum(dtype=jnp.int64),
            ),
        )

    def on_deliver(st, ev):
        """K_PKT_DELIVER: the packet cleared the NIC — run TCP/UDP, then app."""
        m = ev.mask & (ev.kind == K_PKT_DELIVER)
        flags = (ev.p[1] >> 16) & 0xFF
        is_dgram = (flags & F_DGRAM) != 0
        st, nf = T.tcp_rx(st, ctx, m & ~is_dgram, ev.p, ev.time)
        dg = m & is_dgram
        nf = T._notify(
            nf, dg, (ev.p[1] >> 8) & 0xFF, N_DGRAM,
            meta=ev.p[7], meta2=ev.p[8], dlen=ev.p[4],
        )
        return app_on_notify(st, ctx, nf, ev.time, nf.flags != 0)

    def on_timer(st, ev):
        return T.on_tcp_timer(st, ctx, ev)

    def on_txr(st, ev):
        return T.on_tx_resume(st, ctx, ev)

    def on_app(st, ev):
        m = ev.mask & (ev.kind == K_APP)
        return app_on_wakeup(st, ctx, ev, m)

    handlers = {
        K_PKT: on_pkt,
        K_PKT_DELIVER: on_deliver,
        K_TCP_TIMER: on_timer,
        K_TX_RESUME: on_txr,
        K_APP: on_app,
    }
    if not (ctx.has_rx_qlen or ctx.has_cpu):
        # Arrivals are batch-converted by make_pre_window — no K_PKT event
        # ever reaches a round, so the pass (and its cond) would be dead.
        del handlers[K_PKT]
    return handlers


def summary(model: NetState, ctx) -> dict:
    d = {
        "nic_tx_bytes": model.nic.tx_bytes,
        "nic_rx_bytes": model.nic.rx_bytes,
    }
    d.update(_app_module(ctx.model_cfg["app"]).summary(model.app))
    return d
