"""Preemption plane — signal-driven graceful drain.

Long runs end by SIGTERM far more often than by finishing: preemptible TPU
capacity delivers a termination notice with a deadline, not a clean exit.
Before this plane, a SIGTERM was indistinguishable from a crash — everything
since the last throttled snapshot was thrown away and the supervisor charged
a crash to its backoff accounting. Now the first SIGTERM/SIGINT *requests a
drain*: the chunk runner (ckpt.run_chunked, via obs.run_with_heartbeat and
fleet/run.py) finishes the in-flight chunk, commits it, forces a final
snapshot, and exits with the dedicated :data:`consts.EXIT_PREEMPTED` code
plus a parseable stdout record. The supervisor classifies that exit as
clean-resume — no backoff, no crash accounting, checkpoint kept — mirroring
the existing EXIT_CAPACITY taxonomy. Rerunning the same command resumes
bit-identically (the preemption contract, docs/SEMANTICS.md).

A second signal arriving ≥ :data:`FORCE_GRACE_S` after the first forces an
immediate default-action exit (the operator's "no really, die now"). The
grace window exists because one logical interrupt often arrives twice within
milliseconds — kernel process-group delivery plus the supervisor forwarding
to its child — and a duplicate must not turn a graceful drain into a kill.

jax-free: the supervisor imports this without initializing an accelerator.
"""

from __future__ import annotations

import os
import signal
import sys
import time

from shadow1_tpu.consts import EXIT_PREEMPTED  # noqa: F401  (re-export)

# Duplicate-delivery debounce: signals closer together than this are one
# logical drain request; later ones escalate to an immediate exit.
FORCE_GRACE_S = 1.0

_DRAIN_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptedExit(Exception):
    """A drain request was honored: the in-flight chunk is committed (and
    checkpointed, when the run carries a checkpoint path) — the process
    should now exit :data:`EXIT_PREEMPTED`.

    Carries the committed state plus the progress the CLI's stdout record
    reports: ``signame`` (which signal asked), ``done_windows`` (committed
    this invocation), ``win_start`` (absolute sim clock — the resume
    point), ``ckpt`` (snapshot path, None when the run kept no checkpoint)
    and ``generation`` (lineage sequence number of the final snapshot)."""

    def __init__(self, st=None, signame: str = "SIGTERM",
                 done_windows: int = 0, win_start: int = 0,
                 ckpt: str | None = None, generation: int | None = None):
        self.st = st
        self.signame = signame
        self.done_windows = int(done_windows)
        self.win_start = int(win_start)
        self.ckpt = ckpt
        self.generation = generation
        super().__init__(
            f"drain complete after {signame}: {self.done_windows} window(s) "
            f"committed, sim_ns={self.win_start}"
            + (f", snapshot {ckpt}" if ckpt else ", no checkpoint path")
        )


def run_injection_hooks(sim_ns: int) -> None:
    """Chunk-boundary fault/preemption/hang injection, shared by the solo
    and fleet runners (obs.run_with_heartbeat / fleet.run_fleet) so the
    supervisor, drain and watchdog paths are testable in both shapes from
    ONE contract. Inert without the env vars:

    * ``SHADOW1_OBS_CRASH_PRE_SAVE_AT_NS`` — die before the checkpoint is
      written (the supervisor sees a zero-progress crash);
    * ``SHADOW1_OBS_SIGTERM_SELF_AT_NS`` — deliver SIGTERM to ourselves
      (the deterministic twin of a real preemption notice);
    * ``SHADOW1_OBS_HANG_AT_NS`` (+ ``SHADOW1_OBS_HANG_ONCE_FLAG``) — stop
      updating the progress sidecar while staying alive (the dead-tunnel
      shape the watchdog must detect); the flag file makes it fire once so
      a respawn proceeds.

    The post-save crash hook (``SHADOW1_OBS_CRASH_AT_NS``) stays in the
    runners — it is gated on a save actually having happened."""
    crash_pre = os.environ.get("SHADOW1_OBS_CRASH_PRE_SAVE_AT_NS")
    if crash_pre is not None and sim_ns == int(crash_pre):
        os._exit(41)
    sigterm_at = os.environ.get("SHADOW1_OBS_SIGTERM_SELF_AT_NS")
    if sigterm_at is not None and sim_ns == int(sigterm_at):
        os.kill(os.getpid(), signal.SIGTERM)
    hang_at = os.environ.get("SHADOW1_OBS_HANG_AT_NS")
    if hang_at is not None and sim_ns == int(hang_at):
        flag = os.environ.get("SHADOW1_OBS_HANG_ONCE_FLAG")
        if flag is None or not os.path.exists(flag):
            if flag:
                with open(flag, "w") as f:
                    f.write("hung")
            while True:
                time.sleep(3600)


class DrainHandler:
    """Installable SIGTERM/SIGINT drain-request latch.

    ``requested`` flips on the first signal; the chunk runner polls it at
    chunk boundaries (never inside a window — a window is the atomic unit
    of the determinism contract). The handler only ever sets a flag: all
    actual drain work happens at the boundary, on the main thread, outside
    async dispatch."""

    def __init__(self, log=None):
        self.signame: str | None = None
        self._t_first: float | None = None
        self._log = log
        self._prev: dict[int, object] = {}

    @property
    def requested(self) -> bool:
        return self.signame is not None

    def _handle(self, signum, frame):
        now = time.monotonic()
        if self._t_first is not None and now - self._t_first >= FORCE_GRACE_S:
            # A genuine second request: restore the default action and
            # re-raise so the process dies with conventional 128+signum —
            # visible to the supervisor as a crash, not a drain.
            print(f"[preempt] second {signal.Signals(signum).name} — "
                  f"abandoning drain, exiting immediately",
                  file=sys.stderr, flush=True)
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        if self._t_first is None:
            self.signame = signal.Signals(signum).name
            self._t_first = now
            print(f"[preempt] {self.signame} received — draining: finishing "
                  f"the in-flight chunk, committing, writing a final "
                  f"snapshot (send again in >{FORCE_GRACE_S:.0f}s to force "
                  f"exit)", file=sys.stderr, flush=True)

    def install(self) -> "DrainHandler":
        for sig in _DRAIN_SIGNALS:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
