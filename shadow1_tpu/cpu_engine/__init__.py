from shadow1_tpu.cpu_engine.engine import CpuEngine  # noqa: F401
