"""Capacity autotuning: ladder, migration, controller, captune.

The contracts under test (ISSUE 2 acceptance):

* migration is BIT-EXACT — a run whose caps grow and shrink mid-flight
  produces the same metrics/model results as a fixed-cap run (pop order is
  decided by the (time, tb) keys, not slot index), single-device and on the
  8-device mesh, for phold and the TCP net model;
* checkpoints cross caps — a snapshot saved at cap A restores into an
  engine at cap B and continues exactly;
* the controller grows BEFORE overflow — on a workload whose occupancy
  ramps past the static starting cap, ``--auto-caps`` keeps the overflow
  counters at 0;
* ``captune.py`` turns run records into the documented recommendations —
  including reproducing the round-5 "rung5 ev_cap ~6x over-provisioned"
  audit finding from its run record.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.tune import (
    CapController,
    CapPolicy,
    cap_ladder,
    next_step,
    quantize_cap,
    recommend_cap,
    resize_state,
)
from shadow1_tpu.tune.ladder import classify

REPO = os.path.join(os.path.dirname(__file__), "..")


def phold_exp(n_hosts=32, seed=17, end_time=100 * MS, init_events=2):
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end_time,
        latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": init_events},
    )


def tgen_exp(n_hosts=8, seed=21, streams=2, mean_bytes=120_000, end=3 * SEC):
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        bw_bits=10**7,
        model="net",
        model_cfg={
            "app": "tgen",
            "active": np.ones(n_hosts, np.int64),
            "streams": np.full(n_hosts, streams, np.int64),
            "mean_bytes": np.full(n_hosts, mean_bytes, np.float64),
            "mean_think_ns": np.full(n_hosts, 50 * MS, np.float64),
            "start_time": np.full(n_hosts, 1 * MS, np.int64),
        },
    )


def migrate(engine, st, ev_cap=None, outbox_cap=None):
    """Host-side cap migration + re-place on the target engine's devices."""
    host = jax.tree.map(np.asarray, st)
    return engine.place_state(
        resize_state(host, ev_cap=ev_cap, outbox_cap=outbox_cap)
    )


# ---------------------------------------------------------------------------
# ladder
# ---------------------------------------------------------------------------

def test_ladder_quantization():
    lad = cap_ladder(600)
    assert lad == [8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512]
    # Successive steps are bounded geometric (×1.33 / ×1.5): recompiles are
    # O(log range) no matter how occupancy wanders.
    assert all(b / a <= 1.5 for a, b in zip(lad, lad[1:]))
    for need in (1, 8, 9, 64, 65, 96, 97, 500):
        q = quantize_cap(need)
        assert q >= max(need, 8) and q in cap_ladder(2 * q)
    assert quantize_cap(96) == 96  # on-ladder values are fixed points
    assert next_step(64) == 96 and next_step(65) == 96 and next_step(96) == 128
    assert recommend_cap(43) == 96  # the rung5 number (×1.5 → ladder)


def test_classify_matches_round5_audit_conclusions():
    # rung5: 6× over → shrink to 96; rung2/dense: hand-validated tight caps
    # stay "ok"; an under-headroom cap flags grow.
    r5 = classify(43, 256)
    assert r5["verdict"] == "shrink" and r5["recommended"] == 96
    assert r5["over_factor"] == pytest.approx(5.95, abs=0.01)
    assert classify(425, 512)["verdict"] == "ok"
    assert classify(66, 96)["verdict"] == "ok"
    assert classify(425, 480)["verdict"] == "grow"


# ---------------------------------------------------------------------------
# gauges
# ---------------------------------------------------------------------------

def test_gauges_match_cpu_oracle_phold():
    """Window-end fill sampling is engine-independent: the oracle's boundary
    samples equal the batch engine's gauges bit-exactly (overflow-free)."""
    from shadow1_tpu.cpu_engine import CpuEngine

    exp = phold_exp()
    params = EngineParams()
    tm = Engine.metrics_dict(Engine(exp, params).run(n_windows=100))
    cm = CpuEngine(exp, params).run(n_windows=100)
    assert tm["ev_overflow"] == 0 and cm["ev_overflow"] == 0
    assert tm["ev_max_fill"] > 0
    assert tm["ev_max_fill"] == cm["ev_max_fill"]
    assert tm["ob_max_fill"] == cm["ob_max_fill"]


def test_gauges_match_cpu_oracle_tgen():
    from shadow1_tpu.cpu_engine import CpuEngine

    exp = tgen_exp(end=6 * SEC // 10)
    params = EngineParams(ev_cap=256)
    tm = Engine.metrics_dict(Engine(exp, params).run())
    cm = CpuEngine(exp, params).run()
    assert tm["ev_overflow"] == 0 and cm["ev_overflow"] == 0
    assert tm["ev_max_fill"] == cm["ev_max_fill"]
    assert tm["ob_max_fill"] == cm["ob_max_fill"]


def test_compact_gauge_records_bucket_demand():
    """The active-host gauge sizes compact_cap BEFORE enabling it, and its
    recording keeps the compacted/plain engines bit-identical."""
    exp = phold_exp(n_hosts=64, seed=7, end_time=30 * MS)
    m = Engine.metrics_dict(
        Engine(exp, EngineParams(compact_cap=32)).run(n_windows=30)
    )
    m_off = Engine.metrics_dict(Engine(exp, EngineParams()).run(n_windows=30))
    assert m_off["compact_max_fill"] > 0  # measured with compaction OFF too
    assert m == m_off  # the perf knob stays bit-invisible, gauge included
    assert m["compact_max_fill"] <= 64


# ---------------------------------------------------------------------------
# resize migration — bit-exactness
# ---------------------------------------------------------------------------

def test_grow_then_shrink_bit_exact_phold():
    exp = phold_exp()
    ref_eng = Engine(exp, EngineParams(ev_cap=64))
    ref_st = ref_eng.run(n_windows=90)
    engs = {c: Engine(exp, EngineParams(ev_cap=c)) for c in (64, 96, 24)}
    st = engs[64].run(n_windows=30)
    st = engs[96].run(migrate(engs[96], st, ev_cap=96), n_windows=30)
    st = engs[24].run(migrate(engs[24], st, ev_cap=24), n_windows=30)
    assert Engine.metrics_dict(st) == Engine.metrics_dict(ref_st)
    np.testing.assert_array_equal(
        np.asarray(ref_eng.model_summary(ref_st)["hops"]),
        np.asarray(engs[24].model_summary(st)["hops"]),
    )


def test_grow_then_shrink_bit_exact_phold_outbox():
    exp = phold_exp()
    ref = Engine.metrics_dict(Engine(exp, EngineParams()).run(n_windows=60))
    engs = {c: Engine(exp, EngineParams(outbox_cap=c)) for c in (64, 96, 16)}
    st = engs[64].run(n_windows=20)
    st = engs[96].run(migrate(engs[96], st, outbox_cap=96), n_windows=20)
    st = engs[16].run(migrate(engs[16], st, outbox_cap=16), n_windows=20)
    assert Engine.metrics_dict(st) == ref


def test_grow_then_shrink_bit_exact_tgen():
    """The TCP net model across an ev_cap shrink + regrow (the model state
    pytree — sockets, timers, NIC queues — rides the migration untouched)."""
    exp = tgen_exp()
    params = EngineParams(ev_cap=256)
    ref = Engine.metrics_dict(Engine(exp, params).run(n_windows=60))
    engs = {c: Engine(exp, dataclasses.replace(params, ev_cap=c))
            for c in (256, 64)}
    st = engs[256].run(n_windows=20)
    st = engs[64].run(migrate(engs[64], st, ev_cap=64), n_windows=10)
    st = engs[256].run(migrate(engs[256], st, ev_cap=256), n_windows=30)
    m = Engine.metrics_dict(st)
    assert m["ev_overflow"] == 0
    assert m == ref


@pytest.mark.parametrize("model", [
    "phold",
    # tier-1 wall budget (PR 4): the tgen variant costs ~40s; the phold
    # one exercises the same sharded migrate/retune path in ~5s.
    pytest.param("tgen", marks=pytest.mark.slow),
])
def test_grow_then_shrink_bit_exact_sharded(model):
    from shadow1_tpu.shard.engine import ShardedEngine

    if model == "phold":
        exp = phold_exp(n_hosts=64, seed=7, end_time=50 * MS)
        caps, spans = (48, 96, 16), (20, 15, 15)
        base = EngineParams(ev_cap=48)
    else:
        exp = tgen_exp(n_hosts=8, end=1 * SEC)  # 1 host/shard on the 8-mesh
        caps, spans = (256, 64, 256), (20, 10, 20)
        # x2x_cap pinned at the worst-case (h_local·outbox_cap): the
        # convergent small mesh would otherwise trip the auto-cap retry and
        # pay an extra recompile per engine.
        base = EngineParams(ev_cap=256, x2x_cap=64)
    n_total = sum(spans)
    ref = Engine.metrics_dict(Engine(exp, base).run(n_windows=n_total))
    engs = {c: ShardedEngine(exp, dataclasses.replace(base, ev_cap=c))
            for c in dict.fromkeys(caps)}
    assert engs[caps[0]].n_dev == 8, "conftest must provide 8 virtual devices"
    st = engs[caps[0]].run(n_windows=spans[0])
    for cap, span in zip(caps[1:], spans[1:]):
        st = engs[cap].run(migrate(engs[cap], st, ev_cap=cap), n_windows=span)
    m = Engine.metrics_dict(st)
    skip = {"rounds", "round_cap_hits", "x2x_max_fill",
            "fires_pkt", "fires_deliver", "fires_timer", "fires_txr",
            "fires_app", "compact_max_fill"}
    for k, v in ref.items():
        if k not in skip:
            assert m[k] == v, (k, m[k], v)


def test_shrink_refuses_to_drop_events():
    exp = phold_exp(init_events=12)
    eng = Engine(exp, EngineParams(ev_cap=64))
    st = eng.run(n_windows=10)
    with pytest.raises(ValueError, match="cannot shrink ev_cap"):
        resize_state(jax.tree.map(np.asarray, st), ev_cap=8)


# ---------------------------------------------------------------------------
# checkpoint across caps
# ---------------------------------------------------------------------------

def test_checkpoint_restores_into_different_cap(tmp_path):
    from shadow1_tpu.ckpt import load_state, save_state

    exp = phold_exp()
    eng_a = Engine(exp, EngineParams(ev_cap=48))
    eng_b = Engine(exp, EngineParams(ev_cap=96))
    ref = Engine.metrics_dict(eng_b.run(n_windows=100))
    st = eng_a.run(n_windows=40)
    path = str(tmp_path / "capA.npz")
    save_state(st, path)
    st_b = load_state(eng_b.init_state(), path)  # cap 48 → 96 on load
    final = eng_b.run(st_b, n_windows=60)
    assert Engine.metrics_dict(final) == ref
    # The strict mismatch contract survives: different host count still fails.
    other = Engine(phold_exp(n_hosts=64, seed=17), EngineParams(ev_cap=48))
    with pytest.raises(ValueError, match="config mismatch"):
        load_state(other.init_state(), path)


# ---------------------------------------------------------------------------
# the controller (--auto-caps)
# ---------------------------------------------------------------------------

def run_auto(exp, params, n_windows, chunk, policy=None):
    from shadow1_tpu.ckpt import run_chunked

    eng = Engine(exp, params)
    ctl = CapController(eng, lambda p: Engine(exp, p), policy=policy)
    st = run_chunked(eng, n_windows=n_windows, chunk=chunk, retune=ctl)
    return st, ctl


def test_autocap_shrinks_overprovisioned_run_bit_exact():
    """4×-over-provisioned phold: the controller shrinks to the measured
    band and final results still bit-match the fixed-cap run."""
    exp = phold_exp()
    fixed = Engine.metrics_dict(Engine(exp, EngineParams(ev_cap=64)).run(n_windows=100))
    st, ctl = run_auto(exp, EngineParams(ev_cap=64), n_windows=100, chunk=20)
    assert ctl.resizes, "an over-provisioned cap must trigger a shrink"
    assert ctl.final_caps["ev_cap"] < 64
    assert Engine.metrics_dict(st) == fixed


@pytest.mark.slow  # tier-1 wall budget (PR 4): heaviest of its family;
# a faster sibling keeps the coverage in the fast tier; ./ci.sh all runs it.
def test_autocap_grows_before_overflow_tgen():
    """A workload whose occupancy ramps ~13× past the starting cap (TCP
    slow-start): the static cap drops events; --auto-caps must grow ahead
    of the ramp and keep ev_overflow at 0, bit-matching a generously-capped
    fixed run."""
    exp = tgen_exp()
    static = Engine.metrics_dict(Engine(exp, EngineParams(ev_cap=48)).run(n_windows=60))
    assert static["ev_overflow"] > 0, "the static cap must actually overflow"
    big = Engine.metrics_dict(Engine(exp, EngineParams(ev_cap=256)).run(n_windows=60))
    assert big["ev_overflow"] == 0
    st, ctl = run_auto(exp, EngineParams(ev_cap=48), n_windows=60, chunk=2,
                       policy=CapPolicy(headroom=2.0))
    m = Engine.metrics_dict(st)
    assert m["ev_overflow"] == 0, (ctl.resizes, m["ev_overflow"])
    assert ctl.final_caps["ev_cap"] > 48
    assert m == big


def test_autocap_sharded_parity():
    """--auto-caps on the 8-device mesh: resizes reshard the migrated state
    and results stay identical to the single-device auto run."""
    from shadow1_tpu.ckpt import run_chunked
    from shadow1_tpu.shard.engine import ShardedEngine

    exp = phold_exp(n_hosts=64, seed=7, end_time=50 * MS)
    st1, ctl1 = run_auto(exp, EngineParams(ev_cap=96), n_windows=50, chunk=10)
    sh = ShardedEngine(exp, EngineParams(ev_cap=96))
    ctl8 = CapController(sh, lambda p: ShardedEngine(exp, p))
    st8 = run_chunked(sh, n_windows=50, chunk=10, retune=ctl8)
    assert ctl1.resizes and ctl8.resizes
    assert ctl1.final_caps == ctl8.final_caps
    m1, m8 = Engine.metrics_dict(st1), Engine.metrics_dict(st8)
    for k in ("events", "pkts_sent", "pkts_delivered", "ev_overflow",
              "ob_overflow", "ev_max_fill", "ob_max_fill", "windows"):
        assert m1[k] == m8[k], k


def test_autocap_through_run_with_heartbeat(tmp_path):
    """The CLI wiring: controller + heartbeat + ring + checkpoint in one
    chunked run; heartbeats carry the live caps in their fill block."""
    import io

    from shadow1_tpu.obs import run_with_heartbeat

    exp = phold_exp()
    eng = Engine(exp, EngineParams(ev_cap=96, metrics_ring=16))
    ctl = CapController(eng, lambda p: Engine(exp, p))
    buf = io.StringIO()
    st, hb = run_with_heartbeat(eng, n_windows=80, every_windows=16,
                                stream=buf, controller=ctl,
                                ckpt_path=str(tmp_path / "auto.npz"),
                                ckpt_every_s=0.0)
    assert ctl.resizes
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    hbs = [r for r in recs if r["type"] == "heartbeat"]
    assert hbs and all("ev_max_fill" in r.get("fill", {}) for r in hbs)
    # Gauges leave the delta block (they are high-water marks, not rates).
    assert all("ev_max_fill" not in r["delta"] for r in hbs)
    # Later heartbeats report the shrunk cap the run actually used.
    assert hbs[-1]["fill"]["ev_cap"] == ctl.final_caps["ev_cap"]
    # The checkpoint (saved at the resized cap) restores into the config cap.
    from shadow1_tpu.ckpt import load_state

    st2 = load_state(eng.init_state(), str(tmp_path / "auto.npz"))
    assert int(st2.metrics.windows) == 80


def test_autocap_overflow_backstop_grows():
    """Mid-window overflow can hide from the window-end fill gauges (burst
    push that drains before the sample) — any fresh overflow must force a
    grow step regardless of the gauge."""
    import jax.numpy as jnp

    exp = phold_exp()
    eng = Engine(exp, EngineParams(ev_cap=64))
    ctl = CapController(eng, lambda p: Engine(exp, p))
    st = eng.run(n_windows=10)
    assert int(st.metrics.ev_max_fill) < 48  # gauge alone would not grow
    lossy = st._replace(metrics=st.metrics._replace(
        ev_overflow=jnp.asarray(5, jnp.int64)))
    eng2, st2 = ctl(eng, lossy)
    assert eng2.params.ev_cap == 96  # one ladder step up
    # Same cumulative count next chunk = no NEW loss: no further grow —
    # and no shrink back below the lossy cap either (the lossless floor):
    # low window-end fill would otherwise re-trigger the overflow forever.
    quiet = st2._replace(metrics=st2.metrics._replace(
        ev_overflow=jnp.asarray(5, jnp.int64)))
    for _ in range(4):  # > shrink_patience
        eng_n, _ = ctl(eng2, quiet)
        assert eng_n.params.ev_cap == 96
    # A resumed run baselines the counters from its initial state: the
    # historical overflow must not force a spurious grow on respawn.
    ctl2 = CapController(eng, lambda p: Engine(exp, p), initial_state=lossy)
    eng4, _ = ctl2(eng, lossy)
    assert eng4.params.ev_cap == 64


def test_autocap_resume_uses_snapshot_caps(tmp_path):
    """The supervised-respawn path: a checkpoint saved at a grown cap whose
    occupancy no longer fits the config's static cap must resume at the
    SNAPSHOT's caps (ckpt.snapshot_caps), not die in the shrink check."""
    from shadow1_tpu.ckpt import load_state, save_state, snapshot_caps

    exp = phold_exp(init_events=12)  # ~12+ events/host: never fits cap 8
    eng_grown = Engine(exp, EngineParams(ev_cap=64))
    st = eng_grown.run(n_windows=10)
    path = str(tmp_path / "grown.npz")
    save_state(st, path)
    eng_cfg = Engine(exp, EngineParams(ev_cap=8))
    assert snapshot_caps(eng_cfg.init_state(), path) == (64, 64)
    with pytest.raises(ValueError, match="snapshot's caps|--auto-caps"):
        load_state(eng_cfg.init_state(), path)  # the loud, actionable path
    # What cli.py --auto-caps does: rebuild at the snapshot caps and resume.
    st2 = load_state(eng_grown.init_state(), path)
    assert int(eng_grown.run(st2, n_windows=10).metrics.windows) == 20


def test_cli_config_auto_caps_inert_on_cpu_engine(tmp_path):
    """engine.auto_caps in YAML follows the metrics_ring precedent: inert
    (with a warning) under --engine cpu so shared configs still run on the
    oracle; the explicit --auto-caps flag errors."""
    import subprocess
    import sys

    cfg = tmp_path / "auto.yaml"
    cfg.write_text(
        "general: {seed: 3, stop_time: 10 ms}\n"
        "engine: {scheduler: cpu, auto_caps: 1}\n"
        "network: {single_vertex: {latency: 1 ms}}\n"
        "hosts:\n"
        "  - {name: h, count: 4}\n"
        "app:\n"
        "  model: phold\n"
        "  params: {mean_delay_ns: 2000000.0}\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, "-m", "shadow1_tpu", str(cfg)],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-500:]
    assert "auto_caps ignored" in r.stderr
    r2 = subprocess.run([sys.executable, "-m", "shadow1_tpu", str(cfg),
                         "--auto-caps"],
                        env=env, capture_output=True, text=True, timeout=600)
    assert r2.returncode != 0 and "--auto-caps" in r2.stderr


# ---------------------------------------------------------------------------
# captune
# ---------------------------------------------------------------------------

def test_captune_reproduces_rung5_audit(capsys):
    """The acceptance reproduction: from the recorded round-5 audit row,
    captune finds rung5's ev_cap ~6× over-provisioned and recommends the
    96 the config now carries."""
    from shadow1_tpu.tools import captune

    recs = captune.load_records([os.path.join(REPO, "AUDIT_r05_occupancy.jsonl")])
    groups = captune.group_records(recs)
    rows = captune.advise(*captune.peaks_from_records(
        groups["configs/rung5_bitcoin5k.yaml"]))
    (row,) = rows
    assert row["knob"] == "ev_cap" and row["verdict"] == "shrink"
    assert row["recommended"] == 96
    assert 5.9 <= row["over_factor"] <= 6.0  # "~6× over-provisioned"
    assert row["plane_pass_saving"] == pytest.approx(0.62, abs=0.01)
    # The hand-validated caps stay untouched.
    for cfg in ("configs/rung2_tgen100.yaml", "configs/dense_tgen50k.yaml"):
        (r,) = captune.advise(*captune.peaks_from_records(groups[cfg]))
        assert r["verdict"] == "ok", cfg
    # CLI end-to-end: the YAML block carries the provenance comment.
    rc = captune.main([os.path.join(REPO, "AUDIT_r05_occupancy.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ev_cap: 96  # captune: measured peak 43" in out


def test_captune_outbox_pacing_is_not_grow_advice():
    """A full outbox with 0 drops is TCP flow control, not overflow risk —
    and outbox_cap is semantic for TCP, so captune must not advise resizing
    it from fill alone (the rung1 CLI drive surfaces exactly this shape)."""
    from shadow1_tpu.tools import captune

    (row,) = captune.advise({"outbox_cap": 64}, {"outbox_cap": 64}, {})
    assert row["verdict"] == "pacing" and row["recommended"] == 64
    assert "send pacing" in captune.advise_lines([row])[0]
    assert "keep" in captune.render_yaml([row])
    # With actual drops the grow advice stands.
    (row,) = captune.advise({"outbox_cap": 64}, {"outbox_cap": 64},
                            {"outbox_cap": 5})
    assert row["verdict"] == "grow"


def test_captune_sees_overflow_in_heartbeat_deltas():
    """A heartbeat-only log (no ring, no final JSON) must still flag an
    overflowed run — a shrink recommendation from a lossy run's 'peak'
    would repeat the rung2 mistake (the peak is a floor)."""
    from shadow1_tpu.tools import captune

    recs = [
        {"type": "heartbeat", "delta": {"events": 10, "ev_overflow": 100},
         "fill": {"ev_max_fill": 20, "ev_cap": 256}},
        {"type": "heartbeat", "delta": {"events": 10, "ev_overflow": 78},
         "fill": {"ev_max_fill": 20, "ev_cap": 256}},
    ]
    peaks, caps, overflow = captune.peaks_from_records(recs)
    assert overflow["ev_cap"] == 178
    (row,) = captune.advise(peaks, caps, overflow)
    assert row["overflowed"]
    assert "OVERFLOWED" in captune.advise_lines([row])[0]
    # Redundant channels (ring rows sum to heartbeat deltas) never
    # double-count: max of the channels, not their sum.
    recs.append({"type": "ring", "window": 0, "ev_overflow": 178,
                 "evbuf_fill": 20})
    assert captune.peaks_from_records(recs)[2]["ev_cap"] == 178


def test_captune_reads_live_run_records(tmp_path):
    """captune on the records a real run emits: ring JSONL + the CLI's
    final metrics/caps JSON."""
    from shadow1_tpu.obs import run_with_heartbeat
    from shadow1_tpu.tools import captune

    import io

    exp = phold_exp()
    params = EngineParams(ev_cap=96, metrics_ring=32)
    eng = Engine(exp, params)
    buf = io.StringIO()
    st, _ = run_with_heartbeat(eng, n_windows=60, every_windows=20, stream=buf)
    final = {"metrics": Engine.metrics_dict(st),
             "caps": {"ev_cap": params.ev_cap,
                      "outbox_cap": params.outbox_cap}}
    log = tmp_path / "run.log"
    log.write_text(buf.getvalue() + json.dumps(final) + "\n")
    recs = captune.load_records([str(log)])
    peaks, caps, overflow = captune.peaks_from_records(recs)
    assert peaks["ev_cap"] == int(st.metrics.ev_max_fill)
    assert caps["ev_cap"] == 96
    rows = captune.advise(peaks, caps, overflow)
    by_knob = {r["knob"]: r for r in rows}
    assert by_knob["ev_cap"]["verdict"] == "shrink"  # phold barely fills 96
    assert by_knob["ev_cap"]["recommended"] == recommend_cap(peaks["ev_cap"])


def test_heartbeat_report_surfaces_gauges_and_captune(tmp_path, capsys):
    from shadow1_tpu.tools import heartbeat_report as hr

    lines = [
        json.dumps({"type": "heartbeat", "sim_time_s": 0.5, "wall_s": 1.0,
                    "windows": 5, "events_per_sec": 10.0, "sim_per_wall": 0.5,
                    "delta": {"events": 10},
                    "fill": {"ev_max_fill": 43, "ev_cap": 256}}),
        json.dumps({"type": "ring", "window": 0, "sim_time_s": 1e-3,
                    "events": 5, "evbuf_fill": 40, "ev_max_fill": 40,
                    "ob_max_fill": 3, "compact_max_fill": 0,
                    "x2x_max_fill": 0, "ev_overflow": 0}),
    ]
    log = tmp_path / "r.log"
    log.write_text("\n".join(lines) + "\n")
    summary = hr.summarize(hr.load_records(str(log)))
    out = capsys.readouterr().out
    assert "== captune recommendation ==" in out
    assert "SHRINK -> 96" in out
    assert summary["captune"][0]["knob"] == "ev_cap"
    assert "ev_max_fill" in summary["ring"]


# ---------------------------------------------------------------------------
# CLI --auto-caps end to end
# ---------------------------------------------------------------------------

def test_cli_auto_caps(tmp_path):
    import subprocess
    import sys

    cfg = tmp_path / "phold.yaml"
    cfg.write_text(
        "general: {seed: 3, stop_time: 60 ms}\n"
        "engine: {scheduler: tpu, ev_cap: 96}\n"
        "network: {single_vertex: {latency: 1 ms}}\n"
        "hosts:\n"
        "  - {name: h, count: 16}\n"
        "app:\n"
        "  model: phold\n"
        "  params: {mean_delay_ns: 2000000.0, init_events: 2}\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--auto-caps",
         "--heartbeat", "10"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, (r.stdout[-400:], r.stderr[-800:])
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["caps"]["ev_cap"] == 96
    assert out["auto_caps"]["resizes"], "96 is far over phold's peak"
    assert out["auto_caps"]["final"]["ev_cap"] < 96
    assert out["metrics"]["ev_overflow"] == 0


# ---------------------------------------------------------------------------
# the measured win (slow tier: wall-clock assertion)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_autocap_recovers_wallclock_on_overprovisioned_phold():
    """The acceptance benchmark: ev_cap at 4× the measured peak; --auto-caps
    must recover ≥20% wall vs the static cap (numbers recorded in
    docs/PERF.md "cap economics")."""
    import time

    exp = phold_exp(n_hosts=2048, seed=11, end_time=200 * MS, init_events=4)
    peak = int(Engine(exp, EngineParams(ev_cap=96))
               .run(n_windows=40).metrics.ev_max_fill)
    cap = 4 * peak

    def timed(auto: bool):
        params = EngineParams(ev_cap=cap)
        eng = Engine(exp, params)
        ctl = CapController(eng, lambda p: Engine(exp, p)) if auto else None
        from shadow1_tpu.ckpt import run_chunked

        jax.block_until_ready(eng.run(eng.init_state(), n_windows=0))
        if auto:  # pre-build the shrunk engine: compile time is not run time
            tgt = Engine(exp, EngineParams(ev_cap=quantize_cap(
                int(peak * 1.5) + 1)))
            jax.block_until_ready(tgt.run(tgt.init_state(), n_windows=0))
            ctl._engines[(tgt.params.ev_cap, tgt.params.outbox_cap)] = tgt
        t0 = time.perf_counter()
        st = run_chunked(eng, n_windows=200, chunk=20, retune=ctl)
        jax.block_until_ready(st)
        return time.perf_counter() - t0, Engine.metrics_dict(st)

    wall_static, m_static = timed(False)
    wall_auto, m_auto = timed(True)
    assert m_auto == m_static  # bit-exact while saving the wall
    assert wall_auto < 0.8 * wall_static, (wall_static, wall_auto)
