"""The "net" workload model: NIC + TCP/UDP transport + model applications.

This composes the tensor equivalents of the reference's host stack
(SURVEY §2.3): NetworkInterface (net/nic.py), the descriptor/TCP subsystem
(tcp/tcp.py), and the application layer (apps/*) that replaces real plugin
binaries with state-machine traffic models (the sanctioned substitution,
SURVEY §2.4). Event flow per arrived packet mirrors the reference call
stack §3.4: K_PKT (NIC receive queue) → K_PKT_DELIVER (TCP/UDP processing)
→ app notification → app reaction (sends, closes) in the same round.

model_cfg: ``{"app": <name>, ...app-specific numpy arrays}``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

from shadow1_tpu.consts import (
    F_DGRAM,
    K_APP,
    K_PKT,
    K_PKT_DELIVER,
    K_TCP_TIMER,
    K_TX_RESUME,
    N_DGRAM,
    NP,
    WIRE_OVERHEAD,
)
from shadow1_tpu.core.events import push_local
from shadow1_tpu.core.outbox import outbox_append
from shadow1_tpu.net.nic import NicState, ctx_aqm, nic_init, rx_stamp, tx_stamp
from shadow1_tpu.tcp import tcp as T


class NetState(NamedTuple):
    nic: NicState
    tcp: dict
    app: Any


def _app_module(name: str):
    if name == "filexfer":
        from shadow1_tpu.apps import filexfer

        return filexfer
    if name == "dgram":
        from shadow1_tpu.apps import dgram

        return dgram
    if name == "tgen":
        from shadow1_tpu.apps import tgen

        return tgen
    if name == "tor":
        from shadow1_tpu.apps import tor

        return tor
    if name == "bitcoin":
        from shadow1_tpu.apps import bitcoin

        return bitcoin
    raise ValueError(f"unknown app {name!r}")


def init(ctx, evbuf):
    pr = ctx.params
    nic = nic_init(ctx.n_hosts)
    tcpd = T.tcp_init(ctx.n_hosts, pr.sockets_per_host, pr.msgq_cap, pr)
    app_mod = _app_module(ctx.model_cfg["app"])
    app, evbuf, over, tcpd = app_mod.init(ctx, evbuf, tcpd)
    return NetState(nic=nic, tcp=tcpd, app=app), evbuf, over


def udp_send(st, ctx, mask, dst_host, dst_sock, length, meta, meta2, now):
    """Datagram send: NIC uplink stamp + outbox packet with F_DGRAM.

    The reference's UDP socket (src/main/host/descriptor/udp.c): no
    handshake, no reliability; loss/latency/bandwidth still apply.
    """
    p = jnp.zeros((ctx.n_hosts, NP), jnp.int32)
    p = p.at[:, 0].set(ctx.hosts)
    p = p.at[:, 1].set(T.pack_meta(0, dst_sock, F_DGRAM))
    p = p.at[:, 4].set(jnp.asarray(length, jnp.int32))
    p = p.at[:, 7].set(jnp.asarray(meta, jnp.int32))
    p = p.at[:, 8].set(jnp.asarray(meta2, jnp.int32))
    wire = jnp.asarray(length, jnp.int64) + WIRE_OVERHEAD
    nic, depart, sent, red = tx_stamp(
        st.model.nic, mask, wire, now, ctx.bw_up,
        ctx.tx_qlen_ns if ctx.has_qlen else None,
        aqm=ctx_aqm(ctx),
    )
    k = jnp.full(ctx.n_hosts, K_PKT, jnp.int32)
    outbox, ok = outbox_append(st.outbox, sent, dst_host, k, depart, p)
    m = st.metrics
    return st._replace(
        model=st.model._replace(nic=nic),
        outbox=outbox,
        metrics=m._replace(
            ob_overflow=m.ob_overflow + (sent & ~ok).sum(dtype=jnp.int64),
            nic_tx_drops=m.nic_tx_drops
            + (mask & ~sent & ~red).sum(dtype=jnp.int64),
            nic_aqm_drops=m.nic_aqm_drops + red.sum(dtype=jnp.int64),
        ),
    )


def make_handlers(ctx):
    app_mod = _app_module(ctx.model_cfg["app"])
    app_on_notify = app_mod.on_notify
    app_on_wakeup = app_mod.on_wakeup

    def on_pkt(st, ev):
        """K_PKT: packet reached the dst NIC — model the receive queue
        (drop-tail when the downlink queue bound is exceeded)."""
        m = ev.mask & (ev.kind == K_PKT)
        wire = jnp.asarray(ev.p[:, 4], jnp.int64) + WIRE_OVERHEAD
        nic, ready, okq = rx_stamp(
            st.model.nic, m, wire, ev.time, ctx.bw_dn,
            ctx.rx_qlen_ns if ctx.has_qlen else None,
        )
        st = st._replace(model=st.model._replace(nic=nic))
        k = jnp.full(ctx.n_hosts, K_PKT_DELIVER, jnp.int32)
        evbuf, over = push_local(st.evbuf, okq, ready, k, ev.p)
        met = st.metrics
        return st._replace(
            evbuf=evbuf,
            metrics=met._replace(
                ev_overflow=met.ev_overflow + over.sum(dtype=jnp.int64),
                nic_rx_drops=met.nic_rx_drops + (m & ~okq).sum(dtype=jnp.int64),
            ),
        )

    def on_deliver(st, ev):
        """K_PKT_DELIVER: the packet cleared the NIC — run TCP/UDP, then app."""
        m = ev.mask & (ev.kind == K_PKT_DELIVER)
        flags = (ev.p[:, 1] >> 16) & 0xFF
        is_dgram = (flags & F_DGRAM) != 0
        st, nf = T.tcp_rx(st, ctx, m & ~is_dgram, ev.p, ev.time)
        dg = m & is_dgram
        nf = T._notify(
            nf, dg, (ev.p[:, 1] >> 8) & 0xFF, N_DGRAM,
            meta=ev.p[:, 7], meta2=ev.p[:, 8], dlen=ev.p[:, 4],
        )
        return app_on_notify(st, ctx, nf, ev.time, nf.flags != 0)

    def on_timer(st, ev):
        return T.on_tcp_timer(st, ctx, ev)

    def on_txr(st, ev):
        return T.on_tx_resume(st, ctx, ev)

    def on_app(st, ev):
        m = ev.mask & (ev.kind == K_APP)
        return app_on_wakeup(st, ctx, ev, m)

    return {
        K_PKT: on_pkt,
        K_PKT_DELIVER: on_deliver,
        K_TCP_TIMER: on_timer,
        K_TX_RESUME: on_txr,
        K_APP: on_app,
    }


def summary(model: NetState, ctx) -> dict:
    d = {
        "nic_tx_bytes": model.nic.tx_bytes,
        "nic_rx_bytes": model.nic.rx_bytes,
    }
    d.update(_app_module(ctx.model_cfg["app"]).summary(model.app))
    return d
